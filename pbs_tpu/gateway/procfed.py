"""Out-of-process federation: members as real OS processes.

The in-process :class:`~pbs_tpu.gateway.federation.FederatedGateway`
is the deterministic witness — N member objects on one thread, one
virtual timeline, byte-reproducible goldens. This module is the
deployment-shaped twin: each member is a REAL spawned process hosting
one :class:`~pbs_tpu.gateway.gateway.Gateway` pump plus its own
write-ahead intent journal, and every parent↔member interaction rides
``dist/rpc`` — idempotency tokens on every mutating op, a whole-call
deadline from the ``federation.proc.rpc_deadline_ns`` knob on every
client, so a slow or dead member sheds with retry-after instead of
hanging the parent pump.

Topology (docs/GATEWAY.md "Process mode"):

- the PARENT owns the durable routing/lease authority: the consistent-
  hash ring, the :class:`~pbs_tpu.gateway.federation.LeaseBroker`
  banks, the tenant contracts, and one
  :class:`~pbs_tpu.gateway.supervisor.MemberSupervisor` per member
  (heartbeats over rpc, miss budget, restart-with-backoff, drain on
  restart exhaustion);
- each CHILD owns exactly what dies with a real box: its fair queue,
  its admission slice (:class:`~pbs_tpu.gateway.federation
  .LeasedBucket` per tenant), its backends, and its OWN journal file —
  the single durable truth for that member. ``gateway.process.kill``
  is a literal ``SIGKILL`` to the member pid; the restarted child
  rebuilds itself from its journal bytes alone (PR 15's
  :func:`~pbs_tpu.gateway.recovery.recover_gateway`, now load-bearing
  cross-process) and reports the recovery books back over rpc.

Determinism contract: children run on parent-driven virtual time (the
``m.tick`` op carries ``now_ns``), so admission books, queue orders,
and backend service draws are a pure function of the op sequence —
a disarmed (no-kill) process run digests identically run-to-run. What
is NOT deterministic cross-process: wall-clock facts (pids, spawn
latency, which parent tick first observes a death) and therefore the
restart timeline. The chaos harness digests only the deterministic
legs and reports the rest.

Graceful degradation at every seam: a member that misses its lease
renewal (real scheduling delay now, not an injected fault) drops to
its conservative bucket by the existing ``LeasedBucket`` semantics;
an rpc timeout sheds the submit with a retry-after hint; a member that
exhausts ``federation.proc.max_restarts`` is drained from the ring and
its journaled queue handed off to survivors.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import socket
import threading
import time

from pbs_tpu import knobs
from pbs_tpu.faults import injector as _faults
from pbs_tpu.gateway.admission import SLO_CLASSES, TenantQuota
from pbs_tpu.gateway.fairqueue import Request
from pbs_tpu.gateway.federation import HashRing, LeaseBroker, LeasedBucket
from pbs_tpu.gateway.supervisor import MemberSupervisor, ProcessHandle
from pbs_tpu.utils.clock import MS, SEC, VirtualClock

#: Spawn handshakes, heartbeat probes, and reaps are wall-clock facts;
#: everything book-keeping consumes the parent clock's now_ns.
REAL_CLOCK_SEAM = (
    "cross-process supervision rides the host scheduler: spawn "
    "latency, kill delivery and rpc round-trips are real time")

HEARTBEAT_NS = knobs.default("federation.proc.heartbeat_ns")
MISS_BUDGET = knobs.default("federation.proc.miss_budget")
RESTART_BACKOFF_NS = knobs.default("federation.proc.restart_backoff_ns")
MAX_RESTARTS = knobs.default("federation.proc.max_restarts")
RPC_DEADLINE_NS = knobs.default("federation.proc.rpc_deadline_ns")

DEFAULT_RENEW_PERIOD_NS = knobs.default(
    "gateway.federation.renew_period_ns")
DEFAULT_LEASE_TTL_NS = knobs.default("gateway.federation.lease_ttl_ns")

#: Transport failures a parent->member call sheds on (never in-band
#: RpcError: the member executed and answered — that is a bug, not an
#: outage).
_TRANSPORT_ERRORS = (ConnectionError, socket.timeout, OSError)


# -- the member process ------------------------------------------------------


def _member_main(spec: dict) -> None:
    """Child entry point (spawn context: a fresh interpreter). Hosts
    one Gateway + its journal + an RpcServer; everything stateful is
    driven by parent ops — the child never reads a wall clock into its
    books."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from pbs_tpu.gateway.backends import SimServeBackend
    from pbs_tpu.gateway.gateway import Gateway
    from pbs_tpu.gateway.journal import GatewayJournal, read_journal
    from pbs_tpu.gateway.recovery import recover_gateway, replay
    from pbs_tpu.obs.spans import SpanRecorder

    name = spec["name"]
    clock = VirtualClock(int(spec["start_ns"]))
    backends = [
        SimServeBackend(
            f"{name}b{j}", n_slots=int(spec["n_slots"]),
            service_ns_per_cost=int(spec["service_ns_per_cost"]),
            seed=int(spec["seed"]) * 1009 + int(spec["salt"]) * 31 + j)
        for j in range(int(spec["n_backends"]))
    ]
    spans = SpanRecorder()
    jp = spec["journal_path"]
    replayed: dict[str, dict] = {}
    recover_info: dict | None = None
    if spec["recover"]:
        # recover_gateway restores queues/counters/tenants but not
        # admission slices (that is recover_federation's job for the
        # shared-journal layout); in the per-member-journal layout the
        # slice books live HERE, so fold them out of the same bytes.
        view = read_journal(jp)
        st = replay(view.records,
                    lease_ttl_ns=int(spec["lease_ttl_ns"]))
        for (_m, tenant), s in sorted(st.slices.items()):
            book = replayed.setdefault(tenant, {
                "level": 0.0, "leased_spent": 0.0,
                "conservative_spent": 0.0, "expires_ns": 0})
            book["level"] += s.level
            book["leased_spent"] += s.leased_spent
            book["conservative_spent"] += s.conservative_spent
            book["expires_ns"] = max(book["expires_ns"], s.expires_ns)
        gw, info = recover_gateway(jp, backends, clock=clock,
                                   spans=spans)
        recover_info = {
            "generation": info.generation,
            "n_rids": len(info.rids), "n_done": len(info.done),
            "recovered": list(info.recovered),
            "requeued_inflight": list(info.requeued_inflight),
            # recover_gateway emits one SPAN_RECOVER stitch per
            # recovered rid into the recorder passed above.
            "span_recovers": len(info.recovered),
            "torn_bytes": info.torn_bytes,
            "state_digest": info.state_digest,
        }
        journal = gw._journal
    else:
        gw = Gateway(backends, clock=clock, name=name, spans=spans)
        journal = GatewayJournal.create(jp)
        gw.attach_journal(journal, autocommit=True)
    host = _MemberHost(spec, clock, gw, journal, replayed, recover_info)
    host.serve()


class _MemberHost:
    """The child's op surface. Every op runs under the RpcServer's
    single dispatch lock, so gateway state sees a serial op stream —
    the same single-threaded-pump discipline as the in-process tier."""

    def __init__(self, spec, clock, gw, journal, replayed,
                 recover_info):
        from pbs_tpu.dist.rpc import RpcServer

        self.spec = spec
        self.clock = clock
        self.gw = gw
        self.journal = journal
        self.replayed = replayed
        self.recover_info = recover_info
        self.slice_params: dict[str, tuple[float, float, float]] = {}
        self.stop = threading.Event()
        self.srv = RpcServer()
        r = self.srv.register
        r("m.hb", self._op_hb)
        r("m.register_tenant", self._op_register_tenant)
        r("m.credit", self._op_credit)
        r("m.lease_state", self._op_lease_state)
        r("m.submit", self._op_submit)
        r("m.tick", self._op_tick)
        r("m.audit", self._op_audit)
        r("m.adopt_tenant", self._op_adopt_tenant)
        r("m.export_tenant", self._op_export_tenant)
        r("m.drain_books", self._op_drain_books)
        r("m.note_deposit", self._op_note_deposit)
        r("m.recover_info", self._op_recover_info)
        r("m.shutdown", self._op_shutdown)

    # -- ops -------------------------------------------------------------

    def _op_hb(self) -> dict:
        """Pump-health heartbeat. Deliberately NOT lockfree: it rides
        the same dispatch lock as every state op, so a wedged op
        stream shows up as missed heartbeats — which is the condition
        the supervisor exists to repair."""
        return {"now_ns": self.clock.now_ns(),
                "queued": self.gw.queue.depth(),
                "inflight": len(self.gw.inflight)}

    def _make_bucket(self, tenant: str, quota: TenantQuota,
                     now_ns: int) -> LeasedBucket:
        cap, cons_rate, cons_burst = self.slice_params[tenant]
        return LeasedBucket(
            tenant, self.gw.name, quota, capacity=cap,
            conservative_rate=cons_rate, conservative_burst=cons_burst,
            renew_period_ns=int(self.spec["renew_period_ns"]),
            now_ns=now_ns)

    def _op_register_tenant(self, tenant: str, quota: dict,
                            capacity: float, cons_rate: float,
                            cons_burst: float) -> dict:
        """Install/refresh one tenant contract with its slice params.
        Idempotent: a re-push after a membership change (or after this
        member recovered) reslices the live bucket; a bucket that is
        still a plain post-recovery TokenBucket is swapped for a
        LeasedBucket carrying the journal-replayed books."""
        now = self.clock.now_ns()
        q = TenantQuota(**quota)
        self.slice_params[tenant] = (float(capacity), float(cons_rate),
                                     float(cons_burst))
        if tenant not in self.gw.admission.quotas:
            self.gw.admission.bucket_factory = self._make_bucket
            self.gw.register_tenant(tenant, q, now_ns=now)
        b = self.gw.admission._buckets.get(tenant)
        if not isinstance(b, LeasedBucket):
            nb = self._make_bucket(tenant, q, now)
            book = self.replayed.get(tenant)
            if book is not None:
                # The journal's slice books: prepaid level survives
                # the crash (granted tokens are never re-minted), the
                # spend odometers keep the no-rate-inflation identity,
                # and the stale expiry leaves the bucket degraded
                # until the parent's next renewal lands — degradation
                # by real elapsed time, not by the restart itself.
                nb.level = max(0.0, book["level"])
                nb.leased_spent = book["leased_spent"]
                nb.conservative_spent = book["conservative_spent"]
                nb.expires_ns = int(book["expires_ns"])
            self.gw.admission._buckets[tenant] = nb
            b = nb
        else:
            b.reslice(float(capacity), float(cons_rate),
                      float(cons_burst))
        return {"held": b.level}

    def _op_credit(self, tenant: str, tokens: float, ttl_ns: int,
                   bank_minted: float, bank_level: float) -> dict:
        """A broker grant lands: journal the intent FIRST (the grant
        record carries the bank's post-grant odometers — recovery's
        mini-checkpoint), then credit the live bucket."""
        now = self.clock.now_ns()
        b = self.gw.admission._buckets[tenant]
        self.journal.grant(now, tenant, self.gw.name, float(tokens),
                           float(bank_minted), float(bank_level))
        b.credit(float(tokens), now, int(ttl_ns))
        return {"level": b.level}

    def _op_lease_state(self) -> dict:
        out = {}
        for tenant in sorted(self.gw.admission._buckets):
            b = self.gw.admission._buckets[tenant]
            if isinstance(b, LeasedBucket):
                out[tenant] = {"level": b.level,
                               "pending_need": b.pending_need,
                               "capacity": b.capacity}
        return out

    def _op_submit(self, tenant: str, cost: int, slo=None) -> dict:
        r = self.gw.submit(tenant, None, cost=int(cost), slo=slo)
        return {"admitted": r.admitted, "rid": r.rid,
                "reason": r.reason,
                "retry_after_ns": r.retry_after_ns}

    def _op_tick(self, now_ns: int) -> dict:
        delta = int(now_ns) - self.clock.now_ns()
        if delta > 0:
            self.clock.advance(delta)
        done = self.gw.tick()  # autocommit: seals this round's frame
        return {"done": [rid for rid, _info in done],
                "queued": self.gw.queue.depth(),
                "inflight": len(self.gw.inflight)}

    def _op_audit(self) -> dict:
        tenants = {}
        for tenant in sorted(self.gw.admission._buckets):
            b = self.gw.admission._buckets[tenant]
            if isinstance(b, LeasedBucket):
                tenants[tenant] = {
                    "leased_spent": b.leased_spent,
                    "conservative_spent": b.conservative_spent,
                    "held": b.level,
                    "degraded_takes": b.degraded_takes,
                }
        return {"tenants": tenants, "admitted": self.gw.admitted,
                "completed": self.gw.completed,
                "queued": self.gw.queue.depth(),
                "inflight": len(self.gw.inflight)}

    def _op_adopt_tenant(self, cls: str, tenant: str, reqs: list,
                         deficit: float, from_member: str) -> dict:
        """Custody transfer IN (survivor side of a failed member's
        drain): the adopting gateway journals the ADOPT_TENANT intent
        itself before its queue mutates. Payloads arrive as None —
        the journal persists scheduling state, not tenant data."""
        objs = [Request(rid=r["rid"], tenant=r["tenant"], slo=r["slo"],
                        cost=int(r["cost"]), payload=None,
                        submit_ns=int(r["submit_ns"]),
                        requeues=int(r["requeues"]))
                for r in reqs]
        self.gw.adopt_tenant(cls, tenant, objs, float(deficit),
                             from_member=from_member)
        return {"adopted": len(objs)}

    def _op_export_tenant(self, cls: str, tenant: str) -> dict:
        """Custody transfer OUT (graceful drain of a live member):
        hand this tenant's FIFO back to the parent, deficit carried."""
        reqs, deficit = self.gw.queue.take_tenant(cls, tenant)
        return {"reqs": [{"rid": r.rid, "tenant": r.tenant,
                          "slo": r.slo, "cost": r.cost,
                          "submit_ns": r.submit_ns,
                          "requeues": r.requeues} for r in reqs],
                "deficit": deficit}

    def _op_drain_books(self) -> dict:
        """Graceful drain, phase 1: zero every prepaid slice and hand
        the levels back for bank deposit; the lease is released."""
        now = self.clock.now_ns()
        out = {}
        for tenant in sorted(self.gw.admission._buckets):
            b = self.gw.admission._buckets[tenant]
            if isinstance(b, LeasedBucket) and b.level > 0:
                out[tenant] = b.level
                b.level = 0.0
                b.expires_ns = now
        return out

    def _op_note_deposit(self, tenant: str, accepted: float,
                         bank_minted: float, bank_level: float) -> dict:
        """Journal the deposit the parent's bank just accepted, with
        its post-deposit odometers (the recovery checkpoint pair of
        m.drain_books)."""
        self.journal.deposit(self.clock.now_ns(), tenant, self.gw.name,
                             float(accepted), float(bank_minted),
                             float(bank_level))
        return {"ok": True}

    def _op_recover_info(self) -> dict:
        return self.recover_info or {}

    def _op_shutdown(self) -> str:
        self.stop.set()
        return "bye"

    # -- lifecycle -------------------------------------------------------

    def serve(self) -> None:
        self.srv.start()
        host, port = self.srv.address
        # Atomic handshake: the parent polls for this file; a torn
        # write must never hand it half an address.
        tmp = self.spec["port_file"] + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{host} {port} {os.getpid()}\n")
        os.replace(tmp, self.spec["port_file"])
        self.stop.wait()
        try:
            self.journal.commit()
        except Exception:  # noqa: BLE001 — best-effort final seal
            pass
        self.srv.stop()


# -- the parent --------------------------------------------------------------


@dataclasses.dataclass
class _MemberLink:
    """Parent-side state for one member process."""

    name: str
    spec: dict
    handle: ProcessHandle
    client: object
    probe: object
    pid: int
    #: rids acked to callers whose journal frame is not yet sealed
    #: (sealed by the member's next m.tick): torn if the member dies.
    pending_acks: list[str] = dataclasses.field(default_factory=list)
    last_depth: int = 0
    recovered_from_journal: bool = False
    recoveries: list[dict] = dataclasses.field(default_factory=list)


class ProcessFederation:
    """N member processes behind one submit surface, supervised.

    The parent is single-threaded: ``submit`` routes over the ring and
    rides rpc with a whole-call deadline; ``tick`` is the supervision +
    renewal + pump round. All knobs default to the registry row
    (``federation.proc.*``)."""

    def __init__(self, workdir: str, member_names: list[str], *,
                 clock=None, seed: int = 0, n_backends: int = 1,
                 n_slots: int = 2, service_ns_per_cost: int = 3 * MS,
                 renew_period_ns: int | None = None,
                 lease_ttl_ns: int | None = None,
                 heartbeat_ns: int | None = None,
                 miss_budget: int | None = None,
                 restart_backoff_ns: int | None = None,
                 max_restarts: int | None = None,
                 rpc_deadline_ns: int | None = None,
                 vnodes: int = 16):
        if not member_names:
            raise ValueError("process federation needs >= 1 member")
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.clock = clock if clock is not None else VirtualClock()
        self.seed = int(seed)
        self.n_backends = int(n_backends)
        self.n_slots = int(n_slots)
        self.service_ns_per_cost = int(service_ns_per_cost)
        self.renew_period_ns = int(renew_period_ns
                                   if renew_period_ns is not None
                                   else DEFAULT_RENEW_PERIOD_NS)
        self.lease_ttl_ns = int(lease_ttl_ns if lease_ttl_ns is not None
                                else DEFAULT_LEASE_TTL_NS)
        self.heartbeat_ns = int(heartbeat_ns if heartbeat_ns is not None
                                else HEARTBEAT_NS)
        self.miss_budget = int(miss_budget if miss_budget is not None
                               else MISS_BUDGET)
        self.restart_backoff_ns = int(
            restart_backoff_ns if restart_backoff_ns is not None
            else RESTART_BACKOFF_NS)
        self.max_restarts = int(max_restarts if max_restarts is not None
                                else MAX_RESTARTS)
        self.rpc_deadline_ns = int(
            rpc_deadline_ns if rpc_deadline_ns is not None
            else RPC_DEADLINE_NS)
        self.ring = HashRing(vnodes)
        self.broker = LeaseBroker()
        self.quotas: dict[str, TenantQuota] = {}
        self.sups: dict[str, MemberSupervisor] = {}
        self.links: dict[str, _MemberLink] = {}
        self.failed: set[str] = set()
        self.admitted = 0
        self.completed = 0
        self.handoffs = 0
        self.fed_sheds: dict[str, int] = {}
        self.torn_acks = 0
        self.destroyed: dict[str, float] = {}
        self._recovered_spent: dict[str, tuple[float, float]] = {}
        self.durable_rids: set[str] = set()
        self.completed_rids: set[str] = set()
        self.events: list[dict] = []
        self._last_renew_ns: int | None = None
        self._audit_cache: dict[str, dict] = {}
        self._member_names = list(member_names)
        for name in member_names:
            self.ring.add(name)

    # -- spawn / handshake -----------------------------------------------

    def _spec(self, name: str, recover: bool) -> dict:
        salt = 97 if not name[2:].isdigit() else int(name[2:])
        return {
            "name": name,
            "journal_path": os.path.join(self.workdir,
                                         f"{name}.journal"),
            "port_file": os.path.join(self.workdir, f"{name}.port"),
            "recover": bool(recover),
            "n_backends": self.n_backends,
            "n_slots": self.n_slots,
            "service_ns_per_cost": self.service_ns_per_cost,
            "seed": self.seed,
            "salt": salt,
            "start_ns": self.clock.now_ns(),
            "renew_period_ns": self.renew_period_ns,
            "lease_ttl_ns": self.lease_ttl_ns,
        }

    @staticmethod
    def _await_port(port_file: str, handle: ProcessHandle,
                    timeout_s: float = 30.0) -> tuple[str, int, int]:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                with open(port_file) as f:
                    line = f.read()
                if line.endswith("\n"):
                    host, port, pid = line.split()
                    return host, int(port), int(pid)
            except FileNotFoundError:
                pass
            if not handle.alive():
                raise RuntimeError(
                    f"member died during spawn (exit "
                    f"{handle.reap(timeout_s=1.0)}); see {port_file}")
            time.sleep(0.01)
        raise TimeoutError(f"member never wrote {port_file}")

    def _spawn(self, name: str, recover: bool) -> _MemberLink:
        from pbs_tpu.dist.rpc import RpcClient

        spec = self._spec(name, recover)
        try:
            os.unlink(spec["port_file"])
        except FileNotFoundError:
            pass
        handle = ProcessHandle(target=_member_main, args=(spec,))
        handle.start()
        host, port, pid = self._await_port(spec["port_file"], handle)
        deadline_s = self.rpc_deadline_ns / SEC
        client = RpcClient((host, port), fault_key=name,
                           deadline_s=deadline_s, max_retries=3)
        probe = RpcClient((host, port), fault_key=f"{name}/probe",
                          max_retries=0, timeout_s=deadline_s,
                          deadline_s=deadline_s)
        link = _MemberLink(name=name, spec=spec, handle=handle,
                           client=client, probe=probe, pid=pid)
        self.links[name] = link
        return link

    def start(self) -> None:
        now = self.clock.now_ns()
        for name in self._member_names:
            self.sups[name] = MemberSupervisor(
                name, heartbeat_ns=self.heartbeat_ns,
                miss_budget=self.miss_budget,
                restart_backoff_ns=self.restart_backoff_ns,
                max_restarts=self.max_restarts, now_ns=now)
            link = self._spawn(name, recover=False)
            self.sups[name].spawned(link.pid, now)
            self.events.append({"now_ns": now, "event": "spawn",
                                "gateway": name, "pid": link.pid})

    # -- membership views ------------------------------------------------

    def _active(self) -> list[str]:
        """Members that hold admission slices: everything not failed
        (a down-but-restarting member keeps its slice — its journal
        still owns its books)."""
        return [n for n in sorted(self.links) if n not in self.failed]

    def _reachable(self) -> list[str]:
        return [n for n in sorted(self.links)
                if self.sups[n].state in ("live", "suspect")]

    # -- tenants + leases ------------------------------------------------

    def register_tenant(self, tenant: str, quota: TenantQuota) -> None:
        now = self.clock.now_ns()
        self.quotas[tenant] = quota
        self.broker.register(tenant, quota, now)
        for name in self._reachable():
            self._push_tenant(name, tenant)
            self._renew_member(name, only_tenant=tenant)

    def _slice_args(self, quota: TenantQuota) -> dict:
        n = max(1, len(self._active()))
        frac = 1.0 / (2.0 * n)
        return {"capacity": quota.burst / n,
                "cons_rate": quota.rate * frac,
                "cons_burst": max(1.0, quota.burst * frac)}

    def _push_tenant(self, name: str, tenant: str) -> bool:
        quota = self.quotas[tenant]
        try:
            self.links[name].client.call(
                "m.register_tenant", tenant=tenant,
                quota=dataclasses.asdict(quota),
                **self._slice_args(quota))
            return True
        except _TRANSPORT_ERRORS:
            return False  # lease lapse covers it; supervisor repairs

    def _renew_member(self, name: str,
                      only_tenant: str | None = None) -> None:
        """One member's renewal round: read its slice levels, grant
        the top-up from the bank, push the credit. A push that fails
        in transport deposits the grant straight back — the bank never
        leaks tokens to a dead wire."""
        now = self.clock.now_ns()
        link = self.links[name]
        try:
            state = link.client.call("m.lease_state")
        except _TRANSPORT_ERRORS:
            return  # unreachable: its leases lapse, degraded mode
        for tenant in sorted(state):
            if only_tenant is not None and tenant != only_tenant:
                continue
            s = state[tenant]
            want = max(s["capacity"], s["pending_need"]) - s["level"]
            lease = self.broker.grant(tenant, name, max(0.0, want),
                                      now, self.lease_ttl_ns)
            if lease is None:
                continue
            bank = self.broker.banks[tenant]
            try:
                link.client.call(
                    "m.credit", tenant=tenant, tokens=lease.tokens,
                    ttl_ns=self.lease_ttl_ns,
                    bank_minted=bank.minted, bank_level=bank.level)
            except _TRANSPORT_ERRORS:
                self.broker.deposit(tenant, name, lease.tokens, now)

    # -- intake ----------------------------------------------------------

    def _shed(self, reason: str, retry_after_ns: int) -> dict:
        self.fed_sheds[reason] = self.fed_sheds.get(reason, 0) + 1
        return {"admitted": False, "rid": None, "reason": reason,
                "retry_after_ns": int(retry_after_ns)}

    def route(self, tenant: str) -> str | None:
        live = self._reachable()
        if not live:
            return None
        home = self.ring.lookup(tenant)
        if home in live:
            return home
        return min(live,
                   key=lambda n: (self.links[n].last_depth, n))

    def submit(self, tenant: str, cost: int = 1,
               slo: str | None = None) -> dict:
        target = self.route(tenant)
        if target is None:
            return self._shed("no-gateway", self.rpc_deadline_ns)
        link = self.links[target]
        try:
            r = link.client.call("m.submit", tenant=tenant,
                                 cost=int(cost), slo=slo)
        except _TRANSPORT_ERRORS:
            # Shed with retry-after, never hang the caller: the
            # deadline already bounded the whole retry loop.
            return self._shed("rpc-timeout", self.rpc_deadline_ns)
        if r["admitted"]:
            self.admitted += 1
            link.pending_acks.append(r["rid"])
        return r

    # -- supervision + pump ----------------------------------------------

    def kill9(self, name: str) -> None:
        """Literal SIGKILL to the member pid (the realized
        ``gateway.process.kill`` fault point). Detection, restart and
        recovery ride the normal supervision path on later ticks."""
        link = self.links[name]
        self.events.append({"now_ns": self.clock.now_ns(),
                            "event": "sigkill", "gateway": name,
                            "pid": link.pid})
        link.handle.kill9()

    def _on_death(self, name: str, now: int, why: str) -> None:
        link = self.links[name]
        link.handle.reap(timeout_s=2.0)
        if link.pending_acks:
            # The unacked suffix: admitted acks whose journal frame
            # never sealed. Their callers hold a non-durable ack — the
            # cross-process at-least-once contract (RecoveryInfo).
            self.torn_acks += len(link.pending_acks)
            link.pending_acks.clear()
        self.events.append({"now_ns": now, "event": "death",
                            "gateway": name, "why": why})
        verdict = self.sups[name].died(now)
        if verdict == "drain":
            self._drain_failed(name, now)

    def _respawn(self, name: str, now: int) -> None:
        try:
            link = self._spawn(name, recover=True)
        except (RuntimeError, TimeoutError):
            verdict = self.sups[name].died(now)
            if verdict == "drain":
                self._drain_failed(name, now)
            return
        self.sups[name].spawned(link.pid, now)
        link.recovered_from_journal = True
        try:
            link.recoveries.append(
                link.client.call("m.recover_info"))
        except _TRANSPORT_ERRORS:
            pass
        self._audit_cache.pop(name, None)
        self.events.append({"now_ns": now, "event": "recover",
                            "gateway": name, "pid": link.pid})
        # Re-push every tenant: the register op swaps post-recovery
        # plain buckets for LeasedBuckets carrying the journal books,
        # then the renewal re-leases them.
        for tenant in sorted(self.quotas):
            self._push_tenant(name, tenant)
        self._renew_member(name)

    def _drain_failed(self, name: str, now: int) -> None:
        """Restart budget exhausted: remove the member from the ring
        and hand its JOURNALED queue to survivors (its journal is the
        only truth left — the process is gone). Held tokens die with
        the box (destroyed, never re-minted); its spend odometers fold
        into the federation books so every lease_audit identity
        survives."""
        from pbs_tpu.gateway.journal import read_journal
        from pbs_tpu.gateway.recovery import (
            apply_recover_transform,
            replay,
        )

        self.failed.add(name)
        self.ring.remove(name)
        self.broker.revoke(name)
        self._audit_cache.pop(name, None)
        self.events.append({"now_ns": now, "event": "drain-failed",
                            "gateway": name})
        jp = self.links[name].spec["journal_path"]
        try:
            st = replay(read_journal(jp).records,
                        lease_ttl_ns=self.lease_ttl_ns)
        except Exception:  # noqa: BLE001 — journal gone: nothing to hand off
            return
        apply_recover_transform(st)
        for (_m, tenant), s in sorted(st.slices.items()):
            if s.level > 0:
                self.destroyed[tenant] = (
                    self.destroyed.get(tenant, 0.0) + s.level)
            prev = self._recovered_spent.get(tenant, (0.0, 0.0))
            self._recovered_spent[tenant] = (
                prev[0] + s.leased_spent,
                prev[1] + s.conservative_spent)
        targets = self._reachable()
        if not targets:
            return  # queued work stays journaled; nobody can adopt
        for (member, cls, tenant), rids in sorted(st.queues.items()):
            if not rids:
                continue
            reqs = [{"rid": rid, "tenant": st.reqs[rid].tenant,
                     "slo": st.reqs[rid].cls,
                     "cost": st.reqs[rid].cost,
                     "submit_ns": st.reqs[rid].submit_ns,
                     "requeues": st.reqs[rid].requeues}
                    for rid in rids]
            target = min(targets,
                         key=lambda n: (self.links[n].last_depth, n))
            try:
                self.links[target].client.call(
                    "m.adopt_tenant", cls=cls, tenant=tenant,
                    reqs=reqs,
                    deficit=st.deficits.get((member, cls, tenant),
                                            0.0),
                    from_member=name)
                self.handoffs += len(reqs)
            except _TRANSPORT_ERRORS:
                continue  # adopter unreachable; rids stay journaled

    def drain(self, name: str) -> None:
        """Graceful removal of a LIVE member: collect + deposit its
        prepaid tokens, hand its queues off, retire it from the ring."""
        now = self.clock.now_ns()
        link = self.links[name]
        try:
            books = link.client.call("m.drain_books")
            for tenant in sorted(books):
                accepted = self.broker.deposit(tenant, name,
                                               books[tenant], now)
                bank = self.broker.banks[tenant]
                link.client.call("m.note_deposit", tenant=tenant,
                                 accepted=accepted,
                                 bank_minted=bank.minted,
                                 bank_level=bank.level)
            for cls in SLO_CLASSES:
                for tenant in sorted(self.quotas):
                    out = link.client.call("m.export_tenant", cls=cls,
                                           tenant=tenant)
                    if not out["reqs"]:
                        continue
                    targets = [n for n in self._reachable()
                               if n != name]
                    if not targets:
                        break
                    target = min(
                        targets,
                        key=lambda n: (self.links[n].last_depth, n))
                    self.links[target].client.call(
                        "m.adopt_tenant", cls=cls, tenant=tenant,
                        reqs=out["reqs"], deficit=out["deficit"],
                        from_member=name)
                    self.handoffs += len(out["reqs"])
        except _TRANSPORT_ERRORS:
            pass  # fall through: supervision will declare it dead
        self.ring.remove(name)
        self.broker.revoke(name)
        self.events.append({"now_ns": now, "event": "drain",
                            "gateway": name})

    def tick(self) -> list[str]:
        """One parent round: detect deaths, heartbeat, restart due
        members, renew leases, pump every reachable member. Returns
        this round's completed rids."""
        now = self.clock.now_ns()
        # 1. exits the kernel already knows about
        for name in sorted(self.links):
            sup = self.sups[name]
            if (sup.state in ("live", "suspect")
                    and not self.links[name].handle.alive()):
                self._on_death(name, now, "exit")
        # 2. heartbeats (rpc, no retries: a missed ping must stay
        #    a missed ping)
        for name in self._reachable():
            sup = self.sups[name]
            if not sup.beat_due(now):
                continue
            try:
                self.links[name].probe.call("m.hb")
                sup.beat_ok(now)
            except _TRANSPORT_ERRORS:
                if sup.beat_missed(now) == "dead":
                    # Half-dead is worse than dead: a wedged child
                    # still holds its journal fd. Kill for real, then
                    # run the death path.
                    self.links[name].handle.kill9()
                    self._on_death(name, now, "heartbeat")
        # 3. restarts that cleared their backoff
        for name in sorted(self.links):
            if self.sups[name].restart_due(now):
                self._respawn(name, now)
        # 4. renewals
        if (self._last_renew_ns is None
                or now - self._last_renew_ns >= self.renew_period_ns):
            self._last_renew_ns = now
            for name in self._reachable():
                self._renew_member(name)
        # 5. pump
        done: list[str] = []
        for name in self._reachable():
            link = self.links[name]
            try:
                r = link.client.call("m.tick", now_ns=now)
            except _TRANSPORT_ERRORS:
                continue  # heartbeat machinery owns the verdict
            link.last_depth = r["queued"] + r["inflight"]
            # The tick op sealed this member's journal frame: every
            # ack issued before it is now durable.
            if link.pending_acks:
                self.durable_rids.update(link.pending_acks)
                link.pending_acks.clear()
            fresh = [rid for rid in r["done"]
                     if rid not in self.completed_rids]
            self.completed_rids.update(fresh)
            self.completed += len(fresh)
            done.extend(fresh)
        return done

    # -- observability ---------------------------------------------------

    def queued(self) -> int:
        return sum(link.last_depth for link in self.links.values())

    def busy(self) -> bool:
        return self.queued() > 0

    def lease_audit(self) -> dict[str, dict[str, float]]:
        """The no-rate-inflation witness across processes: parent bank
        odometers joined with each member's rpc-reported spend books
        (last-known snapshot for members currently down — their truth
        is in their journal and comes back with them)."""
        audits: dict[str, dict] = {}
        for name in self._reachable():
            try:
                audits[name] = self.links[name].client.call("m.audit")
                self._audit_cache[name] = audits[name]
            except _TRANSPORT_ERRORS:
                pass
        for name in sorted(self.links):
            if name in self.failed or name in audits:
                continue
            cached = self._audit_cache.get(name)
            if cached is not None:
                audits[name] = cached
        out: dict[str, dict[str, float]] = {}
        for tenant, bank in self.broker.audit().items():
            leased = conservative = held = 0.0
            extra = self._recovered_spent.get(tenant)
            if extra is not None:
                leased, conservative = extra
            for name in sorted(audits):
                t = audits[name]["tenants"].get(tenant)
                if t is None:
                    continue
                leased += t["leased_spent"]
                conservative += t["conservative_spent"]
                held += t["held"]
            out[tenant] = {
                **bank,
                "leased_spent": leased,
                "conservative_spent": conservative,
                "held": held,
                "destroyed": self.destroyed.get(tenant, 0.0),
            }
        return out

    def stats(self) -> dict:
        members = {}
        for name in sorted(self.links):
            link = self.links[name]
            sup = self.sups[name]
            members[name] = {
                "state": sup.state,
                "pid": link.pid,
                "restarts": sup.restarts,
                "recovered_from_journal": link.recovered_from_journal,
                "depth": link.last_depth,
            }
        return {
            "admitted": self.admitted,
            "completed": self.completed,
            "handoffs": self.handoffs,
            "torn_acks": self.torn_acks,
            "shed": dict(sorted(self.fed_sheds.items())),
            "ring": self.ring.nodes(),
            "members": members,
        }

    def stop(self) -> None:
        for name in sorted(self.links):
            link = self.links[name]
            try:
                link.client.call("m.shutdown", _deadline=2.0)
            except Exception:  # noqa: BLE001 — dead members can't bow out
                pass
            link.handle.reap(timeout_s=5.0)
            for c in (link.client, link.probe):
                try:
                    c.close()
                except Exception:  # noqa: BLE001
                    pass


# -- the process-mode chaos harness ------------------------------------------


def stock_process_kill_plan(ticks: int) -> list[dict]:
    """The canonical process-mode kill schedule: one SIGKILL to the
    first member a third of the way in — early enough that recovery
    carries real load, late enough that books exist to recover."""
    return [{"tick": max(1, ticks // 3)}]


def run_process_chaos(workload: str = "mixed", seed: int = 0,
                      n_gateways: int = 2, n_tenants: int = 4,
                      ticks: int = 240, tick_ns: int = 1 * MS,
                      kill_plan: list[dict] | None = None,
                      workdir: str | None = None,
                      backends_per_gateway: int = 1,
                      heartbeat_ns: int | None = None,
                      miss_budget: int | None = None,
                      restart_backoff_ns: int | None = None,
                      max_restarts: int | None = None,
                      rpc_deadline_ns: int | None = None,
                      drain_budget: int | None = None) -> dict:
    """One seeded process-mode federation scenario; returns the report
    dict (``ok`` = every invariant held). Members are real processes;
    ``kill_plan`` entries ``{"tick": T[, "member": name]}`` become
    literal SIGKILLs realized through the ``gateway.process.kill``
    fault point. The killed member recovers from its journal bytes
    alone while survivors keep serving (its tenants route to them
    through the ring fallback for the whole down window).

    Deterministic legs (digest-covered): the arrival schedule is a
    pure function of ``(workload, seed)``; a DISARMED run (no kills)
    additionally digests the full end-state books — same seed, same
    digest. Armed runs report the kill/restart timeline instead of
    digesting it: which parent tick observes a SIGKILL is a host-
    scheduler fact."""
    import tempfile

    from pbs_tpu.faults import FaultPlan, FaultSpec
    from pbs_tpu.gateway.chaos import (
        catalog_arrivals,
        draw_arrival,
        quota_for,
    )
    from pbs_tpu.sim.workload import build_workload

    tenants = build_workload(workload, seed=seed, n_tenants=n_tenants)
    arrivals = catalog_arrivals(tenants, seed, tag=13)
    member_names = [f"gw{i}" for i in range(n_gateways)]
    armed = kill_plan is not None and len(kill_plan) > 0
    specs = []
    kill_ticks: dict[str, int] = {}
    for e in (kill_plan or []):
        victim = e.get("member", member_names[0])
        kill_ticks[victim] = int(e["tick"])
        specs.append(FaultSpec("gateway.process.kill", "kill",
                               p=1.0, key=victim,
                               after=int(e["tick"]), times=1))
    owns_plan = False
    if specs:
        _faults.install(FaultPlan(seed=seed, specs=tuple(specs)))
        owns_plan = True
    tmp = None
    if workdir is None:
        tmp = tempfile.TemporaryDirectory(prefix="pbst-procfed-")
        workdir = tmp.name
    problems: list[str] = []
    kills: list[dict] = []
    clock = VirtualClock()
    fed = ProcessFederation(
        workdir, member_names, clock=clock, seed=seed,
        n_backends=backends_per_gateway,
        service_ns_per_cost=3 * tick_ns,
        renew_period_ns=4 * tick_ns, lease_ttl_ns=6 * tick_ns,
        heartbeat_ns=(heartbeat_ns if heartbeat_ns is not None
                      else 8 * tick_ns),
        miss_budget=miss_budget,
        restart_backoff_ns=(restart_backoff_ns
                            if restart_backoff_ns is not None
                            else 4 * tick_ns),
        max_restarts=max_restarts,
        rpc_deadline_ns=rpc_deadline_ns)
    try:
        fed.start()
        for t in tenants:
            fed.register_tenant(
                t.name, quota_for(t.name, t.slo, t.params.weight))
        for tick in range(ticks):
            clock.advance(tick_ns)
            for t in tenants:
                fire, cost = draw_arrival(t, arrivals[t.name])
                if fire:
                    fed.submit(t.name, cost=cost, slo=t.slo)
            for name in sorted(fed.links):
                if name in fed.failed:
                    continue
                f = _faults.consult("gateway.process.kill", name)
                if f is not None:
                    kills.append({"tick": tick, "member": name,
                                  "pid": fed.links[name].pid})
                    fed.kill9(name)
            fed.tick()
        # Drain: pump until every member reports empty (recovered
        # members finish their journaled backlog here).
        budget = drain_budget if drain_budget is not None else 4 * ticks
        for _ in range(budget):
            clock.advance(tick_ns)
            fed.tick()
            if not fed.busy() and not any(
                    link.pending_acks for link in fed.links.values()):
                break
        audit = fed.lease_audit()
        elapsed_s = clock.now_ns() / SEC
        for tenant, a in sorted(audit.items()):
            quota = fed.quotas[tenant]
            bound = quota.burst + quota.rate * elapsed_s + 1e-6
            if a["minted"] > bound:
                problems.append(
                    f"mint bound: {tenant} minted {a['minted']:.3f} "
                    f"> burst + rate*t = {bound:.3f}")
            if a["granted"] > a["minted"] + 1e-6:
                problems.append(
                    f"lease audit: {tenant} granted {a['granted']:.3f}"
                    f" > minted {a['minted']:.3f}")
            backed = (a["leased_spent"] + a["held"] + a["deposited"]
                      + a["destroyed"])
            if backed > a["granted"] + 1e-6:
                problems.append(
                    f"lease audit: {tenant} spent+held+deposited+"
                    f"destroyed {backed:.3f} > granted "
                    f"{a['granted']:.3f}")
        # No job lost: every durably-acked rid completed (the drain
        # loop above ran the tier to empty).
        lost = fed.durable_rids - fed.completed_rids
        if lost:
            problems.append(
                f"no-job-lost: {len(lost)} durable rid(s) never "
                f"completed, e.g. {sorted(lost)[:3]}")
        if fed.busy():
            problems.append(
                f"drain: {fed.queued()} request(s) still queued "
                f"after the drain budget")
        for name, at in sorted(kill_ticks.items()):
            link = fed.links[name]
            sup = fed.sups[name]
            if name in fed.failed:
                continue  # budget exhaustion IS a legal outcome
            if not link.recovered_from_journal:
                problems.append(
                    f"recovery: {name} was SIGKILLed at tick {at} "
                    f"but never recovered from its journal")
            elif not link.recoveries:
                problems.append(
                    f"recovery: {name} restarted without reporting "
                    f"recovery books")
            else:
                info = link.recoveries[-1]
                if info.get("span_recovers", 0) != len(
                        info.get("recovered", [])):
                    problems.append(
                        f"spans: {name} stitched "
                        f"{info.get('span_recovers')} SPAN_RECOVER "
                        f"chains for {len(info.get('recovered', []))}"
                        f" recovered rids")
            if sup.restarts < 1:
                problems.append(
                    f"supervision: {name} shows no restart after "
                    f"SIGKILL")
        stats = fed.stats()
        report = {
            "harness": "procfed", "workload": workload, "seed": seed,
            "gateways": n_gateways, "tenants": n_tenants,
            "ticks": ticks, "tick_ns": tick_ns,
            "stats": stats,
            "audit": {t: {k: round(v, 6) for k, v in sorted(a.items())}
                      for t, a in sorted(audit.items())},
            "process": {
                "members": stats["members"],
                "kills": kills,
                "torn_acks": fed.torn_acks,
                "recoveries": [
                    {"member": name,
                     "generation": info.get("generation"),
                     "recovered": len(info.get("recovered", [])),
                     "requeued_inflight": len(
                         info.get("requeued_inflight", [])),
                     "torn_bytes": info.get("torn_bytes")}
                    for name in sorted(fed.links)
                    for info in fed.links[name].recoveries],
            },
            "problems": problems,
            "ok": not problems,
        }
        sched = hashlib.sha256(json.dumps(
            {"workload": workload, "seed": seed, "ticks": ticks,
             "tenants": [t.name for t in tenants]},
            sort_keys=True).encode()).hexdigest()
        report["arrivals_digest"] = sched
        if not armed:
            # The deterministic leg: disarmed lockstep runs digest
            # their full end-state books.
            doc = {"arrivals": sched, "audit": report["audit"],
                   "admitted": fed.admitted,
                   "completed": fed.completed,
                   "shed": stats["shed"]}
            report["digest"] = hashlib.sha256(json.dumps(
                doc, sort_keys=True,
                separators=(",", ":")).encode()).hexdigest()
        return report
    finally:
        fed.stop()
        if owns_plan:
            _faults.uninstall()
        if tmp is not None:
            tmp.cleanup()
