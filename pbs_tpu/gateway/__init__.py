"""Multi-tenant serving front door (docs/GATEWAY.md).

SLO-aware admission (token buckets + explicit shed with retry-after),
weighted deficit-round-robin fair queueing across tenants with
interactive/batch SLO classes, least-loaded routing with drain/requeue
on backend loss, and queue-delay feedback into the scheduler — the
paper's performance-feedback loop applied at the request-queue layer.

Jax-free by construction: backends arrive already built (a
``ContinuousBatcher`` via :class:`BatcherBackend`, or the simulated
:class:`SimServeBackend`); the gateway itself imports no accelerator
stack, so admission/fairness/routing test and run anywhere.
"""

from pbs_tpu.gateway.admission import (
    BATCH,
    INTERACTIVE,
    SLO_CLASSES,
    AdmissionController,
    Shed,
    TenantQuota,
    TokenBucket,
)
from pbs_tpu.gateway.backends import Backend, BatcherBackend, SimServeBackend
from pbs_tpu.gateway.fairqueue import DeficitRoundRobin, Request
from pbs_tpu.gateway.federation import (
    FederatedGateway,
    HashRing,
    Lease,
    LeaseBroker,
    LeasedBucket,
)
from pbs_tpu.gateway.feedback import sched_feedback_sink
from pbs_tpu.gateway.gateway import (
    GW_LEDGER_SLOTS,
    Gateway,
    SubmitResult,
)


def __getattr__(name: str):
    # The chaos harnesses pull in the sim workload catalog; keep that
    # import lazy so `pbs_tpu.gateway` stays cheap for serving callers
    # (the same pattern as pbs_tpu.faults.run_chaos).
    if name in ("run_gateway_chaos", "run_federation_chaos", "quota_for",
                "stock_crash_plan"):
        from pbs_tpu.gateway import chaos

        return getattr(chaos, name)
    # Durability surface (docs/DURABILITY.md), lazy for the same
    # reason: serving callers without a journal pay nothing.
    if name in ("GatewayJournal", "JournalCorrupt", "ProcessKill",
                "read_journal"):
        from pbs_tpu.gateway import journal

        return getattr(journal, name)
    if name in ("recover_gateway", "recover_federation"):
        from pbs_tpu.gateway import recovery

        return getattr(recovery, name)
    # Process mode (docs/GATEWAY.md "Process mode"), lazy because it
    # drags in multiprocessing + the rpc stack.
    if name in ("ProcessFederation", "run_process_chaos",
                "stock_process_kill_plan"):
        from pbs_tpu.gateway import procfed

        return getattr(procfed, name)
    if name in ("MemberSupervisor", "ProcessHandle"):
        from pbs_tpu.gateway import supervisor

        return getattr(supervisor, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "AdmissionController",
    "BATCH",
    "Backend",
    "BatcherBackend",
    "DeficitRoundRobin",
    "FederatedGateway",
    "GW_LEDGER_SLOTS",
    "Gateway",
    "GatewayJournal",
    "HashRing",
    "INTERACTIVE",
    "JournalCorrupt",
    "Lease",
    "LeaseBroker",
    "LeasedBucket",
    "MemberSupervisor",
    "ProcessFederation",
    "ProcessHandle",
    "ProcessKill",
    "Request",
    "SLO_CLASSES",
    "Shed",
    "SimServeBackend",
    "SubmitResult",
    "TenantQuota",
    "TokenBucket",
    "quota_for",
    "read_journal",
    "recover_federation",
    "recover_gateway",
    "run_federation_chaos",
    "run_gateway_chaos",
    "run_process_chaos",
    "sched_feedback_sink",
    "stock_crash_plan",
    "stock_process_kill_plan",
]
