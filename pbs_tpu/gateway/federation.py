"""Federated gateway tier: N front doors, one global admission contract.

ROADMAP item 1: a single :class:`~pbs_tpu.gateway.gateway.Gateway` pump
is the serialization point — and the single point of failure — for
every tenant. This module shards the front door itself, on the XOS
model (PAPERS.md, arXiv 1901.00825): per-tenant policy (admission
state, SLO, queue credit) travels with the *tenant*, never dies with
the *box*.

- **Placement** — consistent-hash tenant→gateway placement
  (:class:`HashRing`, sha256 virtual nodes). A membership change
  (add/drain/death) remaps only the arcs the changed node owned —
  ~K/N of tenants, never a full reshuffle. The property tests pin the
  exact form: removal moves only the removed node's tenants; an add
  steals tenants only for the new node.

- **Replicated admission** — per-tenant token-bucket levels are leased
  through one authority (:class:`LeaseBroker`; routed through the dist
  :class:`~pbs_tpu.dist.controller.Controller` when one is attached).
  Tokens are *minted* only at the bank (global rate × time, capped by
  the global burst) and reach a gateway only through a lease grant, so
  a tenant spraying requests across N gateways cannot get N× its
  global rate: every admitted cost unit is traceable to a mint, and
  ``lease_audit()`` proves it. When a lease lapses (authority
  unreachable, injected ``lease.expire``), admission *degrades* to a
  conservative local bucket (:class:`LeasedBucket`) instead of
  stalling — small requests keep flowing at a fraction of the fair
  share, and the scrip this mints is accounted separately
  (``conservative_spent``, the "bounded lease slack" the chaos
  harness asserts small).

- **Failover** — the PR 4 invariant hardens from *backend* death to
  *gateway* death: "admitted ⇒ completed-or-requeued, never lost."
  The federation holds the authoritative record of each member's
  queue and inflight table; a killed member's requests hand off to
  the survivors per the new ring — FIFO order preserved, DRR deficits
  carried (``DeficitRoundRobin.take_tenant``/``restore_tenant``) — and
  a *draining* member additionally deposits its unspent lease tokens
  back to the bank. A dead member's unspent tokens die with it
  (``destroyed``: accounted, never re-minted — conservative).

Single-threaded like the member gateways: the owner pumps ``tick()``.
The fault seams (``gateway.death``, ``gateway.partition``,
``lease.expire``) are consulted in sorted member order, so a seeded
:class:`~pbs_tpu.faults.plan.FaultPlan` replays exactly
(docs/FAULTS.md; ``pbst chaos --plan federation``).
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib

from pbs_tpu import knobs
from pbs_tpu.faults import injector as _faults
from pbs_tpu.gateway.admission import (
    SHED_REASON_CODES,
    SLO_CLASSES,
    TenantQuota,
    TokenBucket,
)
from pbs_tpu.gateway.gateway import Gateway, SubmitResult
from pbs_tpu.utils.clock import SEC

#: Default lease cadence: renew every period, die after ttl. The ttl is
#: deliberately < 2 renew periods, so ONE refused renewal opens a short
#: degraded window — lease loss is a condition the tier lives with, not
#: an edge case. Declared in the knob registry (gateway.federation.*).
DEFAULT_RENEW_PERIOD_NS = knobs.default("gateway.federation.renew_period_ns")
DEFAULT_LEASE_TTL_NS = knobs.default("gateway.federation.lease_ttl_ns")
#: Retry-after when no front door can serve at all.
NO_GATEWAY_RETRY_NS = knobs.default("gateway.federation.no_gateway_retry_ns")
#: Default gateway.partition fault duration before the heal fires.
PARTITION_HEAL_NS = knobs.default("gateway.federation.partition_heal_ns")
#: Sealed lease-book checkpoint cadence of an armed journal
#: (docs/DURABILITY.md; knob registry journal.checkpoint_period_ns).
JOURNAL_CKPT_PERIOD_NS = knobs.default("journal.checkpoint_period_ns")
#: Pseudo-member sid for federation-level journal records (no-gateway
#: sheds happen before any member is chosen).
FED_MEMBER = "@fed"


def _hash64(key: str) -> int:
    """Stable 64-bit point on the ring. sha256, never ``hash()`` — str
    hashing is salted per process and would silently reshuffle every
    placement on restart (the injector's rule)."""
    return int.from_bytes(hashlib.sha256(key.encode()).digest()[:8], "big")


class HashRing:
    """Consistent-hash placement with virtual nodes.

    Each node owns ``vnodes`` points on a 2^64 ring; a key maps to the
    first node point at or after its hash (wrapping). Disruption on
    membership change is therefore bounded to the changed node's own
    arcs: removal remaps exactly the keys it owned (~K/N), and an add
    steals keys only for itself.
    """

    def __init__(self, vnodes: int = 64):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = int(vnodes)
        self._nodes: set[str] = set()
        self._points: list[int] = []
        self._owners: list[str] = []

    def _rebuild(self) -> None:
        pairs = sorted(
            (_hash64(f"{node}#{i}"), node)
            for node in self._nodes for i in range(self.vnodes))
        self._points = [p for p, _ in pairs]
        self._owners = [o for _, o in pairs]

    def add(self, node: str) -> None:
        if node in self._nodes:
            raise ValueError(f"ring already has node {node!r}")
        self._nodes.add(node)
        self._rebuild()

    def remove(self, node: str) -> None:
        """Remove if present (idempotent: a drained member has already
        left the ring when its death is reported)."""
        if node in self._nodes:
            self._nodes.discard(node)
            self._rebuild()

    def nodes(self) -> list[str]:
        return sorted(self._nodes)

    def lookup(self, key: str) -> str | None:
        if not self._points:
            return None
        i = bisect.bisect_left(self._points, _hash64(key))
        if i == len(self._points):
            i = 0  # wrap
        return self._owners[i]


# -- the lease protocol ------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Lease:
    """One grant: ``tokens`` left the bank for ``gateway``'s slice of
    ``tenant``'s bucket, valid until ``expires_ns``. A lease with 0
    tokens is still a lease — validity means the authority answered;
    the token count is just the allowance it could afford."""

    tenant: str
    gateway: str
    tokens: float
    expires_ns: int


class GlobalBucket:
    """The bank: a tenant's one true token supply.

    Tokens are *minted* here only — ``rate`` per second, capped by the
    ``burst`` headroom — and only leave through :meth:`grant`. The
    mint/grant/deposit odometers never reset, so conservation is
    checkable after any run: ``granted <= minted`` and
    ``spent + held + deposited + destroyed <= granted``.
    """

    def __init__(self, rate: float, burst: float, now_ns: int):
        self.rate = float(rate)
        self.burst = float(burst)
        self.level = float(burst)
        self.minted = float(burst)
        self.granted = 0.0
        self.deposited = 0.0
        self._last_ns = int(now_ns)

    def _refill(self, now_ns: int) -> None:
        dt_ns = max(0, int(now_ns) - self._last_ns)
        self._last_ns = max(self._last_ns, int(now_ns))
        mint = min(self.rate * dt_ns / SEC, self.burst - self.level)
        if mint > 0:
            self.level += mint
            self.minted += mint

    def grant(self, want: float, now_ns: int) -> float:
        self._refill(now_ns)
        x = min(float(want), self.level)
        if x <= 0:
            return 0.0
        self.level -= x
        self.granted += x
        return x

    def deposit(self, tokens: float, now_ns: int) -> float:
        """Accept returned tokens up to the burst headroom; the excess
        is destroyed (conservative — a deposit must never let the bank
        exceed the burst it advertises). Returns the accepted amount."""
        self._refill(now_ns)
        x = min(float(tokens), self.burst - self.level)
        if x <= 0:
            return 0.0
        self.level += x
        self.deposited += x
        return x


class LeaseBroker:
    """The lease authority: one :class:`GlobalBucket` per tenant plus
    the active lease table. In clustered deployments this attaches to
    the dist Controller (``Controller.attach_admission_broker``) so
    grants ride the controller surface; standalone federations own one
    directly. All methods are driven from the federation's
    single-threaded pump."""

    def __init__(self) -> None:
        self.banks: dict[str, GlobalBucket] = {}
        self.quotas: dict[str, TenantQuota] = {}
        self.leases: dict[tuple[str, str], Lease] = {}
        #: Live multiplier on every tenant's mint rate — the
        #: hot-reloadable global throttle (knob
        #: ``gateway.admission.rate_scale``, docs/KNOBS.md).
        self.rate_scale = 1.0

    def register(self, tenant: str, quota: TenantQuota,
                 now_ns: int) -> None:
        if tenant not in self.banks:
            self.banks[tenant] = GlobalBucket(
                quota.rate * self.rate_scale, quota.burst, now_ns)
            self.quotas[tenant] = quota

    def set_rate_scale(self, scale: float, now_ns: int) -> None:
        """Atomic live re-rate of every bank: settle each bucket's mint
        at the OLD rate up to ``now_ns``, then switch. Settling first
        keeps the mint odometer a true piecewise integral — a scale
        change can never mint retroactively, so the no-rate-inflation
        audit bound (minted <= burst + Σ scaleᵢ·rate·dtᵢ) holds across
        any number of mid-run pushes."""
        scale = float(scale)
        if not (scale > 0.0):
            from pbs_tpu.knobs.registry import KnobError

            raise KnobError([f"rate_scale {scale!r} must be > 0"])
        for tenant in sorted(self.banks):
            bank = self.banks[tenant]
            bank._refill(now_ns)  # settle the old-rate interval
            bank.rate = self.quotas[tenant].rate * scale
        self.rate_scale = scale

    def grant(self, tenant: str, gateway: str, want: float,
              now_ns: int, ttl_ns: int) -> Lease | None:
        bank = self.banks.get(tenant)
        if bank is None:
            return None
        tokens = bank.grant(want, now_ns)
        lease = Lease(tenant, gateway, tokens, int(now_ns) + int(ttl_ns))
        self.leases[(tenant, gateway)] = lease
        return lease

    def deposit(self, tenant: str, gateway: str, tokens: float,
                now_ns: int) -> float:
        bank = self.banks.get(tenant)
        if bank is None:
            return 0.0
        self.leases.pop((tenant, gateway), None)
        return bank.deposit(tokens, now_ns)

    def revoke(self, gateway: str) -> None:
        """Forget a retired gateway's leases — its tokens either came
        back through deposits (drain) or died with the box (death);
        either way the active-lease table must not keep advertising a
        dead member as a holder."""
        for k in [k for k in self.leases if k[1] == gateway]:
            del self.leases[k]

    def audit(self) -> dict[str, dict[str, float]]:
        """Per-tenant odometers — one half of the no-rate-inflation
        witness (the federation's ``lease_audit`` joins the gateway
        half)."""
        return {
            t: {"minted": b.minted, "granted": b.granted,
                "deposited": b.deposited, "bank_level": b.level}
            for t, b in sorted(self.banks.items())
        }


class LeasedBucket:
    """A gateway's slice of one tenant's global bucket. Duck-types
    :class:`~pbs_tpu.gateway.admission.TokenBucket`'s
    ``take``/``retry_after_ns`` surface, so the admission controller
    is unchanged — only the token *source* differs:

    - **leased** — ``level`` holds prepaid tokens that arrived through
      :meth:`credit` (a broker grant). No local minting: sustained
      rate is whatever the bank can afford, which is the tenant's
      global rate split across its gateways.
    - **degraded** — the lease lapsed (authority unreachable, or an
      injected ``lease.expire`` refused the renewal). Prepaid tokens
      remain spendable (they were genuinely granted — the bank never
      reclaims granted tokens, so spending them cannot double-issue),
      and beyond them a conservative emergency bucket mints scrip at a
      small fraction of the fair share, *starting empty* — degradation
      mints by time spent degraded, never by the transition itself.
      Successful renewal drops the emergency bucket (unspent scrip
      expires) and resumes the leased mode.

    ``leased_spent`` / ``conservative_spent`` are the odometers the
    no-rate-inflation audit reads: every admitted cost unit is one or
    the other.
    """

    def __init__(self, tenant: str, gateway: str, quota: TenantQuota,
                 capacity: float, conservative_rate: float,
                 conservative_burst: float, renew_period_ns: int,
                 now_ns: int):
        self.tenant = tenant
        self.gateway = gateway
        self.quota = quota
        self.capacity = float(capacity)  # slice cap; re-sliced on N change
        self.level = 0.0  # prepaid tokens (grants only)
        #: A legal request bigger than the slice (cost in (capacity,
        #: burst]) cannot be covered by capacity-bounded top-ups alone —
        #: without this it would shed "quota" with a retry hint that can
        #: never come true. A failed oversized take records the need and
        #: the next renewals borrow toward it (never past the global
        #: burst, and still only what the bank can afford), exactly what
        #: the one-gateway bucket would have held anyway.
        self.pending_need = 0.0
        self.expires_ns = int(now_ns)  # no lease yet
        self.renew_period_ns = int(renew_period_ns)
        self.leased_spent = 0.0
        self.conservative_spent = 0.0
        self.degraded_takes = 0
        self._cons_rate = float(conservative_rate)
        self._cons_burst = float(conservative_burst)
        self._cons: TokenBucket | None = None

    def leased(self, now_ns: int) -> bool:
        return int(now_ns) < self.expires_ns

    def reslice(self, capacity: float, conservative_rate: float,
                conservative_burst: float) -> None:
        """Membership changed: both the slice cap AND the degraded-mode
        floor re-split, so Σ slice caps ≤ global burst and Σ emergency
        rates stay ≤ half the global rate whatever N becomes (a floor
        pinned at creation time would sum past the bound after
        add/remove cycles). A live emergency bucket re-rates in place,
        its level clamped to the new burst — never minted by the
        change."""
        self.capacity = float(capacity)
        self._cons_rate = float(conservative_rate)
        self._cons_burst = float(conservative_burst)
        if self._cons is not None:
            self._cons.rate = self._cons_rate
            self._cons.burst = self._cons_burst
            self._cons.level = min(self._cons.level, self._cons_burst)

    def credit(self, tokens: float, now_ns: int, ttl_ns: int) -> None:
        """The lease path: a broker grant lands here, and ONLY here —
        this is the sole writer of leased level besides ``take`` (the
        ``gw-lease-bypass`` check flags any other)."""
        self.level += float(tokens)
        self.expires_ns = int(now_ns) + int(ttl_ns)
        self._cons = None  # recovery: unspent emergency scrip expires

    def _emergency(self, now_ns: int) -> TokenBucket:
        if self._cons is None:
            cons = TokenBucket(self._cons_rate, self._cons_burst, now_ns)
            cons.level = 0.0  # scrip accrues with degraded TIME only
            self._cons = cons
        return self._cons

    def take(self, cost: float, now_ns: int) -> bool:
        if self.level >= cost:
            self.level -= cost
            self.leased_spent += cost
            if cost >= self.pending_need:
                # The starving request (or a bigger one) got served.
                # A SMALLER take must not clear the flag — interleaved
                # small traffic would forever reset the borrow target
                # before a renewal could reach it.
                self.pending_need = 0.0
            return True
        if cost > self.capacity:
            # Oversized-but-legal (leased OR degraded): flag the borrow
            # target so the renewal loop — resuming renewals counts —
            # can accumulate past the slice cap.
            self.pending_need = max(self.pending_need,
                                    min(float(cost), self.quota.burst))
        if self.leased(now_ns):
            return False  # in-lease exhaustion: wait for the next top-up
        self.degraded_takes += 1
        if self._emergency(now_ns).take(cost, now_ns):
            self.conservative_spent += cost
            return True
        return False

    def retry_after_ns(self, cost: float, now_ns: int) -> int:
        if self.leased(now_ns):
            return max(1, self.renew_period_ns)
        if cost > self._cons_burst:
            # The emergency bucket can NEVER cover this request; its
            # refill horizon would be a retry hint that cannot come
            # true (the admission module's cost-over-burst lesson).
            # The honest hint is the lease-recovery cadence.
            return max(1, self.renew_period_ns)
        return self._emergency(now_ns).retry_after_ns(cost, now_ns)


# -- the federation ----------------------------------------------------------


class FederatedGateway:
    """N member gateways behind one submit surface.

    Members arrive fully built (each with its own backends) and MUST
    share the federation's clock — placement, leases, and the fault
    schedule are all functions of one timeline. The federation routes
    ``submit`` by consistent hash (falling back to the least-loaded
    serviceable member when the home is dead, draining, partitioned,
    or has no routable backend under a FRESH controller health view),
    pumps every live member in ``tick``, renews admission leases, and
    repairs membership changes with requeue handoff.
    """

    def __init__(self, members: list[Gateway], controller=None,
                 clock=None, vnodes: int = 64,
                 renew_period_ns: int = DEFAULT_RENEW_PERIOD_NS,
                 lease_ttl_ns: int = DEFAULT_LEASE_TTL_NS,
                 conservative_frac: float | None = None,
                 spans=None, journal=None):
        if not members:
            raise ValueError("federation needs at least one gateway")
        self.clock = clock if clock is not None else members[0].clock
        #: ONE SpanRecorder shared by every member (obs/spans.py):
        #: all members pump on this federation's single thread, so a
        #: shared ring keeps each request's chain in emission order
        #: even when custody moves between members — the stitched
        #: timeline is a property of construction, not of a merge.
        self.spans = spans
        self.controller = controller
        self.broker = LeaseBroker()
        if controller is not None and hasattr(controller,
                                              "attach_admission_broker"):
            controller.attach_admission_broker(self.broker)
        self.ring = HashRing(vnodes)
        self.renew_period_ns = int(renew_period_ns)
        self.lease_ttl_ns = int(lease_ttl_ns)
        #: Emergency-bucket share of the fair share when a lease lapses;
        #: None = 1/(2·N) at bucket-creation time, so even every member
        #: degrading at once stays under half the global rate.
        self.conservative_frac = conservative_frac
        self.members: dict[str, Gateway] = {}
        self.quotas: dict[str, TenantQuota] = {}
        self._draining: set[str] = set()
        self._partitioned: dict[str, int] = {}  # name -> heal deadline
        self._retired: list[Gateway] = []  # dead/removed, kept for audit
        self.admitted = 0
        self.completed = 0
        self.handoffs = 0
        self.remaps = 0  # membership changes (ring epochs)
        self.lease_refusals = 0
        self.fed_sheds: dict[str, int] = {}
        self.destroyed: dict[str, float] = {}  # tokens dead boxes took down
        self.events: list[dict] = []
        #: Live-knob bridge (attach_knobs): polled once per tick, so
        #: application points are a deterministic function of the
        #: federation's own timeline.
        self._knob_watcher = None
        self.applied_knobs: dict[str, float | int] = {}
        #: Per-member knob bridges (attach_knobs(per_member=True), the
        #: autopilot canary path): one member-keyed watcher each, so a
        #: scoped push reaches exactly its canary set. Adoptions are
        #: recorded in ``knob_adoptions`` (digest-covered when the
        #: autopilot chaos harness is armed).
        self._knob_channel = None
        self._member_watchers: dict[str, object] = {}
        self.knob_adoptions: list[dict] = []
        #: Shadow-trace capture (pbs_tpu/autopilot): arrivals recorded
        #: at the federation's submit surface. None = zero cost.
        self.shadow = None
        self._last_renew_ns: int | None = None
        self._health_cache: tuple[int, dict] = (-1, {})
        #: Write-ahead intent journal (gateway/journal.py,
        #: docs/DURABILITY.md): ONE journal shared by every member —
        #: membership, tenant contracts, lease grant/deposit/destroy
        #: odometer records, and sealed lease-book checkpoints are
        #: journaled here, members stage their request intents into
        #: it, and the federation group-commits ONE frame per
        #: ``tick()``. None = zero cost.
        self.journal = None
        self._last_ckpt_ns: int | None = None
        #: Spend odometers of members that no longer exist as objects
        #: (killed/retired before a crash): recovery folds them in
        #: here so ``lease_audit``'s "admitted cost is token-backed"
        #: identity survives the restart. tenant -> (leased,
        #: conservative). Empty on a never-recovered federation.
        self._recovered_spent: dict[str, tuple[float, float]] = {}
        for gw in members:
            self._attach(gw)
        if journal is not None:
            self.attach_journal(journal)

    # -- journal (docs/DURABILITY.md) ------------------------------------

    def attach_journal(self, journal) -> None:
        """Arm the shared write-ahead journal: every current and
        future member stages its request intents into it (each
        journals its own identity image on attach; commit stays with
        the federation — one frame per ``tick()``), and membership
        deaths, custody transfers, lease odometer records, and sealed
        lease-book checkpoints are journaled from here."""
        if self.journal is not None:
            raise ValueError(
                "federation already has a journal attached; one "
                "durable record owns the front door")
        self.journal = journal
        for name in sorted(self.members):
            self.members[name].attach_journal(journal, autocommit=False)

    # -- membership ------------------------------------------------------

    def _attach(self, gw: Gateway) -> None:
        if gw.name in self.members:
            raise ValueError(f"duplicate gateway name {gw.name!r}")
        if gw.clock is not self.clock:
            raise ValueError(
                f"gateway {gw.name!r} does not share the federation "
                "clock; placement and leases need one timeline")
        if gw.admission.quotas or gw.admission._buckets:
            # A member arriving with its OWN registered tenants holds
            # plain local buckets that mint at the full tenant rate —
            # an invisible bypass of the federation's global-rate
            # contract (absent from lease_audit, N× for a sprayer).
            raise ValueError(
                f"gateway {gw.name!r} has locally registered tenants "
                f"({sorted(gw.admission.quotas) or sorted(gw.admission._buckets)}); "
                "members join bare — register tenants through "
                "FederatedGateway.register_tenant, the lease path")
        if self.journal is not None:
            gw.attach_journal(self.journal, autocommit=False)
        self.members[gw.name] = gw
        gw.admission.bucket_factory = self._bucket_factory(gw.name)
        if self.spans is not None:
            gw.attach_spans(self.spans)
        if self._knob_channel is not None:
            # Per-member adoption armed: a late joiner (rejoin path)
            # gets its own member-keyed watcher, primed so it starts
            # from the channel's current applicable state instead of a
            # gap (a scoped canary value stays foreign to it).
            self._member_watchers[gw.name] = \
                self._make_member_watcher(gw.name)
        self.ring.add(gw.name)

    def _bucket_factory(self, gw_name: str):
        def make(tenant: str, quota: TenantQuota,
                 now_ns: int) -> LeasedBucket:
            n = self._slice_count()
            frac = self._conservative_share(n)
            return LeasedBucket(
                tenant, gw_name, quota,
                capacity=quota.burst / n,
                conservative_rate=quota.rate * frac,
                conservative_burst=max(1.0, quota.burst * frac),
                renew_period_ns=self.renew_period_ns, now_ns=now_ns)
        return make

    def _slice_count(self) -> int:
        """Members that hold admission slices: active and not draining
        (a draining member deposited its tokens back and takes no new
        submissions)."""
        return max(1, len([n for n in self.members
                           if n not in self._draining]))

    def _conservative_share(self, n: int) -> float:
        return (self.conservative_frac
                if self.conservative_frac is not None
                else 1.0 / (2.0 * n))

    def _reslice(self) -> None:
        """Recompute slice capacities AND degraded-mode floors after a
        membership change: the global burst stays split across the
        members that can admit (Σ caps ≤ burst), and the conservative
        emergency rates re-split too (Σ ≤ rate/2) — a floor pinned at
        bucket-creation N would sum past the global rate after enough
        add/remove cycles."""
        n = self._slice_count()
        frac = self._conservative_share(n)
        for gw in self.members.values():
            for b in gw.admission._buckets.values():
                if isinstance(b, LeasedBucket):
                    b.reslice(b.quota.burst / n, b.quota.rate * frac,
                              max(1.0, b.quota.burst * frac))

    def add(self, gw: Gateway) -> None:
        """Live membership add (scale-out or rejoin): the new member
        takes over only its own ring arcs (~K/N tenants remap to it),
        learns every known tenant, and gets initial leases."""
        now = self.clock.now_ns()
        self._attach(gw)
        for tenant, quota in sorted(self.quotas.items()):
            gw.register_tenant(tenant, quota, now_ns=now)
        self._reslice()
        self.remaps += 1
        self.events.append({"now_ns": now, "event": "add",
                            "gateway": gw.name})
        self._renew_all(now, force=True)

    def drain(self, name: str) -> None:
        """Graceful removal, phase 1: leave the ring (new placements
        remap immediately), hand queued requests off NOW — FIFO order
        and DRR deficits carried — and deposit unspent lease tokens
        back to the bank. The member keeps pumping until its inflight
        requests complete; ``tick`` retires it at zero."""
        gw = self.members[name]
        if name in self._draining:
            return
        now = self.clock.now_ns()
        if self.journal is not None:
            self.journal.member_event(now, name, "drain")
        self.events.append({"now_ns": now, "event": "drain",
                            "gateway": name})
        self.ring.remove(name)
        self._draining.add(name)
        for tenant in sorted(gw.admission._buckets):
            b = gw.admission._buckets[tenant]
            if isinstance(b, LeasedBucket) and b.level > 0:
                accepted = self._deposit(tenant, name, b.level, now)
                if self.journal is not None:
                    bank = self.broker.banks.get(tenant)
                    self.journal.deposit(
                        now, tenant, name, accepted,
                        bank.minted if bank else 0.0,
                        bank.level if bank else 0.0)
                b.level = 0.0
                b.expires_ns = now  # lease released
        self._handoff_queued(gw)
        self._reslice()
        self.remaps += 1

    def kill(self, name: str) -> None:
        """Gateway death: the front door dies with requests queued,
        requests inflight on its backends, and unspent lease tokens.
        The federation — the authoritative record of every member's
        state, the controller's view of the box — repairs it: queued
        FIFOs hand off with their deficits, inflight casualties requeue
        at the survivors' front (oldest first), the dead box's backends
        are fenced, and its unspent tokens are accounted ``destroyed``
        (never re-minted: death is conservative, not inflationary)."""
        gw = self.members.pop(name)  # no longer an adoption target
        self._member_watchers.pop(name, None)
        now = self.clock.now_ns()
        if self.journal is not None:
            self.journal.member_event(now, name, "kill")
        self.events.append({"now_ns": now, "event": "kill",
                            "gateway": name})
        self.ring.remove(name)
        self._draining.discard(name)
        self._partitioned.pop(name, None)
        for b in gw.backends:
            fail = getattr(b, "fail", None)
            if fail is not None:
                fail()
        for tenant in sorted(gw.admission._buckets):
            b = gw.admission._buckets[tenant]
            if isinstance(b, LeasedBucket) and b.level > 0:
                if self.journal is not None:
                    self.journal.destroy(now, tenant, name, b.level)
                self.destroyed[tenant] = (
                    self.destroyed.get(tenant, 0.0) + b.level)
                b.level = 0.0
        self._reslice()
        self.remaps += 1
        self._handoff_queued(gw)
        # Inflight casualties: requeue_front per request, so iterate
        # newest-first — the oldest casualty must end up at the head.
        casualties = sorted(gw.inflight.values(),
                            key=lambda r: (r.submit_ns, r.rid),
                            reverse=True)
        gw.inflight.clear()
        for req in casualties:
            target = self._handoff_target(req.tenant)
            if self.spans is not None:
                self.spans.handoff(now, req.rid, name, target.name)
            target.adopt(req)
            self.handoffs += 1
        self.broker.revoke(name)
        self._retired.append(gw)

    def _handoff_queued(self, gw: Gateway) -> None:
        now = self.clock.now_ns()
        for cls in SLO_CLASSES:
            for tenant in gw.queue.tenants(cls):
                reqs, deficit = gw.queue.take_tenant(cls, tenant)
                if not reqs:
                    continue
                target = self._handoff_target(tenant)
                if self.spans is not None:
                    for r in reqs:
                        self.spans.handoff(now, r.rid, gw.name,
                                           target.name)
                # The custody-move intent is journaled by the adopting
                # member itself, before its queue mutates.
                target.adopt_tenant(cls, tenant, reqs, deficit,
                                    from_member=gw.name)
                self.handoffs += len(reqs)

    def _handoff_target(self, tenant: str) -> Gateway:
        """The adopting member for a casualty: the tenant's new home if
        routable, else the least-loaded unpartitioned member, else ANY
        remaining member (a draining or partitioned member adopting
        work delays its exit — never-lost beats drain latency)."""
        home = self.ring.lookup(tenant)
        if home is not None and home in self.members \
                and home not in self._partitioned:
            return self.members[home]
        ranked = sorted(self.members.items())
        pool = ([g for n, g in ranked if n not in self._partitioned
                 and n not in self._draining]
                or [g for n, g in ranked if n not in self._draining]
                or [g for _, g in ranked])
        if not pool:
            raise RuntimeError("no gateway left to adopt casualties")
        return min(pool, key=lambda g: (self._member_load(g), g.name))

    def _retire(self, name: str) -> None:
        if self.journal is not None:
            self.journal.member_event(self.clock.now_ns(), name,
                                      "retire")
        gw = self.members.pop(name)
        self._member_watchers.pop(name, None)
        self._draining.discard(name)
        self._partitioned.pop(name, None)
        self.ring.remove(name)
        self.broker.revoke(name)
        self._retired.append(gw)

    # -- shadow capture (pbs_tpu/autopilot, docs/AUTOPILOT.md) -----------

    def attach_shadow(self, recorder) -> None:
        """Install a shadow-trace recorder at the federation's submit
        surface: every arrival across every member is captured into
        one bounded ring (time, tenant, class, cost) with the tenant
        contracts needed to replay a window stand-alone. Purely an
        observer — no randomness, no digest movement."""
        self.shadow = recorder
        for tenant, quota in sorted(self.quotas.items()):
            recorder.note_tenant(tenant, quota)

    # -- tenants ---------------------------------------------------------

    def register_tenant(self, tenant: str, quota: TenantQuota) -> None:
        now = self.clock.now_ns()
        self.quotas[tenant] = quota
        if self.shadow is not None:
            self.shadow.note_tenant(tenant, quota)
        self.broker.register(tenant, quota, now)
        for name in sorted(self.members):
            self.members[name].register_tenant(tenant, quota, now_ns=now)
        # Initial grants for THIS tenant only — a full renewal round
        # here would be O(T²·N) over a registration loop and would
        # consume other tenants' lease.expire fault streams before the
        # run starts.
        for name in sorted(self.members):
            if name in self._partitioned or name in self._draining:
                continue
            b = self.members[name].admission._buckets.get(tenant)
            if isinstance(b, LeasedBucket):
                self._renew_one(name, tenant, b, now)

    # -- routing + intake ------------------------------------------------

    def _member_load(self, gw: Gateway) -> int:
        return gw.queue.depth() + len(gw.inflight)

    def _member_serviceable(self, gw: Gateway, health: dict) -> bool:
        """At least one backend could take a dispatch: alive, and not
        vetoed by a FRESH controller health entry (stale entries are
        unknown, not verdicts — the staleness satellite's rule)."""
        for b in gw.backends:
            if not b.alive():
                continue
            h = health.get(b.name)
            if (h is not None and not h.get("stale", False)
                    and (not h["alive"] or h["breaker"] == "open")):
                continue
            return True
        return False

    def _health(self) -> dict:
        """The controller view, snapshotted once per clock instant —
        submit bursts within a tick reuse it instead of rebuilding the
        per-agent dict per request (the member pumps' once-per-tick
        discipline, applied to intake)."""
        if self.controller is None:
            return {}
        now = self.clock.now_ns()
        stamp, view = self._health_cache
        if stamp != now:
            view = self.controller.backend_health()
            self._health_cache = (now, view)
        return view

    def route(self, tenant: str) -> Gateway | None:
        """The tenant's home member per the ring, or — when the home is
        dead, draining, partitioned, or has no routable backend — the
        least-loaded serviceable member (cross-gateway least-loaded
        routing over the same ``Controller.backend_health()`` view the
        member pumps use). None = no front door can serve at all."""
        health = self._health()
        live = [self.members[n] for n in sorted(self.members)
                if n not in self._partitioned and n not in self._draining]
        live = [g for g in live if self._member_serviceable(g, health)]
        if not live:
            return None
        home = self.ring.lookup(tenant)
        for g in live:
            if g.name == home:
                return g
        return min(live, key=lambda g: (self._member_load(g), g.name))

    def submit(self, tenant: str, payload, cost: int = 1,
               slo: str | None = None) -> SubmitResult:
        if self.shadow is not None:
            q = self.quotas.get(tenant)
            cls = slo or (q.slo if q is not None else "batch")
            self.shadow.on_submit(self.clock.now_ns(), tenant, cls,
                                  max(1, int(cost)))
        target = self.route(tenant)
        if target is None:
            # Every front door is dead/partitioned: an explicit shed
            # with a backoff hint, never a hang or a silent drop.
            if self.journal is not None:
                q = self.quotas.get(tenant)
                cls = slo or (q.slo if q is not None else "batch")
                self.journal.shed(
                    self.clock.now_ns(), FED_MEMBER, tenant,
                    SLO_CLASSES.index(cls)
                    if cls in SLO_CLASSES else 0,
                    SHED_REASON_CODES["no-gateway"])
            self.fed_sheds["no-gateway"] = \
                self.fed_sheds.get("no-gateway", 0) + 1
            return SubmitResult(False, None, "no-gateway",
                                NO_GATEWAY_RETRY_NS)
        r = target.submit(tenant, payload, cost=cost, slo=slo)
        if r.admitted:
            self.admitted += 1
        return r

    # -- live knobs (docs/KNOBS.md) --------------------------------------

    def attach_knobs(self, channel, per_member: bool = False) -> None:
        """Subscribe this federation to a knob channel
        (knobs/channel.py). Pushes are adopted at the next ``tick()``
        — one poll per pump round, so mid-run reconfiguration lands at
        a deterministic point of the run's own timeline (virtual-clock
        chaos runs replay bit-identically). A push the channel
        REJECTED (malformed/out-of-range) never moves the generation,
        so it is invisible here by construction — atomicity end to
        end.

        ``per_member=True`` (the autopilot canary path,
        docs/AUTOPILOT.md) additionally creates one member-keyed
        watcher per gateway: a push scoped to a member subset is
        adopted by exactly that subset, members joining later get
        primed watchers, and every member adoption is recorded in
        ``knob_adoptions``. The default keeps the single federation-
        level watcher — bit-identical to the pre-canary behavior."""
        from pbs_tpu.knobs.channel import KnobWatcher

        if self._knob_watcher is not None:
            # A second attach would silently orphan the first channel:
            # its pushes would keep validating and moving generations
            # while the federation adopts nothing — the worst kind of
            # misconfiguration (looks armed, does nothing). One
            # federation, one knob channel.
            raise ValueError(
                "federation already has a knob channel attached; "
                "one control plane owns the knob surface")
        watcher = KnobWatcher(channel)
        watcher.add(self._apply_knobs)
        self._knob_watcher = watcher
        if per_member:
            self._knob_channel = channel
            for name in sorted(self.members):
                self._member_watchers[name] = \
                    self._make_member_watcher(name)

    def _make_member_watcher(self, name: str):
        """One member-keyed watcher: scoped pushes reach exactly their
        canary set, and what the member adopted is recorded with the
        federation's own timestamp (the autopilot digest covers it)."""
        from pbs_tpu.knobs.channel import KnobWatcher

        gw = self.members[name]

        def _adopt(changed: dict, values: dict,
                   _gw=gw, _name=name) -> None:
            adopted = _gw.apply_member_knobs(changed, values)
            if adopted:
                self.knob_adoptions.append({
                    "now_ns": self.clock.now_ns(),
                    "member": _name,
                    "knobs": {k: values[k] for k in adopted},
                })

        watcher = KnobWatcher(self._knob_channel, member=name)
        watcher.add(_adopt)
        # Current-state-first: the member adopts the channel's present
        # applicable truth at attach (a canary-scoped value stays
        # foreign to it), so every member carries the same reference
        # baseline before any canary starts — and a later rollback
        # restores the canary member to exactly its peers' state.
        watcher.prime()
        return watcher

    def _apply_knobs(self, changed: dict, values: dict) -> None:
        now = self.clock.now_ns()
        if "gateway.admission.rate_scale" in changed:
            # The live throttle: settle-then-switch on every bank (see
            # LeaseBroker.set_rate_scale for the audit argument).
            self.broker.set_rate_scale(
                float(changed["gateway.admission.rate_scale"]), now)
        self.applied_knobs.update(changed)
        # Digest-covered adoption record: the scenario digest proves
        # WHEN the federation adopted WHAT (gateway/chaos.py).
        self.events.append({
            "now_ns": now, "event": "knobs",
            "gateway": ",".join(f"{k}={values[k]}"
                                for k in sorted(changed)) or "-",
        })

    # -- leases ----------------------------------------------------------

    def _grant(self, tenant: str, gateway: str, want: float,
               now_ns: int) -> Lease | None:
        if self.controller is not None and hasattr(self.controller,
                                                   "admission_lease"):
            return self.controller.admission_lease(
                tenant, gateway, want, now_ns, self.lease_ttl_ns)
        return self.broker.grant(tenant, gateway, want, now_ns,
                                 self.lease_ttl_ns)

    def _deposit(self, tenant: str, gateway: str, tokens: float,
                 now_ns: int) -> float:
        if self.controller is not None and hasattr(self.controller,
                                                   "admission_deposit"):
            return self.controller.admission_deposit(
                tenant, gateway, tokens, now_ns)
        return self.broker.deposit(tenant, gateway, tokens, now_ns)

    def _renew_all(self, now_ns: int, force: bool = False) -> None:
        """One renewal round: every reachable member tops every leased
        bucket back up to its slice capacity and extends its lease.
        The ``lease.expire`` fault sits exactly where a real authority
        timeout would: the renewal simply does not happen, and the
        bucket degrades at expiry. Partitioned members cannot renew —
        their leases lapse naturally, which is the degraded-mode story,
        not a special case."""
        if (not force and self._last_renew_ns is not None
                and now_ns - self._last_renew_ns < self.renew_period_ns):
            return
        self._last_renew_ns = now_ns
        for name in sorted(self.members):
            if name in self._partitioned or name in self._draining:
                continue
            gw = self.members[name]
            for tenant in sorted(gw.admission._buckets):
                b = gw.admission._buckets[tenant]
                if isinstance(b, LeasedBucket):
                    self._renew_one(name, tenant, b, now_ns)

    def _renew_one(self, name: str, tenant: str, b: LeasedBucket,
                   now_ns: int) -> None:
        f = _faults.consult("lease.expire", f"{name}:{tenant}")
        if f is not None:
            self.lease_refusals += 1
            return
        # Top up to the slice cap — or past it toward a recorded
        # oversized-request need (bounded by the global burst; the bank
        # still only grants what it holds).
        want = max(b.capacity, b.pending_need) - b.level
        lease = self._grant(tenant, name, max(0.0, want), now_ns)
        if lease is not None:
            if self.journal is not None:
                # The grant record carries the bank's post-grant
                # odometers — each one is a sealed mini-checkpoint of
                # the mint/level state recovery rebuilds from.
                bank = self.broker.banks.get(tenant)
                self.journal.grant(
                    now_ns, tenant, name, lease.tokens,
                    bank.minted if bank else 0.0,
                    bank.level if bank else 0.0)
            b.credit(lease.tokens, now_ns, self.lease_ttl_ns)

    # -- the pump --------------------------------------------------------

    def tick(self) -> list[tuple[str, dict]]:
        """One federation round: fire membership fault seams, heal due
        partitions, renew leases, pump every reachable member, retire
        drained members that emptied. Returns this tick's completions
        across all members."""
        now = self.clock.now_ns()
        if self._knob_watcher is not None:
            self._knob_watcher.poll()
        if self._member_watchers:
            # Per-member adoption, one poll per live member per tick —
            # skipping partitioned members (a partition IS network
            # isolation; they catch up at heal through the same poll).
            for name in sorted(self._member_watchers):
                if name in self.members and name not in self._partitioned:
                    self._member_watchers[name].poll()
        for name in sorted(self.members):
            if name in self._partitioned:
                continue
            f = _faults.consult("gateway.partition", name)
            if f is not None:
                self._partitioned[name] = now + int(
                    f.args.get("duration_ns", PARTITION_HEAL_NS))
                self.events.append({"now_ns": now, "event": "partition",
                                    "gateway": name})
        for name in sorted(self.members):
            if len(self.members) <= 1:
                break  # quorum guard: never fence the last front door
            f = _faults.consult("gateway.death", name)
            if f is not None:
                self.kill(name)
        for name in sorted(self._partitioned):
            if now >= self._partitioned[name]:
                del self._partitioned[name]
                self.events.append({"now_ns": now, "event": "heal",
                                    "gateway": name})
        self._renew_all(now)
        if self.journal is not None and (
                self._last_ckpt_ns is None
                or now - self._last_ckpt_ns >= JOURNAL_CKPT_PERIOD_NS):
            # Sealed lease-book checkpoint: the bank odometers land as
            # a CKPT group recovery reconciles against
            # (docs/DURABILITY.md "Checkpoints").
            self._last_ckpt_ns = now
            self.journal.checkpoint(now, self.broker.audit())
        done: list[tuple[str, dict]] = []
        for name in sorted(self.members):
            if name in self._partitioned:
                continue
            done.extend(self.members[name].tick())
        self.completed += len(done)
        for name in sorted(self._draining):
            gw = self.members.get(name)
            if gw is not None and not gw.busy():
                self.events.append({"now_ns": now, "event": "remove",
                                    "gateway": name})
                self._retire(name)
        if self.spans is not None:
            self.spans.flush()
        if self.journal is not None:
            # ONE group-commit frame per federation round, AFTER the
            # span flush: the span ring is always a superset of the
            # committed journal, so a mid-commit crash leaves only
            # EXTRA span records (the unacked suffix), never a
            # committed intent without its span.
            self.journal.commit()
        return done

    # -- observability ---------------------------------------------------

    def queued(self) -> int:
        return sum(gw.queue.depth() for gw in self.members.values())

    def inflight_count(self) -> int:
        return sum(len(gw.inflight) for gw in self.members.values())

    def busy(self) -> bool:
        return bool(self.queued() or self.inflight_count())

    def stats(self) -> dict:
        shed: dict[str, int] = dict(self.fed_sheds)
        for gw in list(self.members.values()) + self._retired:
            for k, v in gw.admission.sheds.items():
                shed[k] = shed.get(k, 0) + v
        members = {}
        for name in sorted(self.members):
            gw = self.members[name]
            members[name] = {
                "draining": name in self._draining,
                "partitioned": name in self._partitioned,
                "queued": gw.queue.depth(),
                "inflight": len(gw.inflight),
                "admitted": gw.admitted,
                "adopted": gw.adopted,
            }
        return {
            "admitted": self.admitted,
            "completed": self.completed,
            "queued": self.queued(),
            "inflight": self.inflight_count(),
            "handoffs": self.handoffs,
            "remaps": self.remaps,
            "lease_refusals": self.lease_refusals,
            "shed": dict(sorted(shed.items())),
            "ring": self.ring.nodes(),
            "members": members,
            "retired": sorted(g.name for g in self._retired),
        }

    def lease_audit(self) -> dict[str, dict[str, float]]:
        """The no-rate-inflation witness, per tenant: bank odometers
        (minted/granted/deposited) joined with the gateway-side spend
        odometers, unspent ``held`` tokens, and tokens ``destroyed`` by
        gateway death. The chaos harness asserts the conservation laws
        over this view; see docs/GATEWAY.md."""
        out: dict[str, dict[str, float]] = {}
        everyone = list(self.members.values()) + self._retired
        for tenant, bank in self.broker.audit().items():
            leased_spent = conservative_spent = held = 0.0
            extra = self._recovered_spent.get(tenant)
            if extra is not None:
                leased_spent, conservative_spent = extra
            for gw in everyone:
                b = gw.admission._buckets.get(tenant)
                if isinstance(b, LeasedBucket):
                    leased_spent += b.leased_spent
                    conservative_spent += b.conservative_spent
                    held += b.level
            out[tenant] = {
                **bank,
                "leased_spent": leased_spent,
                "conservative_spent": conservative_spent,
                "held": held,
                "destroyed": self.destroyed.get(tenant, 0.0),
            }
        return out
