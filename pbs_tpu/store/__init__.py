from pbs_tpu.store.store import Store, Transaction, TransactionError

__all__ = ["Store", "Transaction", "TransactionError"]
