"""Hierarchical config/rendezvous store — the xenstore analog.

Reference: xenstore (``xen-4.2.1/tools/xenstore``, 11.7k LoC C +
oxenstored) is the control-plane rendezvous: a transactional
hierarchical key-value tree with watches, used by the toolstack and
guests to exchange configuration and device state.

Here: an in-process tree with path keys (``/jobs/train/weight``),
watches firing on subtree changes (xenstore watch semantics: a watch on
a prefix fires for any descendant), simple transactions
(all-or-nothing batches with optimistic version checks), and optional
JSON file persistence for cross-process handoff. The ``pbst`` CLI and
the controller use it as their source of truth.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable

from pbs_tpu.obs.lockprof import ProfiledLock
from pbs_tpu.runtime.xsm import xsm_check


def _norm(path: str) -> str:
    if not path.startswith("/"):
        raise ValueError(f"store paths are absolute: {path!r}")
    while "//" in path:
        path = path.replace("//", "/")
    return path.rstrip("/") or "/"


class TransactionError(Exception):
    pass


class Store:
    def __init__(self, persist_path: str | None = None):
        self._data: dict[str, Any] = {}
        self._version: dict[str, int] = {}
        self._watches: list[tuple[str, Callable[[str, Any], None]]] = []
        self._lock = ProfiledLock("store", recursive=True)
        self._persist = persist_path
        if persist_path and os.path.exists(persist_path):
            with open(persist_path) as f:
                raw = json.load(f)
            self._data = dict(raw.get("data", {}))
            self._version = {k: int(v) for k, v in raw.get("version", {}).items()}

    # -- basic ops -------------------------------------------------------

    def write(self, path: str, value: Any, subject: str = "system") -> None:
        path = _norm(path)
        xsm_check(subject, "store.write", path)
        with self._lock:
            self._data[path] = value
            self._version[path] = self._version.get(path, 0) + 1
            self._fire(path, value)
            self._save()

    def read(self, path: str, default: Any = None,
             subject: str = "system") -> Any:
        """In-process callers default to the system subject; RPC/CLI
        surfaces pass the caller's label so an enforcing policy governs
        information flow too (FLASK checks reads, not only writes)."""
        path = _norm(path)
        xsm_check(subject, "store.read", path)
        with self._lock:
            return self._data.get(path, default)

    def exists(self, path: str, subject: str = "system") -> bool:
        # Existence is information too: a read-denied label must not be
        # able to probe the key space.
        path = _norm(path)
        xsm_check(subject, "store.read", path)
        return path in self._data

    def rm(self, path: str, subject: str = "system") -> int:
        """Remove path and its whole subtree (xenstore rm). Returns the
        number of removed keys."""
        path = _norm(path)
        xsm_check(subject, "store.rm", path)
        with self._lock:
            doomed = [k for k in self._data
                      if k == path or k.startswith(path + "/")]
            for k in doomed:
                del self._data[k]
                self._version[k] = self._version.get(k, 0) + 1
                self._fire(k, None)
            self._save()
            return len(doomed)

    def ls(self, path: str, subject: str = "system") -> list[str]:
        """Immediate children names (xenstore-ls one level)."""
        path = _norm(path)
        xsm_check(subject, "store.read", path)
        prefix = "" if path == "/" else path
        out = set()
        with self._lock:
            for k in self._data:
                if k.startswith(prefix + "/"):
                    rest = k[len(prefix) + 1:]
                    out.add(rest.split("/", 1)[0])
        return sorted(out)

    def version(self, path: str, subject: str = "system") -> int:
        path = _norm(path)
        xsm_check(subject, "store.read", path)
        return self._version.get(path, 0)

    # -- watches (fire for the key or any ancestor watch prefix) ---------

    def watch(self, prefix: str, fn: Callable[[str, Any], None],
              subject: str = "system") -> None:
        """A watch is a standing read of the subtree — same check."""
        xsm_check(subject, "store.read", _norm(prefix))
        self._watches.append((_norm(prefix), fn))

    def unwatch(self, prefix: str, fn) -> None:
        self._watches.remove((_norm(prefix), fn))

    def _fire(self, path: str, value: Any) -> None:
        for prefix, fn in list(self._watches):
            if path == prefix or path.startswith(prefix + "/") or prefix == "/":
                fn(path, value)

    # -- transactions ----------------------------------------------------

    def transaction(self, subject: str = "system") -> "Transaction":
        return Transaction(self, subject=subject)

    def _save(self) -> None:
        if not self._persist:
            return
        tmp = self._persist + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"data": self._data, "version": self._version}, f)
        os.replace(tmp, self._persist)


class Transaction:
    """Optimistic all-or-nothing batch: reads record versions; commit
    fails if any read key changed (xenstore transaction semantics)."""

    def __init__(self, store: Store, subject: str = "system"):
        self.store = store
        self.subject = subject
        self._reads: dict[str, int] = {}
        self._writes: dict[str, Any] = {}
        self._rms: list[str] = []

    def read(self, path: str, default: Any = None) -> Any:
        path = _norm(path)
        if path in self._writes:
            return self._writes[path]
        self._reads[path] = self.store.version(path, subject=self.subject)
        return self.store.read(path, default, subject=self.subject)

    def write(self, path: str, value: Any) -> None:
        self._writes[_norm(path)] = value

    def rm(self, path: str) -> None:
        self._rms.append(_norm(path))

    def commit(self) -> None:
        s = self.store
        # XSM before any mutation: a transaction must not bypass the
        # checks its individual ops would face (and a denial must leave
        # the batch unapplied — all-or-nothing includes policy).
        for path in self._rms:
            xsm_check(self.subject, "store.rm", path)
        for path in self._writes:
            xsm_check(self.subject, "store.write", path)
        with s._lock:
            for path, ver in self._reads.items():
                if s.version(path) != ver:
                    raise TransactionError(
                        f"conflict on {path}: version {ver} -> "
                        f"{s.version(path)}"
                    )
            # Apply the whole batch in memory, persist ONCE, then fire
            # watches — so a crash cannot leave a half-persisted batch
            # and watchers never observe intermediate states.
            fired: list[tuple[str, Any]] = []
            for path in self._rms:
                doomed = [k for k in s._data
                          if k == path or k.startswith(path + "/")]
                for k in doomed:
                    del s._data[k]
                    s._version[k] = s._version.get(k, 0) + 1
                    fired.append((k, None))
            for path, value in self._writes.items():
                s._data[path] = value
                s._version[path] = s._version.get(path, 0) + 1
                fired.append((path, value))
            s._save()
            for path, value in fired:
                s._fire(path, value)
