"""Typed, seeded scenario genomes: the traffic shapes nobody wrote down.

The workload catalog carries five hand-written mixes; the pathologies
the paper cares about (lock-holder-preemption-style interference,
contention collapse) emerge from traffic SHAPES — diurnal waves, flash
crowds, retry storms after a front-door death, correlated long-context
bursts, tenant misbehavior, multi-region skew. A :class:`Genome` is a
flat, typed, bounded gene vector that composes those primitives into

- a catalog-compatible workload (``build_tenants`` → the shared
  :func:`pbs_tpu.sim.workload.make_mix` constructor, so genome tenants
  and hand-written mixes come from ONE generator set),
- a gateway/federation arrival shape (:class:`GenomeArrivals`, an
  :class:`~pbs_tpu.gateway.chaos.ArrivalModel`), and
- a :class:`~pbs_tpu.faults.plan.FaultPlan` (genome-driven front-door
  adversity, docs/FAULTS.md).

Every operator is a pure function of a sha256-derived seed:
``from_seed``, ``mutate``, and ``crossover`` produce byte-identical
genomes for the same inputs on any host, which is what makes the hunt
archive (hunt.py) and the promoted corpus (corpus.py) replayable CI
artifacts. Construct genomes ONLY through those factories (or
``from_dict`` on a validated gene dict) — the ``scenario-discipline``
check pass flags raw ``Genome(...)`` calls outside this module.

XOS's lens (PAPERS.md, arXiv 1901.00825) shaped the gene set: each
misbehavior primitive stresses a policy travelling with the TENANT
(its admission contract, its lease slice, its SLO class), not the box.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math

import numpy as np

from pbs_tpu.faults.plan import FaultPlan, FaultSpec
from pbs_tpu.gateway.admission import BATCH, INTERACTIVE
from pbs_tpu.gateway.chaos import ArrivalModel
from pbs_tpu.sim.workload import TenantSpec, make_mix
from pbs_tpu.utils.clock import MS

GENOME_VERSION = 1

#: Decimal places every float gene is rounded to at creation: the
#: canonical JSON of a genome — and therefore its digest — is
#: byte-stable across hosts.
_ROUND = 6


@dataclasses.dataclass(frozen=True)
class Gene:
    """One typed, bounded gene."""

    name: str
    kind: str  # "int" | "float"
    lo: int | float
    hi: int | float
    doc: str = ""


#: The gene vector, in canonical order. Mutation/crossover walk this
#: table, so adding a gene extends every operator at once.
GENES: tuple[Gene, ...] = (
    # -- tenant composition (feeds make_mix) ---------------------------
    Gene("n_tenants", "int", 3, 8, "tenants in the mix"),
    Gene("w_hbm", "float", 0.0, 1.0, "kind weight: memory-bound steady"),
    Gene("w_coll", "float", 0.0, 1.0, "kind weight: collective-contended"),
    Gene("w_compute", "float", 0.0, 1.0, "kind weight: compute-bound"),
    Gene("w_alt", "float", 0.0, 1.0, "kind weight: phase-alternating"),
    Gene("w_serve", "float", 0.0, 1.0, "kind weight: bursty serving"),
    # -- arrival shape (feeds GenomeArrivals) --------------------------
    Gene("rate_interactive", "float", 0.05, 0.90,
         "base per-tick fire probability, interactive tenants"),
    Gene("rate_batch", "float", 0.02, 0.60,
         "base per-tick fire probability, batch tenants"),
    Gene("diurnal_amp", "float", 0.0, 1.0,
         "diurnal wave amplitude over the run"),
    Gene("diurnal_periods", "int", 1, 6, "diurnal cycles per run"),
    Gene("flash_at", "float", 0.0, 1.0,
         "flash-crowd start (fraction of the run)"),
    Gene("flash_len", "float", 0.0, 0.3, "flash-crowd length fraction"),
    Gene("flash_mult", "float", 1.0, 8.0,
         "fire-probability multiplier inside the flash window"),
    Gene("retry_mult", "int", 0, 4,
         "thundering-herd factor: forced re-submissions per shed"),
    Gene("longctx_at", "float", 0.0, 1.0,
         "correlated long-context burst start fraction"),
    Gene("longctx_len", "float", 0.0, 0.3, "long-context burst length"),
    Gene("longctx_mult", "float", 1.0, 6.0,
         "batch cost multiplier inside the burst (burst-capped)"),
    Gene("oversize_p", "float", 0.0, 0.3,
         "probability a batch request is oversized-but-legal (cost in "
         "(burst/N, burst]: the lease-borrow path)"),
    Gene("spray_frac", "float", 0.0, 0.5,
         "fraction of tenants misbehaving: firing at max rate every "
         "tick regardless of shape (gateway spraying)"),
    Gene("region_skew", "float", 0.0, 1.0,
         "multi-region skew: first-half tenants run hot, second-half "
         "cold, concentrating load on their ring homes"),
    # -- fault shape (feeds fault_plan) --------------------------------
    Gene("death_p", "float", 0.0, 0.01, "gateway.death kill probability"),
    Gene("partition_p", "float", 0.0, 0.01,
         "gateway.partition probability"),
    Gene("partition_ms", "int", 5, 40, "partition heal time"),
    Gene("lease_expire_p", "float", 0.0, 0.9,
         "lease.expire renewal-refusal probability (a lapse needs "
         "consecutive refusals across a TTL, so the degraded "
         "conservative-bucket regime only shows up near the top of "
         "this range)"),
    Gene("admit_shed_p", "float", 0.0, 0.03,
         "gateway.admit injected-shed probability"),
    Gene("misroute_p", "float", 0.0, 0.15,
         "gateway.route misroute probability"),
    # -- crash shape (feeds crash_plan; docs/DURABILITY.md). These are
    # EXTENSION genes: serialized only when nonzero, so every genome
    # and corpus entry minted before they existed keeps its digest —
    # and its recorded golden replay — byte-identical.
    Gene("crash_p", "float", 0.0, 0.008,
         "gateway.process.kill whole-process death probability per "
         "harness tick (journal-recovered kill-9)"),
    Gene("crash_positions", "int", 0, 3,
         "deterministic kill-9 count, bucketized: k kills land at "
         "evenly spaced tick fractions i/(k+1) of the run"),
)

#: Genes added after the corpus format shipped: zero is the exact
#: pre-gene behavior, omitted from the canonical serialization so old
#: digests cannot move, and defaulted to zero on load.
EXTENSION_GENES = ("crash_p", "crash_positions")

_GENES_BY_NAME = {g.name: g for g in GENES}

#: Tenant kinds a genome composes, in the weight-gene order above.
_KIND_ORDER = ("hbm", "coll", "compute", "alt", "serve")


def derive_seed(*parts) -> int:
    """sha256-fold arbitrary labelled parts into a 63-bit seed — the
    ONLY seed derivation the scenario subsystem uses (sweep's
    ``cell_seed`` idiom), so every stream is independent, labelled,
    and platform-stable."""
    h = hashlib.sha256("|".join(str(p) for p in parts).encode())
    return int.from_bytes(h.digest()[:8], "big") & ((1 << 63) - 1)


def _coerce(gene: Gene, value) -> int | float:
    if gene.kind == "int":
        v = int(value)
    else:
        v = round(float(value), _ROUND)
    return min(gene.hi, max(gene.lo, v))


@dataclasses.dataclass(frozen=True)
class Genome:
    """An immutable gene dict plus the derived identity digest.

    Do not call the constructor directly — genomes come from the
    seeded factories (``from_seed``/``mutate``/``crossover``) or from
    a serialized dict (``from_dict``), which is what keeps every
    genome in an archive or corpus reproducible from its recorded
    provenance (the ``scenario-raw-genome`` rule enforces this)."""

    genes: tuple[tuple[str, int | float], ...]

    # -- identity --------------------------------------------------------

    def __getitem__(self, name: str) -> int | float:
        for k, v in self.genes:
            if k == name:
                return v
        raise KeyError(name)

    def as_dict(self) -> dict:
        # Extension genes serialize only when nonzero (zero IS the
        # pre-gene behavior): a genome that never crashes has the same
        # canonical bytes — and digest, and eval seed, and recorded
        # golden replay — it had before the genes existed.
        return {"version": GENOME_VERSION,
                "genes": {k: v for k, v in self.genes
                          if v != 0 or k not in EXTENSION_GENES}}

    def canonical(self) -> str:
        return json.dumps(self.as_dict(), sort_keys=True,
                          separators=(",", ":"))

    def digest(self) -> str:
        return hashlib.sha256(self.canonical().encode()).hexdigest()

    def name(self) -> str:
        """The registered-workload name (embeds the content digest, so
        re-registering is idempotent by construction)."""
        return f"scn:{self.digest()[:16]}"

    # -- factories -------------------------------------------------------

    @classmethod
    def _from_values(cls, values: dict) -> "Genome":
        genes = tuple((g.name, _coerce(g, values[g.name])) for g in GENES)
        return cls(genes=genes)

    @classmethod
    def from_seed(cls, seed: int) -> "Genome":
        """Uniform draw of every gene from its declared range."""
        rng = np.random.default_rng(derive_seed("genome", seed))
        values = {}
        for g in GENES:
            if g.kind == "int":
                values[g.name] = int(rng.integers(g.lo, int(g.hi) + 1))
            else:
                values[g.name] = float(rng.uniform(g.lo, g.hi))
        return cls._from_values(values)

    @classmethod
    def from_dict(cls, d: dict) -> "Genome":
        """Validated load (corpus/archive entries). Unknown, missing,
        or out-of-range genes are errors — a corpus entry that no
        longer fits the declared gene table must fail loudly, not
        silently clamp into a different scenario."""
        if d.get("version") != GENOME_VERSION:
            raise ValueError(
                f"genome version {d.get('version')!r} != "
                f"{GENOME_VERSION}")
        raw = d.get("genes")
        if not isinstance(raw, dict):
            raise ValueError("genome carries no genes dict")
        # Absent extension genes mean zero (their omitted-when-zero
        # serialization), never an error: pre-extension corpus
        # entries stay loadable at their recorded digests.
        raw = {**{g: 0 for g in EXTENSION_GENES}, **raw}
        unknown = sorted(set(raw) - set(_GENES_BY_NAME))
        missing = sorted(set(_GENES_BY_NAME) - set(raw))
        if unknown or missing:
            raise ValueError(
                f"genome genes mismatch: unknown={unknown} "
                f"missing={missing}")
        for name, value in raw.items():
            g = _GENES_BY_NAME[name]
            if isinstance(value, bool) or \
                    not isinstance(value, (int, float)):
                raise ValueError(f"gene {name}: {value!r} not a number")
            if not (g.lo <= value <= g.hi):
                raise ValueError(
                    f"gene {name}: {value!r} outside [{g.lo}, {g.hi}]")
        return cls._from_values(raw)

    def mutate(self, seed: int, rate: float = 0.35) -> "Genome":
        """Perturb each gene with probability ``rate`` (gaussian step
        scaled to the gene's range, clamped); at least one gene always
        moves. Pure function of (self, seed, rate)."""
        rng = np.random.default_rng(
            derive_seed("mutate", self.digest(), seed,
                        round(float(rate), _ROUND)))
        # Fixed consumption: one pick-draw and one step-draw per gene,
        # plus one forced-gene index — branch-free stream usage.
        picks = rng.random(len(GENES))
        steps = rng.standard_normal(len(GENES))
        forced = int(rng.integers(0, len(GENES)))
        values = {}
        moved = False
        for i, g in enumerate(GENES):
            v = self[g.name]
            if picks[i] < rate or i == forced:
                span = float(g.hi) - float(g.lo)
                v = _coerce(g, float(v) + 0.25 * span * float(steps[i]))
                if g.kind == "int" and v == self[g.name]:
                    # An int gene whose step rounded away still moves
                    # (deterministically, toward the far bound).
                    v = _coerce(g, v + (1 if steps[i] >= 0 else -1))
                moved = moved or v != self[g.name]
            values[g.name] = v
        if not moved:
            # Every picked gene was already pinned at a bound it
            # stepped into: flip the forced gene across its range.
            g = GENES[forced]
            cur = self[g.name]
            flipped = _coerce(
                g, float(g.hi) + float(g.lo) - float(cur))
            if flipped == cur:
                # The flip is the identity at the exact range
                # midpoint — send the gene to a bound instead, so
                # "at least one gene always moves" actually holds.
                flipped = _coerce(
                    g, g.lo if float(cur) >
                    (float(g.lo) + float(g.hi)) / 2 else g.hi)
            values[g.name] = flipped
        return type(self)._from_values(values)

    def crossover(self, other: "Genome", seed: int) -> "Genome":
        """Uniform per-gene crossover: each gene comes from self or
        ``other``. Pure function of (self, other, seed)."""
        rng = np.random.default_rng(
            derive_seed("cross", self.digest(), other.digest(), seed))
        take = rng.random(len(GENES))
        values = {
            g.name: (self[g.name] if take[i] < 0.5 else other[g.name])
            for i, g in enumerate(GENES)
        }
        return type(self)._from_values(values)

    # -- bridges ---------------------------------------------------------

    def tenant_kinds(self, seed: int, n_tenants: int) -> list[str]:
        """Per-tenant kind choices from the weight genes: a seeded
        categorical draw (pure function of genome + seed). At least
        one always-on tenant is guaranteed — a mix of only bursty
        serving tenants would idle the partition between bursts."""
        w = np.array([max(1e-6, float(self[f"w_{k}"]))
                      for k in _KIND_ORDER])
        w = w / w.sum()
        rng = np.random.default_rng(
            derive_seed("kinds", self.digest(), seed))
        kinds = [
            _KIND_ORDER[int(rng.choice(len(_KIND_ORDER), p=w))]
            for _ in range(max(1, int(n_tenants)))
        ]
        if all(k == "serve" for k in kinds):
            kinds[0] = "hbm"
        return kinds

    def build_tenants(self, seed: int, n_tenants: int,
                      horizon_ns: int) -> list[TenantSpec]:
        """The genome→workload bridge: catalog-compatible tenants via
        the SAME :func:`make_mix` constructor the hand-written catalog
        uses."""
        return make_mix(self.tenant_kinds(seed, n_tenants), seed,
                        horizon_ns)

    def register(self):
        """Register this genome's workload builder under
        :meth:`name` so the sim engine and chaos harnesses run it by
        name. Returns the name; pair with
        ``sim.workload.unregister_workload`` when done."""
        from pbs_tpu.sim.workload import register_workload

        return register_workload(
            self.name(),
            lambda seed, n, horizon_ns: self.build_tenants(
                seed, n, horizon_ns))

    def fault_plan(self, seed: int) -> FaultPlan:
        """Genome-driven front-door adversity: the federation fault
        points at the genome's probabilities (docs/FAULTS.md). Zero-
        probability specs are omitted so the plan dict — which the
        chaos report records — names only the pressure actually
        applied."""
        g = self
        specs: list[FaultSpec] = []
        if g["death_p"] > 0:
            specs.append(FaultSpec("gateway.death", "kill",
                                   p=g["death_p"], after=20, times=2))
        if g["partition_p"] > 0:
            specs.append(FaultSpec(
                "gateway.partition", "partition", p=g["partition_p"],
                times=3,
                args={"duration_ns": int(g["partition_ms"]) * MS}))
        if g["lease_expire_p"] > 0:
            specs.append(FaultSpec("lease.expire", "expire",
                                   p=g["lease_expire_p"]))
        if g["admit_shed_p"] > 0:
            specs.append(FaultSpec(
                "gateway.admit", "shed", p=g["admit_shed_p"],
                args={"retry_after_ns": 10 * MS}))
        if g["misroute_p"] > 0:
            specs.append(FaultSpec("gateway.route", "misroute",
                                   p=g["misroute_p"]))
        return FaultPlan(seed=int(seed), specs=tuple(specs)).validate()

    def gateway_fault_plan(self, seed: int) -> FaultPlan:
        """The single-gateway subset (no federation seams) for the
        ``run_gateway_chaos`` leg of the stress scorer."""
        g = self
        specs = []
        if g["admit_shed_p"] > 0:
            specs.append(FaultSpec(
                "gateway.admit", "shed", p=g["admit_shed_p"],
                args={"retry_after_ns": 10 * MS}))
        if g["misroute_p"] > 0:
            specs.append(FaultSpec("gateway.route", "misroute",
                                   p=g["misroute_p"]))
        return FaultPlan(seed=int(seed), specs=tuple(specs)).validate()

    def crash_plan(self, ticks: int) -> "list[dict] | None":
        """The crash genes as a ``run_federation_chaos(crash_plan=)``
        schedule (docs/DURABILITY.md): ``crash_positions`` kills land
        at evenly spaced tick fractions, ``crash_p`` adds seeded
        probabilistic kills (times-capped). Both zero — the
        pre-extension genome — returns None, which arms no journal
        and keeps every recorded golden byte-identical."""
        p = float(self["crash_p"])
        k = int(self["crash_positions"])
        if p == 0 and k == 0:
            return None
        plan: list[dict] = []
        for j in range(k):
            plan.append({"tick": ((j + 1) * int(ticks)) // (k + 1)})
        if p > 0:
            plan.append({"p": p, "times": 2, "after": 20})
        return plan

    def process_kill_plan(self, ticks: int, seed: int) -> "list[dict] | None":
        """The crash genes realized for PROCESS MODE (docs/GATEWAY.md
        "Process mode"), where every kill is a literal SIGKILL to a
        member pid and must be tick-positioned — a real signal cannot
        be aimed at a byte offset, and the probabilistic ``crash_p``
        stream has no in-process consult point to ride. So the
        probabilistic gene is realized HERE, seeded and times-capped
        (2) like the in-process plan entry it mirrors, into concrete
        ticks; ``crash_positions`` lands at the same evenly spaced
        fractions as ``crash_plan``. Both genes zero returns None: a
        genome that never crashes kills no processes."""
        p = float(self["crash_p"])
        k = int(self["crash_positions"])
        if p == 0 and k == 0:
            return None
        plan = [{"tick": ((j + 1) * int(ticks)) // (k + 1)}
                for j in range(k)]
        if p > 0:
            rng = np.random.default_rng(int(seed) * 9176 + 77)
            fired = 0
            for t in range(20, int(ticks)):
                if fired >= 2:
                    break
                if rng.random() < p:
                    plan.append({"tick": t})
                    fired += 1
        plan.sort(key=lambda e: e["tick"])
        return plan or None

    def arrival_model(self, tenants, ticks: int, seed: int,
                      n_gateways: int = 3) -> "GenomeArrivals":
        return GenomeArrivals(self, tenants, ticks, seed,
                              n_gateways=n_gateways)


class GenomeArrivals(ArrivalModel):
    """The genome's per-tick traffic shape over the chaos harness's
    per-tenant rng streams.

    Determinism contract: ``draw`` consumes a FIXED number of stream
    draws per call (fire, interactive cost, batch cost, oversize)
    whatever branch the shape takes, so the decision stream is a pure
    function of the harness seed — the same rule the stock
    :func:`~pbs_tpu.gateway.chaos.draw_arrival` follows.

    Reactive shape state (the retry-storm backlog, per-tenant
    submit/shed books the scorer reads) lives on the instance: one
    instance per harness run, never reused.
    """

    def __init__(self, genome: Genome, tenants, ticks: int, seed: int,
                 n_gateways: int = 3):
        self.genome = genome
        self.ticks = max(1, int(ticks))
        self.order = [t.name for t in tenants]
        self.index = {name: i for i, name in enumerate(self.order)}
        n = len(self.order)
        g = genome
        # Misbehaving (spraying) tenants: a seeded choice, pure in
        # (genome, seed) — NOT "the first k" (that would alias the
        # region-skew split).
        rng = np.random.default_rng(
            derive_seed("spray", genome.digest(), seed))
        k = int(round(float(g["spray_frac"]) * n))
        self.spraying = set(
            int(i) for i in rng.choice(n, size=min(k, n), replace=False))
        # Oversized-but-legal batch cost: past the per-member lease
        # slice (burst/N) but never past the global burst — the borrow
        # path (gateway/federation.py), NOT the permanent
        # cost-over-burst shed — for the batch quota the harness
        # derives from the catalog contract (quota_for).
        from pbs_tpu.gateway.chaos import quota_for

        batch_burst = float(quota_for("b", BATCH, 1).burst)
        self.oversize_cost = min(
            int(batch_burst),
            max(int(batch_burst // max(1, n_gateways)) + 1,
                int(0.8 * batch_burst)))
        self.pending_retries: dict[str, int] = {}
        self.submits: dict[str, int] = {}
        self.sheds: dict[str, int] = {}
        # draw() runs once per tick per tenant on the chaos hot path;
        # genes are immutable, so snapshot the ones it reads as plain
        # attributes instead of paying Genome.__getitem__'s linear
        # scan ~10 times per call.
        self._rate_i = float(g["rate_interactive"])
        self._rate_b = float(g["rate_batch"])
        self._diurnal_periods = int(g["diurnal_periods"])
        self._diurnal_amp = float(g["diurnal_amp"])
        self._flash_at = float(g["flash_at"])
        self._flash_len = float(g["flash_len"])
        self._flash_mult = float(g["flash_mult"])
        self._region_skew = float(g["region_skew"])
        self._longctx_at = float(g["longctx_at"])
        self._longctx_len = float(g["longctx_len"])
        self._longctx_mult = float(g["longctx_mult"])
        self._oversize_p = float(g["oversize_p"])
        self._retry_mult = int(g["retry_mult"])

    def _window(self, tick: int, at: float, length: float) -> bool:
        frac = tick / self.ticks
        return at <= frac < at + length

    def draw(self, t, tick: int, rng):
        u = float(rng.random())
        cost_i = 1 + int(rng.integers(0, 3))
        cost_b = 4 + int(rng.integers(0, 9))
        over = float(rng.random())

        i = self.index.get(t.name, 0)
        interactive = t.slo == INTERACTIVE
        p = self._rate_i if interactive else self._rate_b
        # Diurnal wave: the run is one day, genes set cycles/amplitude.
        # A TRIANGLE wave, deliberately: it is built from IEEE basic
        # ops only (bit-deterministic on every host), where sin()'s
        # last ulp varies across libm versions — and a one-ulp flip on
        # a fire threshold would make corpus golden digests
        # host-dependent.
        cycles = self._diurnal_periods * tick / self.ticks
        pos = cycles - math.floor(cycles)
        p *= 1.0 + self._diurnal_amp * (1.0 - 4.0 * abs(pos - 0.5))
        # Flash crowd window.
        if self._window(tick, self._flash_at, self._flash_len):
            p *= self._flash_mult
        # Multi-region skew: first half hot, second half cold.
        skew = self._region_skew
        if i < len(self.order) // 2:
            p *= 1.0 + skew
        else:
            p *= max(0.05, 1.0 - 0.8 * skew)
        # Misbehavior: spraying tenants ignore every shape and hammer.
        if i in self.spraying:
            p = 0.95
        fire = u < min(0.95, max(0.0, p))
        # Retry storm: a shed earlier turns into forced re-submission
        # pressure now (thundering herd after a front-door event).
        backlog = self.pending_retries.get(t.name, 0)
        if not fire and backlog > 0:
            self.pending_retries[t.name] = backlog - 1
            fire = True

        cost = cost_i if interactive else cost_b
        if not interactive:
            if self._window(tick, self._longctx_at, self._longctx_len):
                # Correlated long-context burst: every batch tenant's
                # cost inflates together (capped under the burst so
                # admission stays legal).
                cost = min(int(cost * self._longctx_mult), 100)
            if over < self._oversize_p:
                cost = self.oversize_cost
        if fire:
            self.submits[t.name] = self.submits.get(t.name, 0) + 1
        return fire, cost

    def note_result(self, tenant: str, tick: int,
                    admitted: bool) -> None:
        if not admitted:
            self.sheds[tenant] = self.sheds.get(tenant, 0) + 1
            mult = self._retry_mult
            if mult > 0:
                self.pending_retries[tenant] = \
                    self.pending_retries.get(tenant, 0) + mult

    def shed_asymmetry(self) -> float:
        """Max−min per-tenant shed fraction — the scorer's shed-
        asymmetry axis (a uniform overload sheds everyone equally;
        a pathological shape starves SOME tenants at the door)."""
        fracs = []
        for name in self.order:
            subs = self.submits.get(name, 0)
            if subs:
                fracs.append(self.sheds.get(name, 0) / subs)
        if not fracs:
            return 0.0
        return round(max(fracs) - min(fracs), _ROUND)
