"""Stress scorer: how hard a scenario genome leans on the invariants.

One candidate evaluation runs the genome through THREE harnesses
("Fake Runs, Real Fixes", PAPERS.md — thousands of simulated
tenant-hours hunting defects before production traffic does):

- the **sim** harness (``sim/sweep.run_cell``; native C dispatch core
  when the toolchain is present, the Python witness otherwise — the
  usual tier contract, digests tier-invariant) → Jain fairness
  collapse under the scheduler;
- the **gateway** harness (``run_gateway_chaos`` with the genome's
  arrival shape and admission faults) → shed asymmetry at one front
  door;
- the **federation** harness (``run_federation_chaos`` with the
  genome's arrival shape AND its fault plan) → SLO burn, lease-audit
  slack, span-gap proximity, plus the run's golden
  ``trace_digest``/``report_digest`` pair.

The axes are normalized to [0, 1], weighted by the ``scenarios.score.*``
registry knobs into one stress score, and discretized into a behavior
signature (the hunt archive's MAP-Elites key). Everything is rounded
before aggregation, so a stress report — and the archive built from
it — is byte-stable across runs, hosts, and worker counts.

The **invariant gate** (:func:`gate`) is what stands between a
frontier candidate and the archive: the federation leg re-runs and
must (a) hold every chaos invariant (no-job-lost, the piecewise mint
bound, span continuity — ``report["ok"]``) and (b) reproduce the
recorded digests exactly (same-seed-same-digest). A candidate whose
own replay drifts is rejected — an unreproducible pathology is not a
regression test.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from pbs_tpu.scenarios.genome import Genome, derive_seed
from pbs_tpu.utils.clock import MS

_ROUND = 6

#: Axis order everywhere (signature strings, weights, reports).
AXES = ("burn", "fairness", "slack", "gap", "shed")


@dataclasses.dataclass(frozen=True)
class StressConfig:
    """Harness shape one evaluation runs under — part of every corpus
    entry, so a promoted scenario replays on ITS grid, not whatever
    the module defaults became later."""

    base_seed: int = 0
    ticks: int = 240
    tick_ns: int = 1 * MS
    n_gateways: int = 3
    backends_per_gateway: int = 2
    gw_ticks: int = 160
    gw_backends: int = 3
    sim_policy: str = "feedback"
    sim_horizon_ns: int = 100 * MS

    def as_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "StressConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(d) - fields)
        if unknown:
            raise ValueError(f"unknown stress-config keys {unknown}")
        return cls(**d)

    @classmethod
    def demo(cls, base_seed: int = 0) -> "StressConfig":
        """The tier-1 smoke shape (`pbst scenarios hunt --demo`):
        small enough that a whole hunt fits the 5 s budget on a
        loaded 1-vCPU host."""
        return cls(base_seed=base_seed, ticks=120, gw_ticks=80,
                   sim_horizon_ns=40 * MS)


def eval_seed(genome: Genome, cfg: StressConfig) -> int:
    """The evaluation seed: a pure function of (genome, base seed) —
    the same genome always replays the same realization, which is
    what makes archive entries and corpus goldens reproducible."""
    return derive_seed("eval", genome.digest(), cfg.base_seed)


def _norm(x: float) -> float:
    """Unbounded-ratio squash into [0, 1): x/(1+x)."""
    x = max(0.0, float(x))
    return round(x / (1.0 + x), _ROUND)


def _federation_axes(rep: dict) -> dict[str, float]:
    burn = 0.0
    for t in rep["slo"]["tenants"].values():
        burn = max(burn, float(t["burn_rate"]))
    leased = conservative = 0.0
    for a in rep["lease_audit"].values():
        leased += float(a["leased_spent"])
        conservative += float(a["conservative_spent"])
    slack = conservative / max(1.0, leased + conservative)
    transfers = int(rep["spans"]["handoff_events"])
    for t in rep["slo"]["tenants"].values():
        transfers += int(t["requeues"])
    gap = transfers / max(1, int(rep["stats"]["admitted"]))
    return {
        "burn": _norm(burn),
        "slack": round(min(1.0, slack), _ROUND),
        "gap": _norm(gap),
    }


def resolve_scoring() -> dict:
    """Snapshot the ``scenarios.score.w_*`` weights and the signature
    bucket count from the knob registry IN THIS PROCESS. ``evaluate``
    takes the snapshot as an argument so ``evaluate_many`` can resolve
    it once in the parent and ship it to spawn workers — a process-
    local knob overlay (``knobs.set_local``) would otherwise be
    invisible to fresh worker processes and break the 1-vs-N
    worker-count digest parity the hunt pins."""
    from pbs_tpu import knobs

    return {
        "weights": {a: float(knobs.get(f"scenarios.score.w_{a}"))
                    for a in AXES},
        "buckets": int(knobs.get("scenarios.hunt.archive_buckets")),
    }


def evaluate(genome: Genome, cfg: StressConfig,
             scoring: dict | None = None) -> dict:
    """One full candidate evaluation → the canonical stress report
    (axes, weighted score, behavior signature, per-harness summaries,
    and the federation run's golden digests). Pure function of
    (genome, cfg, scoring); every float pre-rounded. ``scoring=None``
    resolves :func:`resolve_scoring` in-process."""
    from pbs_tpu.gateway.chaos import (
        run_federation_chaos,
        run_gateway_chaos,
    )
    from pbs_tpu.sim.sweep import SweepCell, run_cell
    from pbs_tpu.sim.workload import unregister_workload

    seed = eval_seed(genome, cfg)
    n_tenants = int(genome["n_tenants"])
    name = genome.register()
    try:
        sim_rep = run_cell(
            SweepCell.make(name, cfg.sim_policy, rep=0,
                           n_tenants=n_tenants,
                           horizon_ns=cfg.sim_horizon_ns),
            base_seed=cfg.base_seed)

        gw_tenants = genome.build_tenants(seed, n_tenants,
                                          cfg.gw_ticks * cfg.tick_ns)
        gw_model = genome.arrival_model(gw_tenants, cfg.gw_ticks, seed,
                                        n_gateways=1)
        gw_rep = run_gateway_chaos(
            workload=name, seed=seed, n_backends=cfg.gw_backends,
            n_tenants=n_tenants, ticks=cfg.gw_ticks,
            tick_ns=cfg.tick_ns, plan=genome.gateway_fault_plan(seed),
            arrival_model=gw_model)

        fed_tenants = genome.build_tenants(seed, n_tenants,
                                           cfg.ticks * cfg.tick_ns)
        fed_model = genome.arrival_model(fed_tenants, cfg.ticks, seed,
                                         n_gateways=cfg.n_gateways)
        fed_rep = run_federation_chaos(
            workload=name, seed=seed, n_gateways=cfg.n_gateways,
            backends_per_gateway=cfg.backends_per_gateway,
            n_tenants=n_tenants, ticks=cfg.ticks, tick_ns=cfg.tick_ns,
            plan=genome.fault_plan(seed), arrival_model=fed_model,
            # Crash genes -> journal-recovered kill-9s; None (both
            # genes zero) arms no journal and keeps the recorded
            # goldens byte-identical (docs/DURABILITY.md).
            crash_plan=genome.crash_plan(cfg.ticks))
    finally:
        unregister_workload(name)

    axes = {
        "fairness": round(
            max(0.0, 1.0 - float(sim_rep["jain_fairness"])), _ROUND),
        "shed": round(gw_model.shed_asymmetry(), _ROUND),
        **_federation_axes(fed_rep),
    }
    scoring = scoring or resolve_scoring()
    weights = scoring["weights"]
    buckets = int(scoring["buckets"])
    score = round(sum(weights[a] * axes[a] for a in AXES), _ROUND)
    signature = "-".join(
        str(min(buckets - 1, int(axes[a] * buckets))) for a in AXES)
    return {
        "genome": genome.as_dict(),
        "seed": seed,
        "axes": {a: axes[a] for a in AXES},
        "score": score,
        "signature": signature,
        "ok": bool(sim_rep is not None and gw_rep["ok"]
                   and fed_rep["ok"]),
        "problems": list(gw_rep["problems"]) + list(fed_rep["problems"]),
        "sim": {
            "jain_fairness": sim_rep["jain_fairness"],
            "wait_p99_us": sim_rep["wait_p99_us"],
            "switches_per_s": sim_rep["switches_per_s"],
        },
        "gateway": {
            "admitted": gw_rep["stats"]["admitted"],
            "shed": gw_rep["stats"]["shed"],
            "trace_digest": gw_rep["trace_digest"],
        },
        "federation": {
            "admitted": fed_rep["stats"]["admitted"],
            "completed": fed_rep["stats"]["completed"],
            "handoffs": fed_rep["stats"]["handoffs"],
            "lease_refusals": fed_rep["stats"]["lease_refusals"],
            "worst_burn": max(
                [float(t["burn_rate"])
                 for t in fed_rep["slo"]["tenants"].values()] or [0.0]),
        },
        "golden": {
            "trace_digest": fed_rep["trace_digest"],
            "report_digest": fed_rep["report_digest"],
        },
    }


def run_gate(genome: Genome, cfg: StressConfig,
             expect: dict | None = None) -> dict:
    """THE chaos invariant gate: re-run the federation leg and demand
    (a) every invariant held (no-job-lost, mint bound, span
    continuity) and (b) — when ``expect`` carries recorded digests —
    byte-identical replay (same-seed-same-digest). Used both at
    archive admission (hunt.py) and at corpus replay
    (``pbst scenarios replay --check``)."""
    from pbs_tpu.gateway.chaos import run_federation_chaos
    from pbs_tpu.sim.workload import unregister_workload

    seed = eval_seed(genome, cfg)
    n_tenants = int(genome["n_tenants"])
    name = genome.register()
    try:
        tenants = genome.build_tenants(seed, n_tenants,
                                       cfg.ticks * cfg.tick_ns)
        model = genome.arrival_model(tenants, cfg.ticks, seed,
                                     n_gateways=cfg.n_gateways)
        rep = run_federation_chaos(
            workload=name, seed=seed, n_gateways=cfg.n_gateways,
            backends_per_gateway=cfg.backends_per_gateway,
            n_tenants=n_tenants, ticks=cfg.ticks, tick_ns=cfg.tick_ns,
            plan=genome.fault_plan(seed), arrival_model=model,
            crash_plan=genome.crash_plan(cfg.ticks))
    finally:
        unregister_workload(name)
    problems = list(rep["problems"])
    if expect is not None:
        for key in ("trace_digest", "report_digest"):
            if rep[key] != expect[key]:
                problems.append(
                    f"{key} drift: recorded {expect[key][:16]}… "
                    f"replayed {rep[key][:16]}… — the scenario is not "
                    "reproducible at this tree")
    return {
        "ok": not problems,
        "problems": problems,
        "trace_digest": rep["trace_digest"],
        "report_digest": rep["report_digest"],
        "admitted": rep["stats"]["admitted"],
        "completed": rep["stats"]["completed"],
    }


def _evaluate_star(payload: tuple[dict, dict, dict]) -> dict:
    genome_d, cfg_d, scoring = payload
    return evaluate(Genome.from_dict(genome_d),
                    StressConfig.from_dict(cfg_d), scoring=scoring)


def evaluate_many(genomes, cfg: StressConfig,
                  workers: int = 1) -> list[dict]:
    """Evaluate a population; results in input order on ANY worker
    count (the sweep substrate's rule — pool.map preserves order, and
    every evaluation is shared-nothing: each worker registers the
    genome's workload in its own process). The scoring knobs are
    resolved HERE, in the parent, and shipped to workers — see
    :func:`resolve_scoring`."""
    genomes = list(genomes)
    scoring = resolve_scoring()
    if workers <= 1 or len(genomes) <= 1:
        return [evaluate(g, cfg, scoring=scoring) for g in genomes]
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    payloads = [(g.as_dict(), cfg.as_dict(), scoring)
                for g in genomes]
    with ctx.Pool(min(workers, len(genomes))) as pool:
        return pool.map(_evaluate_star, payloads)
