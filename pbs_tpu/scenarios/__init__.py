"""pbs_tpu.scenarios — coverage-guided adversarial scenario frontier.

Find the pathologies before millions of users do (ROADMAP 5;
docs/SCENARIOS.md): seeded scenario genomes compose arrival primitives
(diurnal waves, flash crowds, retry storms, long-context bursts,
tenant misbehavior, multi-region skew) into catalog-compatible
workloads + fault plans (genome.py); a stress scorer runs each
candidate through the sim/gateway/federation harnesses and measures
the invariant pressure it produces (score.py); a MAP-Elites hunt
keeps the best pressure per behavior signature, with every admission
re-proved under the full chaos invariant gate (hunt.py); and found
pathologies are promoted into a checked-in regression corpus replayed
by `pbst scenarios replay --check` (corpus.py).

jax-free by construction: the whole stack rides the sim/gateway tier.
"""

from pbs_tpu.scenarios.corpus import (
    CORPUS_DIR,
    PROMOTE_AXES,
    corpus_digest,
    corpus_paths,
    load_entry,
    make_entry,
    promote_frontier,
    replay_corpus,
    replay_entry,
    save_entry,
    whatif_entry,
    whatif_window,
)
from pbs_tpu.scenarios.genome import (
    GENES,
    GENOME_VERSION,
    Gene,
    Genome,
    GenomeArrivals,
    derive_seed,
)
from pbs_tpu.scenarios.hunt import (
    HuntConfig,
    archive_digest,
    hunt,
)
from pbs_tpu.scenarios.score import (
    AXES,
    StressConfig,
    evaluate,
    evaluate_many,
    run_gate,
)

__all__ = [
    "AXES",
    "CORPUS_DIR",
    "GENES",
    "GENOME_VERSION",
    "PROMOTE_AXES",
    "Gene",
    "Genome",
    "GenomeArrivals",
    "HuntConfig",
    "StressConfig",
    "archive_digest",
    "corpus_digest",
    "corpus_paths",
    "derive_seed",
    "evaluate",
    "evaluate_many",
    "hunt",
    "load_entry",
    "make_entry",
    "promote_frontier",
    "replay_corpus",
    "replay_entry",
    "run_gate",
    "save_entry",
    "whatif_entry",
    "whatif_window",
]
