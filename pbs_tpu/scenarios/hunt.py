"""Coverage-guided scenario hunt: an elite archive over behavior space.

A MAP-Elites-shaped loop (`pbst scenarios hunt`): seed a population of
random genomes, evaluate each through the stress scorer (score.py),
and keep the archive's best stress score PER BEHAVIOR SIGNATURE — the
discretized (burn, fairness, slack, gap, shed) cell. Coverage guidance
falls out of the key: a candidate only displaces an incumbent that
stresses the invariants the SAME way but harder; a candidate with a
new signature claims new territory however mediocre its score. The
next generation breeds from the archive (mutation + crossover of
elites), so search pressure concentrates where stress was found while
the signature grid keeps it spread across qualitatively different
pathologies.

Admission is gated: every would-be archive entry re-runs under the
full chaos invariant gate (score.run_gate — no-job-lost, mint bound,
span continuity, same-seed-same-digest). A candidate whose replay
drifts or whose run violates an invariant is REJECTED and logged; the
archive holds only reproducible, invariant-clean pathologies, which
is what makes promotion (corpus.py) sound.

Determinism: populations, breeding choices, and admission order are
pure functions of the hunt seed (sha256-derived streams, sorted
iteration); evaluations are shared-nothing and order-preserved
(score.evaluate_many), so the archive — and its digest — is
byte-identical on any worker count. The loop constants (population,
generations, rates, archive bounds) come from the ``scenarios.hunt.*``
registry knobs: hunts are tunable with ``pbst knobs set``, no code
edits.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any

import numpy as np

from pbs_tpu.scenarios.genome import Genome, derive_seed
from pbs_tpu.scenarios.score import (
    AXES,
    StressConfig,
    evaluate_many,
    run_gate,
)

HUNT_VERSION = 1


def _knob(name: str):
    from pbs_tpu import knobs

    return knobs.get(name)


@dataclasses.dataclass(frozen=True)
class HuntConfig:
    """One hunt's shape. Defaults come from the ``scenarios.hunt.*``
    knobs at construction time (``HuntConfig.from_knobs``), so a
    ``pbst knobs set scenarios.hunt.population=32`` changes the next
    hunt without touching code."""

    seed: int = 0
    population: int = 8
    generations: int = 4
    mutation_rate: float = 0.35
    crossover_rate: float = 0.5
    archive_max: int = 64
    stress: StressConfig = dataclasses.field(
        default_factory=StressConfig)

    @classmethod
    def from_knobs(cls, seed: int = 0,
                   stress: StressConfig | None = None) -> "HuntConfig":
        return cls(
            seed=int(seed),
            population=int(_knob("scenarios.hunt.population")),
            generations=int(_knob("scenarios.hunt.generations")),
            mutation_rate=float(_knob("scenarios.hunt.mutation_rate")),
            crossover_rate=float(
                _knob("scenarios.hunt.crossover_rate")),
            archive_max=int(_knob("scenarios.hunt.archive_max")),
            stress=stress or StressConfig(base_seed=int(seed)),
        )

    @classmethod
    def demo(cls, seed: int = 0) -> "HuntConfig":
        """The tier-1 smoke shape: a real (tiny) hunt in a few
        seconds on a loaded 1-vCPU host."""
        return cls(seed=int(seed), population=4, generations=2,
                   stress=StressConfig.demo(base_seed=int(seed)))

    def as_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["stress"] = self.stress.as_dict()
        return d


def archive_digest(archive: dict[str, dict]) -> str:
    """sha256 over the canonical archive — the hunt's determinism
    witness (same seed + config ⇒ same digest, any worker count)."""
    payload = json.dumps(archive, sort_keys=True,
                         separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()


def _entry_from(result: dict) -> dict:
    """The archived slice of a stress report (everything promotion and
    replay need; canonical key order via sorted dumps later)."""
    return {
        "genome": result["genome"],
        "seed": result["seed"],
        "axes": result["axes"],
        "score": result["score"],
        "signature": result["signature"],
        "sim": result["sim"],
        "federation": result["federation"],
        "golden": result["golden"],
    }


def _breed(archive: dict[str, dict], cfg: HuntConfig,
           generation: int) -> list[Genome]:
    """Next population from the elites: a seeded, pure-function mix of
    elite mutation, elite crossover, and fresh blood when the archive
    is still thin."""
    elites = [archive[sig] for sig in
              sorted(archive, key=lambda s: (-archive[s]["score"], s))]
    out: list[Genome] = []
    for i in range(cfg.population):
        slot_seed = derive_seed("breed", cfg.seed, generation, i)
        if not elites:
            out.append(Genome.from_seed(slot_seed))
            continue
        rng = np.random.default_rng(slot_seed)
        u = float(rng.random())
        a = Genome.from_dict(
            elites[int(rng.integers(0, len(elites)))]["genome"])
        if u < cfg.crossover_rate and len(elites) > 1:
            b = Genome.from_dict(
                elites[int(rng.integers(0, len(elites)))]["genome"])
            child = a.crossover(b, slot_seed)
            # A self-cross is the identity: fall through to mutation
            # so the slot still explores.
            if child.digest() == a.digest():
                child = a.mutate(slot_seed, rate=cfg.mutation_rate)
        else:
            child = a.mutate(slot_seed, rate=cfg.mutation_rate)
        out.append(child)
    return out


def hunt(cfg: HuntConfig, workers: int = 1,
         progress=None) -> dict:
    """Run the loop; returns the hunt document:
    ``{"archive": {signature: entry}, "archive_digest", "log",
    "rejected", ...}``. ``progress`` (optional callable) receives one
    line per generation."""
    archive: dict[str, dict] = {}
    seen: set[str] = set()
    rejected: list[dict] = []
    log: list[dict] = []
    population = [
        Genome.from_seed(derive_seed("init", cfg.seed, i))
        for i in range(cfg.population)
    ]
    for generation in range(cfg.generations):
        fresh: list[Genome] = []
        for g in population:
            if g.digest() not in seen:
                seen.add(g.digest())
                fresh.append(g)
        results = evaluate_many(fresh, cfg.stress, workers=workers)
        admitted = 0
        for genome, res in zip(fresh, results):
            sig = res["signature"]
            incumbent = archive.get(sig)
            if incumbent is not None and \
                    res["score"] <= incumbent["score"]:
                continue
            # Frontier candidate: through the full invariant gate
            # before it may displace anything. A candidate whose OWN
            # evaluation already violated an invariant is rejected
            # without paying for the gate's federation replay.
            if not res["ok"]:
                rejected.append({
                    "generation": generation,
                    "signature": sig,
                    "genome_digest": genome.digest(),
                    "problems": res["problems"][:5],
                })
                continue
            verdict = run_gate(genome, cfg.stress, expect=res["golden"])
            if not verdict["ok"]:
                rejected.append({
                    "generation": generation,
                    "signature": sig,
                    "genome_digest": genome.digest(),
                    "problems": verdict["problems"][:5],
                })
                continue
            archive[sig] = _entry_from(res)
            admitted += 1
        # Bound the archive: evict the weakest cells, loudly.
        evicted = 0
        while len(archive) > cfg.archive_max:
            worst = min(archive,
                        key=lambda s: (archive[s]["score"], s))
            del archive[worst]
            evicted += 1
        best = max((e["score"] for e in archive.values()),
                   default=0.0)
        entry = {
            "generation": generation,
            "evaluated": len(fresh),
            "admitted": admitted,
            "evicted": evicted,
            "archive_size": len(archive),
            "best_score": best,
        }
        log.append(entry)
        if progress is not None:
            progress(
                f"gen {generation}: evaluated {len(fresh)} "
                f"admitted {admitted} archive {len(archive)} "
                f"best {best:.4f}")
        if generation + 1 < cfg.generations:
            population = _breed(archive, cfg, generation)
    return {
        "version": HUNT_VERSION,
        "config": cfg.as_dict(),
        "axes": list(AXES),
        "archive": {sig: archive[sig] for sig in sorted(archive)},
        "archive_digest": archive_digest(
            {sig: archive[sig] for sig in sorted(archive)}),
        "log": log,
        "rejected": rejected,
    }
