"""Promoted regression corpus: found pathologies become permanent CI.

The point of the hunt is not the archive — it is that every discovered
pathology GRADUATES into a checked-in regression scenario
(``pbs_tpu/scenarios/corpus/*.json``), exactly the way ``pbst chaos``
plans and tuned-profile check blocks work today: genome + seed +
harness config + stress report + golden trace/report digests, replayed
by ``pbst scenarios replay --check`` in tier-1. A later change that
moves ANY of a promoted scenario's digests fails CI — either the
change regressed the pathology's handling (fix it) or it legitimately
moved the behavior (re-promote in the same PR, like refreshing
``perf/baseline.json``).

Corpus entries are selected per STRESS AXIS (``promote_frontier``):
one scenario each for the invariant pressures worth pinning — SLO
burn, fairness collapse, lease-audit slack, … — so the corpus spans
qualitatively different failure shapes instead of five flavors of the
same flood. Every entry re-runs the full chaos invariant gate at
promotion time; nothing unreproducible or invariant-violating can be
promoted.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any

from pbs_tpu.scenarios.genome import Genome
from pbs_tpu.scenarios.score import AXES, StressConfig, run_gate

CORPUS_VERSION = 1

#: The checked-in corpus (shipped regression scenarios).
CORPUS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "corpus")

#: Default promotion axes: the three invariant pressures the
#: acceptance bar pins (one scenario each, distinct entries).
PROMOTE_AXES = ("burn", "fairness", "slack")


def entry_name(axis: str, genome_digest: str) -> str:
    return f"{axis}-{genome_digest[:12]}"


def make_entry(axis: str, archive_entry: dict,
               stress_cfg: StressConfig, note: str = "") -> dict:
    """One corpus document from a hunt-archive entry (hunt.py
    ``_entry_from`` shape). The golden digests and the full harness
    config ride along so replay needs nothing but this file."""
    for key in ("genome", "seed", "axes", "score", "signature",
                "golden"):
        if key not in archive_entry:
            raise ValueError(f"archive entry missing {key!r}")
    golden = archive_entry["golden"]
    if not golden.get("trace_digest") or not golden.get("report_digest"):
        raise ValueError("archive entry carries no golden digests")
    return {
        "version": CORPUS_VERSION,
        "name": entry_name(
            axis, Genome.from_dict(archive_entry["genome"]).digest()),
        "axis": axis,
        "note": note or (
            f"promoted by `pbst scenarios promote` (docs/SCENARIOS.md);"
            f" stresses the {axis} axis at "
            f"{archive_entry['axes'][axis]}. Regenerate in the same PR"
            " as any change that moves this scenario's digests —"
            " `pbst scenarios replay --check` gates it"),
        "config": stress_cfg.as_dict(),
        "genome": archive_entry["genome"],
        "seed": archive_entry["seed"],
        "stress": {
            "axes": archive_entry["axes"],
            "score": archive_entry["score"],
            "signature": archive_entry["signature"],
            "sim": archive_entry.get("sim", {}),
            "federation": archive_entry.get("federation", {}),
        },
        "golden": {
            "trace_digest": golden["trace_digest"],
            "report_digest": golden["report_digest"],
        },
    }


def save_entry(entry: dict, corpus_dir: str | None = None) -> str:
    """Atomic, stable-key write (corpus files are checked in)."""
    d = corpus_dir or CORPUS_DIR
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{entry['name']}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(entry, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_entry(path: str) -> dict:
    with open(path) as f:
        entry = json.load(f)
    if not isinstance(entry, dict):
        raise ValueError(f"{path}: corpus entry is not a JSON object")
    if entry.get("version") != CORPUS_VERSION:
        raise ValueError(
            f"{path}: corpus version {entry.get('version')!r} != "
            f"{CORPUS_VERSION}")
    for key in ("name", "genome", "seed", "config", "golden"):
        if key not in entry:
            raise ValueError(f"{path}: corpus entry missing {key!r}")
    for key in ("genome", "config", "golden"):
        if not isinstance(entry[key], dict):
            raise ValueError(
                f"{path}: corpus {key!r} must be an object")
    g = entry["golden"]
    if not g.get("trace_digest") or not g.get("report_digest"):
        raise ValueError(f"{path}: corpus entry missing golden digests")
    Genome.from_dict(entry["genome"])  # gene-table validation
    return entry


def corpus_paths(corpus_dir: str | None = None) -> list[str]:
    d = corpus_dir or CORPUS_DIR
    if not os.path.isdir(d):
        return []
    return [os.path.join(d, f) for f in sorted(os.listdir(d))
            if f.endswith(".json")]


def corpus_digest(entries: list[dict]) -> str:
    """sha256 over the canonical corpus stream (sorted by name) — the
    whole-corpus determinism witness `pbst scenarios replay` prints."""
    h = hashlib.sha256()
    for e in sorted(entries, key=lambda e: e["name"]):
        h.update(json.dumps(e, sort_keys=True,
                            separators=(",", ":")).encode())
        h.update(b"\n")
    return h.hexdigest()


def replay_entry(entry: dict, check: bool = True) -> dict:
    """Re-run one promoted scenario through the chaos invariant gate;
    ``check`` additionally demands byte-identical digests against the
    recorded goldens (the CI mode). Returns the verdict dict."""
    genome = Genome.from_dict(entry["genome"])
    cfg = StressConfig.from_dict(entry["config"])
    verdict = run_gate(genome, cfg,
                       expect=entry["golden"] if check else None)
    return {
        "name": entry["name"],
        "axis": entry.get("axis"),
        "ok": verdict["ok"],
        "problems": verdict["problems"],
        "expected_trace_digest": entry["golden"]["trace_digest"],
        "got_trace_digest": verdict["trace_digest"],
        "expected_report_digest": entry["golden"]["report_digest"],
        "got_report_digest": verdict["report_digest"],
        "admitted": verdict["admitted"],
        "completed": verdict["completed"],
    }


def replay_corpus(corpus_dir: str | None = None,
                  check: bool = True) -> dict:
    """Replay every corpus entry; the `pbst scenarios replay` engine.
    ``ok`` = every entry held its invariants (and, with ``check``,
    its digests)."""
    entries = [load_entry(p) for p in corpus_paths(corpus_dir)]
    verdicts = [replay_entry(e, check=check) for e in entries]
    return {
        "version": CORPUS_VERSION,
        "corpus_dir": corpus_dir or CORPUS_DIR,
        "entries": len(entries),
        "corpus_digest": corpus_digest(entries),
        "verdicts": verdicts,
        "ok": bool(entries) and all(v["ok"] for v in verdicts),
    }


def whatif_window(entry: dict):
    """A promoted scenario as an autopilot shadow-replay input: the
    genome's arrival stream synthesized into a
    :class:`~pbs_tpu.autopilot.recorder.ShadowWindow` (the workload
    IS arrivals — the recorder's own rule), with the same tenant
    admission contracts and the same per-tenant seeded streams the
    federation harness consumes (``catalog_arrivals`` tag 11). Open
    loop by construction: no gateway in sight, so shed-reactive
    shapes (retry storms) contribute their base pressure only — this
    is "the traffic the tenants ASK for", which is exactly what a
    shadow window captures at the submit seam."""
    from pbs_tpu.autopilot.recorder import ShadowWindow
    from pbs_tpu.gateway.chaos import catalog_arrivals, quota_for

    genome = Genome.from_dict(entry["genome"])
    cfg = StressConfig.from_dict(entry["config"])
    seed = int(entry["seed"])
    n_tenants = int(genome["n_tenants"])
    horizon_ns = cfg.ticks * cfg.tick_ns
    tenants = genome.build_tenants(seed, n_tenants, horizon_ns)
    model = genome.arrival_model(tenants, cfg.ticks, seed,
                                 n_gateways=cfg.n_gateways)
    rngs = catalog_arrivals(tenants, seed, tag=11)
    arrivals: list[tuple[int, str, str, int]] = []
    for tick in range(cfg.ticks):
        for t in tenants:
            fire, cost = model.draw(t, tick, rngs[t.name])
            if fire:
                arrivals.append(
                    (tick * cfg.tick_ns, t.name, t.slo, int(cost)))
    contracts = {}
    for t in tenants:
        q = quota_for(t.name, t.slo, t.params.weight)
        contracts[t.name] = {
            "rate": q.rate, "burst": q.burst, "weight": q.weight,
            "slo": q.slo, "max_queued": q.max_queued,
        }
    return ShadowWindow(t0_ns=0, t1_ns=horizon_ns,
                        arrivals=tuple(arrivals), tenants=contracts)


def whatif_entry(entry: dict, quick: bool = True,
                 workers: int = 1) -> dict:
    """Close the loop with the autopilot: what tuned profile would
    the shadow search propose if production traffic looked like this
    promoted pathology? Pure function of the entry (the search seeds
    from the synthesized window's digest), so the verdict is a stable
    artifact worth reading next to the scenario."""
    from pbs_tpu.autopilot.shadow import classify_window, shadow_search

    window = whatif_window(entry)
    proposal = shadow_search(window, quick=quick, workers=workers)
    return {
        "name": entry["name"],
        "axis": entry.get("axis"),
        "window_digest": window.digest(),
        "arrivals": len(window.arrivals),
        "workload_class": classify_window(window),
        "proposal": proposal,
    }


def promote_frontier(hunt_result: dict,
                     corpus_dir: str | None = None,
                     axes=PROMOTE_AXES,
                     min_axis: float = 0.0) -> list[dict[str, Any]]:
    """Select + gate + write: for each requested axis, the archive
    entry with the highest value ON THAT AXIS (ties break on score
    then signature; an entry already promoted for an earlier axis is
    skipped, so the corpus files are distinct scenarios). Entries
    whose axis value is ≤ ``min_axis`` are skipped — promoting a
    scenario that does not actually stress its axis would pin noise.
    Each selected entry re-runs the invariant gate against its
    recorded goldens before anything is written."""
    archive = hunt_result.get("archive", {})
    stress_cfg = StressConfig.from_dict(
        hunt_result["config"]["stress"])
    taken: set[str] = set()
    out: list[dict[str, Any]] = []
    for axis in axes:
        if axis not in AXES:
            raise KeyError(f"unknown stress axis {axis!r}; "
                           f"known: {list(AXES)}")
        ranked = sorted(
            (e for sig, e in archive.items() if sig not in taken),
            key=lambda e: (-e["axes"][axis], -e["score"],
                           e["signature"]))
        if not ranked or ranked[0]["axes"][axis] <= min_axis:
            out.append({"axis": axis, "promoted": False,
                        "reason": "no archive entry stresses this "
                                  "axis above the floor"})
            continue
        entry = ranked[0]
        taken.add(entry["signature"])
        genome = Genome.from_dict(entry["genome"])
        verdict = run_gate(genome, stress_cfg,
                           expect=entry["golden"])
        if not verdict["ok"]:
            out.append({"axis": axis, "promoted": False,
                        "reason": "invariant gate rejected the "
                                  "candidate at promotion",
                        "problems": verdict["problems"][:5]})
            continue
        doc = make_entry(axis, entry, stress_cfg)
        path = save_entry(doc, corpus_dir)
        out.append({"axis": axis, "promoted": True, "path": path,
                    "name": doc["name"],
                    "axis_value": entry["axes"][axis],
                    "score": entry["score"]})
    return out
