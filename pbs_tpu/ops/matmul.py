"""Instrumented Pallas TPU matmul: the kernel counts its own work.

The reference's research core hinges on *hardware* counters the guest
can read cheaply (``drivers/perfctr/x86.c:228-312`` — rdpmc with zero
hypercalls). A TPU exposes no per-tenant PMC file, but a Pallas kernel
can play the PMU's role for the op it implements: alongside the
product, it emits a small counter vector accumulated on-device across
grid cells — MXU tile invocations, HBM tile traffic, and a
data-derived event (all-zero A tiles, the sparsity the MXU wasted work
on). The host scales tiles into FLOPs exactly like perf tooling scales
event counts, then feeds them to the telemetry ledger through the
job-metrics channel (``TpuBackend._METRIC_KEYS``).

Blockwise schedule: grid (M/bm, N/bn, K/bk) with k innermost; each
(i, j) output block accumulates over k in fp32 directly in the output
ref (initialized at k==0 — the standard Pallas matmul pattern). The
stats ref maps every grid cell to one block, so on TPU's sequential
grid the accumulation is race-free; interpreter mode (CPU CI) follows
the same order.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK = 256

# Stat vector slots (i32; tile counts, not raw flops — the host scales,
# like software scaling a PMC event count, so 2^31 is never a limit).
STAT_MXU_TILES = 0
STAT_A_ZERO_TILES = 1
STAT_READ_KIB = 2
STAT_WRITE_KIB = 3
N_STATS = 4


def _mm_kernel(a_ref, b_ref, o_ref, stats_ref, *, n_k: int):
    i, j, k = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(jnp.logical_and(i == 0, jnp.logical_and(j == 0, k == 0)))
    def _init_stats():
        # Per-slot scalar stores: the stats ref lives in SMEM (the
        # scalar memory — r5 stage-2 on-chip finding: Mosaic rejects
        # scalar stores to VMEM, which interpret mode accepted), and
        # SMEM takes scalar writes, not vector ones.
        for t in range(N_STATS):
            stats_ref[t] = 0

    @pl.when(k == 0)
    def _init_out():
        o_ref[...] = jnp.zeros_like(o_ref)

    a = a_ref[...]
    b = b_ref[...]
    o_ref[...] += jax.lax.dot_general(
        a, b, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    # -- the PMU duty: count what just happened -------------------------
    a_kib = (a.size * a.dtype.itemsize) // 1024
    b_kib = (b.size * b.dtype.itemsize) // 1024
    o_kib = (o_ref.size * o_ref.dtype.itemsize) // 1024
    a_is_zero = (jnp.count_nonzero(a) == 0).astype(jnp.int32)
    stats_ref[STAT_MXU_TILES] += 1
    stats_ref[STAT_A_ZERO_TILES] += a_is_zero
    stats_ref[STAT_READ_KIB] += a_kib + b_kib
    # one write per finished (i, j) block
    stats_ref[STAT_WRITE_KIB] += jnp.where(k == n_k - 1, o_kib, 0)


@dataclasses.dataclass(frozen=True)
class MatmulStats:
    """Host-scaled view of the kernel's counter vector."""

    mxu_tiles: int
    a_zero_tiles: int
    flops: int  # tiles x 2 x bm x bn x bk (software-scaled, PMC-style)
    hbm_read_bytes: int
    hbm_write_bytes: int

    def metrics(self) -> dict[str, int]:
        """Shape expected by the Job metrics channel (step_fn returning
        ``(state, metrics)``) — lands in DEVICE_FLOPS / HBM_BYTES ledger
        slots via ``TpuBackend._METRIC_KEYS``."""
        return {
            "device_flops": self.flops,
            "hbm_bytes": self.hbm_read_bytes + self.hbm_write_bytes,
        }


def scale_stats(raw, block_m: int, block_n: int, block_k: int) -> MatmulStats:
    """raw: the (N_STATS,) i32 vector from :func:`instrumented_matmul`."""
    tiles = int(raw[STAT_MXU_TILES])
    return MatmulStats(
        mxu_tiles=tiles,
        a_zero_tiles=int(raw[STAT_A_ZERO_TILES]),
        flops=tiles * 2 * block_m * block_n * block_k,
        hbm_read_bytes=int(raw[STAT_READ_KIB]) * 1024,
        hbm_write_bytes=int(raw[STAT_WRITE_KIB]) * 1024,
    )


@functools.partial(
    jax.jit, static_argnames=("block_m", "block_n", "block_k", "interpret"))
def instrumented_matmul(
    a: jax.Array,  # (M, K)
    b: jax.Array,  # (K, N)
    block_m: int = DEFAULT_BLOCK,
    block_n: int = DEFAULT_BLOCK,
    block_k: int = DEFAULT_BLOCK,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Returns ``(a @ b, stats)`` — stats is the raw (N_STATS,) i32
    on-device counter vector; scale with :func:`scale_stats`.
    fp32 accumulation regardless of input dtype (MXU-native)."""
    M, K = a.shape
    K2, N = b.shape
    if K != K2:
        raise ValueError(f"inner dims differ: {K} vs {K2}")
    bm, bn, bk = min(block_m, M), min(block_n, N), min(block_k, K)
    if M % bm or N % bn or K % bk:
        raise ValueError(
            f"shape ({M},{K})x({K},{N}) not divisible by blocks "
            f"({bm},{bn},{bk})")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n_k = K // bk

    out, stats = pl.pallas_call(
        functools.partial(_mm_kernel, n_k=n_k),
        grid=(M // bm, N // bn, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
            # Scalar counters accumulate in SMEM (Mosaic: VMEM takes
            # vector stores only); whole array, every grid cell.
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M, N), jnp.float32),
            jax.ShapeDtypeStruct((N_STATS,), jnp.int32),
        ],
        interpret=interpret,
    )(a, b)
    return out, stats
