from pbs_tpu.ops.attention import flash_attention

__all__ = ["flash_attention"]
