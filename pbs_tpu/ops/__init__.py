from pbs_tpu.ops.attention import flash_attention
from pbs_tpu.ops.matmul import (
    MatmulStats,
    instrumented_matmul,
    scale_stats,
)

__all__ = [
    "MatmulStats",
    "flash_attention",
    "instrumented_matmul",
    "scale_stats",
]
