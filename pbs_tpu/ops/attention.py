"""Pallas TPU flash attention (causal, GQA) with online softmax.

The hot op of the flagship workload, written blockwise so attention
probabilities never materialize in HBM: per (batch, head, q-block)
grid cell, iterate over k/v blocks with the online-softmax recurrence
(running max m, normalizer l, fp32 accumulator) — the standard
flash-attention scheme expressed in Pallas for the MXU/VMEM hierarchy
(block sizes 128, fp32 accumulation via ``preferred_element_type``).

Causal skip: a q-block only visits k-blocks up to its diagonal —
``fori_loop`` with a traced upper bound, so the work per row is
triangular, not square.

Falls back to interpreter mode off-TPU so the same code path is tested
on CPU CI (the fake-backend pattern, SURVEY.md §4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, causal: bool,
                  sm_scale: float, block_k: int):
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # (BQ, hd)
    bq = q.shape[0]
    hd = q.shape[1]
    s_len = k_ref.shape[2]
    i = pl.program_id(2)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        if causal:
            qpos = i * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    if causal:
        # Only k-blocks at or before this q-block's diagonal.
        n_blocks = jax.lax.div(i * bq + bq + block_k - 1, block_k)
    else:
        n_blocks = s_len // block_k
    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret"))
def flash_attention(
    q: jax.Array,  # (B, S, H, hd)
    k: jax.Array,  # (B, S, Hkv, hd)
    v: jax.Array,  # (B, S, Hkv, hd)
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool | None = None,
) -> jax.Array:
    """Returns (B, S, H, hd). GQA: H must be a multiple of Hkv."""
    B, S, H, hd = q.shape
    Hkv = k.shape[2]
    if H % Hkv:
        raise ValueError(f"H={H} not a multiple of Hkv={Hkv}")
    group = H // Hkv
    bq = min(block_q, S)
    bk = min(block_k, S)
    if S % bq or S % bk:
        raise ValueError(f"S={S} must be divisible by block sizes {bq},{bk}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # (B, H, S, hd) layout: heads become a grid dimension.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    kernel = functools.partial(
        _flash_kernel, causal=causal, sm_scale=1.0 / np.sqrt(hd), block_k=bk)
    out = pl.pallas_call(
        kernel,
        grid=(B, H, S // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, S, hd),
                         lambda b, h, i, g=group: (b, h // g, 0, 0)),
            pl.BlockSpec((1, 1, S, hd),
                         lambda b, h, i, g=group: (b, h // g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, hd), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, S, hd), q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)
