"""Pallas TPU flash attention (causal, GQA), training-grade.

The hot op of the flagship workload, written blockwise so attention
probabilities never materialize in HBM: per (batch, head, q-block)
grid cell, iterate over k/v blocks with the online-softmax recurrence
(running max m, normalizer l, fp32 accumulator) — the standard
flash-attention scheme expressed in Pallas for the MXU/VMEM hierarchy
(block sizes 128, fp32 accumulation via ``preferred_element_type``).

Differentiable end to end via ``jax.custom_vjp``: the forward kernel
additionally emits the per-row logsumexp, and the backward pass is two
more Pallas kernels — a dq pass (grid over q-blocks, loop over
k-blocks) and a dk/dv pass (grid over *kv*-head k-blocks, loop over
q-blocks and the GQA group, so the group reduction happens in-kernel).
Recompute-not-store: backward rebuilds p = exp(s - lse) blockwise from
q/k, exactly like forward, so nothing O(S²) ever exists.

Causal skip: a q-block only visits k-blocks up to its diagonal (and a
k-block only visits q-blocks from its diagonal on) — ``fori_loop`` with
a traced bound, so the work per row is triangular, not square.

Ragged S is accepted: the wrapper zero-pads up to the block size,
masks padded keys in-kernel, and slices padded query rows off.  The
backward kernels rely on the padded rows' output cotangent being zero,
which the wrapper's slice guarantees.

Falls back to interpreter mode off-TPU so the same code paths are
tested on CPU CI (the fake-backend pattern, SURVEY.md §4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from pbs_tpu.utils.params import integer_param

# Block-shape defaults, env-tunable so the on-chip sweep can explore
# the VMEM/occupancy trade at long S without code edits (e.g.
# PBST_FLASH_BLOCK_Q=256 PBST_FLASH_BLOCK_K=512 python bench_longctx.py).
# Registered through the boot-param registry: a malformed value warns
# and falls back instead of making the package unimportable.
_block_q_param = integer_param("flash_block_q", 128)
_block_k_param = integer_param("flash_block_k", 128)


def _tile_checked(v: int, fallback: int, axis: str, mult: int) -> int:
    # Mosaic block shapes need (sublane, lane) multiples of (8, 128);
    # catch an off-tile knob HERE with the knob's name, not deep in
    # the kernel lowering (on-chip debug cycles are expensive).
    if v <= 0 or v % mult:
        print(f"pbst: PBST_FLASH_BLOCK_{axis}={v} is not a positive "
              f"multiple of {mult}; using {fallback}")
        return fallback
    return v


DEFAULT_BLOCK_Q = _tile_checked(_block_q_param.value, 128, "Q", 8)
DEFAULT_BLOCK_K = _tile_checked(_block_k_param.value, 128, "K", 128)
NEG_INF = -1e30


# -- forward ----------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal: bool,
                sm_scale: float, block_k: int, valid_len: int):
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # (BQ, hd)
    bq = q.shape[0]
    hd = q.shape[1]
    s_len = k_ref.shape[2]
    i = pl.program_id(2)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        kpos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        if causal:
            qpos = i * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        if valid_len < s_len:
            # Padded tail keys (S was rounded up to the block size):
            # mask them out; padded *query* rows produce garbage that
            # the host-side slice discards.
            s = jnp.where(kpos < valid_len, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    if causal:
        # Only k-blocks at or before this q-block's diagonal.
        n_blocks = jax.lax.div(i * bq + bq + block_k - 1, block_k)
    else:
        n_blocks = s_len // block_k
    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # lse rides a trailing singleton lane dim: TPU block shapes need the
    # last two dims (sublane, lane) divisible by (8, 128) or equal to
    # the array's — (bq, 1) with array (..., S, 1) satisfies that.
    lse_ref[0, 0] = m + jnp.log(jnp.maximum(l, 1e-30))


def _fwd_call(qt, kt, vt, causal, bq, bk, valid_len, interpret,
              out_f32=False):
    """(o, lse) on padded (B, H, S_pad, hd) / (B, Hkv, S_pad, hd) inputs.

    ``out_f32`` emits o in fp32 — used by the lse variant so a combiner
    (ring attention) folds full-precision partials instead of ones
    already rounded to the compute dtype."""
    B, H, S_pad, hd = qt.shape
    group = H // kt.shape[1]
    kernel = functools.partial(
        _fwd_kernel, causal=causal, sm_scale=1.0 / np.sqrt(hd), block_k=bk,
        valid_len=valid_len)
    return pl.pallas_call(
        kernel,
        grid=(B, H, S_pad // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, S_pad, hd),
                         lambda b, h, i, g=group: (b, h // g, 0, 0)),
            pl.BlockSpec((1, 1, S_pad, hd),
                         lambda b, h, i, g=group: (b, h // g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(
                (B, H, S_pad, hd),
                jnp.float32 if out_f32 else qt.dtype),
            jax.ShapeDtypeStruct((B, H, S_pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)


# -- backward ---------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dlse_ref,
                   dq_ref, *, causal: bool, sm_scale: float, block_k: int,
                   valid_len: int):
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]    # (BQ, 1)
    # Softmax-jacobian diagonal minus the lse output's own cotangent:
    # ds = p * (dp - delta + dlse), since d lse_i / d s_ij = p_ij.
    delta = dl_ref[0, 0] - dlse_ref[0, 0]   # (BQ, 1)
    bq, hd = q.shape
    s_len = k_ref.shape[2]
    i = pl.program_id(2)

    def body(j, acc):
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        kpos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        if causal:
            qpos = i * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        if valid_len < s_len:
            s = jnp.where(kpos < valid_len, s, NEG_INF)
        p = jnp.exp(s - lse)            # masked entries: exp(-huge) = 0
        dp = jax.lax.dot_general(
            do, v, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return acc + jax.lax.dot_general(
            ds, k, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        n_blocks = jax.lax.div(i * bq + bq + block_k - 1, block_k)
    else:
        n_blocks = s_len // block_k
    acc = jax.lax.fori_loop(
        0, n_blocks, body, jnp.zeros((bq, hd), jnp.float32))
    dq_ref[0, 0] = (acc * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dlse_ref,
                    dk_ref, dv_ref, *, causal: bool, sm_scale: float,
                    block_q: int, valid_len: int, group: int):
    k = k_ref[0, 0].astype(jnp.float32)  # (BK, hd)
    v = v_ref[0, 0].astype(jnp.float32)
    bk, hd = k.shape
    s_len = q_ref.shape[2]
    j = pl.program_id(2)

    def body(i, carry):
        dk, dv = carry
        # GQA: this kv head serves `group` q heads — reduce in-kernel.
        for r in range(group):
            q = q_ref[0, r, pl.ds(i * block_q, block_q), :].astype(
                jnp.float32) * sm_scale
            do = do_ref[0, r, pl.ds(i * block_q, block_q), :].astype(
                jnp.float32)
            lse = lse_ref[0, r, pl.ds(i * block_q, block_q), :]   # (BQ, 1)
            delta = (dl_ref[0, r, pl.ds(i * block_q, block_q), :]
                     - dlse_ref[0, r, pl.ds(i * block_q, block_q), :])
            s = jax.lax.dot_general(
                q, k, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # (BQ, BK)
            kpos = j * bk + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            if causal:
                qpos = i * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, bk), 0)
                s = jnp.where(kpos <= qpos, s, NEG_INF)
            if valid_len < s_len:
                s = jnp.where(kpos < valid_len, s, NEG_INF)
            p = jnp.exp(s - lse)
            # Padded q rows have do == 0 (wrapper slice guarantees a
            # zero cotangent), so they contribute nothing here even
            # though their p is degenerate.
            dv = dv + jax.lax.dot_general(
                p, do, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(
                do, v, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - delta)
            dk = dk + jax.lax.dot_general(
                ds, q, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        return dk, dv

    # Causal: a k-block only receives gradient from q-blocks at or
    # after its diagonal.
    i0 = jax.lax.div(j * bk, block_q) if causal else 0
    dk0 = jnp.zeros((bk, hd), jnp.float32)
    dv0 = jnp.zeros((bk, hd), jnp.float32)
    dk, dv = jax.lax.fori_loop(i0, s_len // block_q, body, (dk0, dv0))
    # dk accumulated against scaled q; the remaining sm_scale factor of
    # d(s)/d(k) is already inside q, so no extra scaling here.
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


# -- custom-vjp core on padded, (B, H, S, hd)-transposed operands -----------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(qt, kt, vt, causal, bq, bk, valid_len, interpret, out_f32):
    return _fwd_call(qt, kt, vt, causal, bq, bk, valid_len, interpret,
                     out_f32)


def _flash_fwd(qt, kt, vt, causal, bq, bk, valid_len, interpret, out_f32):
    o, lse = _fwd_call(qt, kt, vt, causal, bq, bk, valid_len, interpret,
                       out_f32)
    return (o, lse), (qt, kt, vt, o, lse)


def _flash_bwd(causal, bq, bk, valid_len, interpret, out_f32, res, ct):
    do, dlse = ct  # dlse is nonzero when the caller consumed lse
    qt, kt, vt, o, lse = res
    B, H, S_pad, hd = qt.shape
    Hkv = kt.shape[1]
    group = H // Hkv
    sm_scale = 1.0 / np.sqrt(hd)
    # delta_i = rowsum(do_i * o_i): the softmax-jacobian diagonal term,
    # elementwise — XLA fuses this; no kernel needed. Trailing singleton
    # lane dim for the same TPU block-shape reason as lse.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
    dlse = dlse.astype(jnp.float32)

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, causal=causal, sm_scale=sm_scale, block_k=bk,
            valid_len=valid_len),
        grid=(B, H, S_pad // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, S_pad, hd),
                         lambda b, h, i, g=group: (b, h // g, 0, 0)),
            pl.BlockSpec((1, 1, S_pad, hd),
                         lambda b, h, i, g=group: (b, h // g, 0, 0)),
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, hd), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qt.shape, qt.dtype),
        interpret=interpret,
    )(qt, kt, vt, do, lse, delta, dlse)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, causal=causal, sm_scale=sm_scale, block_q=bq,
            valid_len=valid_len, group=group),
        grid=(B, Hkv, S_pad // bk),
        in_specs=[
            pl.BlockSpec((1, group, S_pad, hd),
                         lambda b, kv, j: (b, kv, 0, 0)),
            pl.BlockSpec((1, 1, bk, hd), lambda b, kv, j: (b, kv, j, 0)),
            pl.BlockSpec((1, 1, bk, hd), lambda b, kv, j: (b, kv, j, 0)),
            pl.BlockSpec((1, group, S_pad, hd),
                         lambda b, kv, j: (b, kv, 0, 0)),
            pl.BlockSpec((1, group, S_pad, 1),
                         lambda b, kv, j: (b, kv, 0, 0)),
            pl.BlockSpec((1, group, S_pad, 1),
                         lambda b, kv, j: (b, kv, 0, 0)),
            pl.BlockSpec((1, group, S_pad, 1),
                         lambda b, kv, j: (b, kv, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, hd), lambda b, kv, j: (b, kv, j, 0)),
            pl.BlockSpec((1, 1, bk, hd), lambda b, kv, j: (b, kv, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(kt.shape, kt.dtype),
            jax.ShapeDtypeStruct(vt.shape, vt.dtype),
        ],
        interpret=interpret,
    )(qt, kt, vt, do, lse, delta, dlse)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


# -- public API -------------------------------------------------------------


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def plan_blocks(S: int, block_q: int, block_k: int) -> tuple[int, int, int]:
    """Mosaic-safe (bq, bk, S_pad) for the position dim.

    The hardware contract this encodes (r5 stage-2 on-chip finding —
    interpret mode accepts violations, Mosaic rejects them):
    position-dim loads index in sublane units of 8, so bq (the score
    tile's sublane dim) and every load offset must be a multiple of 8;
    bk lands in the score tile's LANE dim, where the module keeps the
    stricter full-lane contract its knob validator already asserts
    (``_tile_checked`` mult=128 for K — only chip-validated at 128,
    so the planner never emits less).  A short or ragged S therefore
    pads UP to a 128-multiple tile rather than clamping blocks down to
    S (S=127 clamped bq/bk to 127 and Mosaic refused the 127-row
    loads).  Invariants (pinned host-side by
    tests/test_attention.py::test_plan_blocks_mosaic_contract):
    bq % 8 == 0; bk % 128 == 0; S_pad >= S; S_pad % bq == S_pad % bk
    == 0.
    """
    s_tile = _round_up(max(S, 1), 128)
    # API callers may pass any positive block knob; round up to each
    # dim's quantum before fitting (the env knobs are pre-validated by
    # _tile_checked, this covers direct callers).
    bk = min(_round_up(max(block_k, 1), 128), s_tile)
    bq = min(_round_up(max(block_q, 1), 8), s_tile)
    # Mutual divisibility so one S_pad serves both grids: bq above bk
    # rounds down to a bk multiple; bq below bk rounds down to a
    # multiple-of-8 divisor of bk (floor 8 — bk is a 128 multiple).
    if bq >= bk:
        bq = (bq // bk) * bk
    else:
        while bk % bq:
            bq -= 8
    S_pad = _round_up(S, max(bq, bk))
    return bq, bk, S_pad


def _flash_padded(q, k, v, causal, block_q, block_k, interpret,
                  out_f32=False):
    """Shared pad/transpose plumbing; returns ((B,S,H,hd) o, (B,S,H,1)
    lse) with padding removed."""
    B, S, H, hd = q.shape
    Hkv = k.shape[2]
    if H % Hkv:
        raise ValueError(f"H={H} not a multiple of Hkv={Hkv}")
    bq, bk, S_pad = plan_blocks(S, block_q, block_k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    if S_pad != S:
        pad = [(0, 0), (0, S_pad - S), (0, 0), (0, 0)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)

    # (B, H, S, hd) layout: heads become a grid dimension.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    o, lse = _flash(qt, kt, vt, causal, bq, bk, S, interpret, out_f32)
    return (o[:, :, :S].transpose(0, 2, 1, 3),
            lse[:, :, :S].transpose(0, 2, 1, 3))


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret"))
def flash_attention(
    q: jax.Array,  # (B, S, H, hd)
    k: jax.Array,  # (B, S, Hkv, hd)
    v: jax.Array,  # (B, S, Hkv, hd)
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool | None = None,
) -> jax.Array:
    """Returns (B, S, H, hd). GQA: H must be a multiple of Hkv.

    Differentiable (custom VJP with Pallas backward kernels). Any S is
    accepted: a ragged tail (e.g. the S-1 of next-token training) is
    zero-padded up to the block size inside this wrapper; padded keys
    are masked in-kernel and padded query rows sliced off.
    """
    return _flash_padded(q, k, v, causal, block_q, block_k, interpret)[0]


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret"))
def flash_attention_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Like :func:`flash_attention` but also returns the per-row
    logsumexp, shape (B, S, H, 1) fp32 — the combiner state that lets a
    caller fold independently-computed attention partials (ring
    attention folds one of these per rotating k/v chunk). The lse
    output participates in autodiff (its cotangent feeds the ds term
    in the backward kernels). o is emitted in fp32 so the caller's
    fold accumulates at full precision regardless of compute dtype."""
    return _flash_padded(q, k, v, causal, block_q, block_k, interpret,
                         out_f32=True)
