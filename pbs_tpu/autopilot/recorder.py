"""Shadow-trace capture: live gateway traffic as a replayable asset.

The autopilot loop's first stage (docs/AUTOPILOT.md): continuously
record what the serving tier is actually being asked to do — arrival
times, tenant, SLO class, cost — into a bounded ring, cheap enough to
leave on forever. A captured **window** is then a pure value: it
replays in background sim (autopilot/shadow.py) under any candidate
knob setting, byte-stably, because the capture carries everything a
stand-alone re-schedule needs (the tenant admission contracts ride
along) and nothing host-dependent.

Design rules, inherited from the trace/sweep substrate:

- **Observer only.** ``on_submit`` is four scalar stores into
  preallocated arrays; the recorder draws no randomness and consults
  no fault streams, so arming it moves no digest.
- **Bounded ring retention.** A long-lived gateway overwrites its
  oldest capture instead of growing; ``dropped`` counts what aged out
  (the same graceful degradation as a full trace ring).
- **Canonical bytes.** Windows serialize through the sim trace's
  canonical JSON (``sim/trace.dumps_canonical``) — sorted keys, no
  whitespace, ints only — so ``digest()`` is stable across hosts and
  the record→replay roundtrip test can pin byte equality.
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from pbs_tpu.sim.trace import dumps_canonical

SHADOW_SCHEMA_VERSION = 1


@dataclasses.dataclass(frozen=True)
class ShadowWindow:
    """One captured traffic window, self-contained and replayable.

    ``arrivals`` are ``(t_rel_ns, tenant, cls, cost)`` tuples in
    capture order, times relative to ``t0_ns``. ``tenants`` maps
    tenant name -> admission contract (``rate``/``burst``/``weight``/
    ``slo``/``max_queued``) — the quota the live tier enforced, so the
    replay admits under the same law.
    """

    t0_ns: int
    t1_ns: int
    arrivals: tuple[tuple[int, str, str, int], ...]
    tenants: dict[str, dict]
    dropped: int = 0

    def lines(self) -> list[str]:
        """Canonical JSONL encoding (meta line first, then one line
        per arrival) — what ``save`` writes and ``digest`` hashes."""
        out = [dumps_canonical({
            "kind": "shadow-meta", "v": SHADOW_SCHEMA_VERSION,
            "t0_ns": int(self.t0_ns), "t1_ns": int(self.t1_ns),
            "dropped": int(self.dropped),
            "tenants": {t: dict(sorted(m.items()))
                        for t, m in sorted(self.tenants.items())},
        })]
        out.extend(dumps_canonical({
            "kind": "arrival", "t": int(t), "tenant": tenant,
            "cls": cls, "cost": int(cost)})
            for t, tenant, cls, cost in self.arrivals)
        return out

    def digest(self) -> str:
        h = hashlib.sha256()
        for ln in self.lines():
            h.update(ln.encode())
            h.update(b"\n")
        return h.hexdigest()

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            for ln in self.lines():
                f.write(ln + "\n")

    @classmethod
    def load(cls, path: str) -> "ShadowWindow":
        import json

        meta = None
        arrivals: list[tuple[int, str, str, int]] = []
        with open(path) as f:
            for ln in f:
                if not ln.strip():
                    continue
                rec = json.loads(ln)
                if rec.get("kind") == "shadow-meta":
                    meta = rec
                elif rec.get("kind") == "arrival":
                    arrivals.append((int(rec["t"]), rec["tenant"],
                                     rec["cls"], int(rec["cost"])))
        if meta is None:
            raise ValueError(f"{path}: no shadow-meta record")
        if meta.get("v") != SHADOW_SCHEMA_VERSION:
            raise ValueError(
                f"{path}: shadow schema v{meta.get('v')!r} != "
                f"{SHADOW_SCHEMA_VERSION}")
        return cls(t0_ns=int(meta["t0_ns"]), t1_ns=int(meta["t1_ns"]),
                   arrivals=tuple(arrivals),
                   tenants={t: dict(m)
                            for t, m in meta["tenants"].items()},
                   dropped=int(meta.get("dropped", 0)))


class ShadowRecorder:
    """Bounded ring of live arrivals + the tenant contracts to replay
    them. Attach with ``Gateway.attach_shadow`` /
    ``FederatedGateway.attach_shadow``; the submit seam calls
    :meth:`on_submit` once per arrival (admitted or shed — sheds are an
    admission *outcome*; the workload is arrivals)."""

    def __init__(self, capacity: int = 1 << 15):
        if capacity < 1:
            raise ValueError("ShadowRecorder needs capacity >= 1")
        self.capacity = int(capacity)
        self._t = np.zeros(self.capacity, dtype=np.int64)
        self._tenant = np.zeros(self.capacity, dtype=np.int32)
        self._cls = np.zeros(self.capacity, dtype=np.int8)
        self._cost = np.zeros(self.capacity, dtype=np.int32)
        self._n = 0  # total ever recorded; head = n % capacity
        self._tenant_ix: dict[str, int] = {}
        self._tenant_names: list[str] = []
        self.tenant_meta: dict[str, dict] = {}
        #: SLO-class interning is fixed (two classes), index matches
        #: gateway.admission.SLO_CLASSES order for trace-friendliness.
        self._cls_ix = {"interactive": 0, "batch": 1}
        self._cls_names = ("interactive", "batch")

    # -- producers -------------------------------------------------------

    def note_tenant(self, tenant: str, quota) -> None:
        """Capture the admission contract a replay must enforce. Duck-
        typed on the TenantQuota surface; idempotent (last write
        wins, matching live re-registration)."""
        self.tenant_meta[tenant] = {
            "rate": float(quota.rate),
            "burst": float(quota.burst),
            "weight": int(quota.weight),
            "slo": str(quota.slo),
            "max_queued": int(quota.max_queued),
        }

    def on_submit(self, now_ns: int, tenant: str, cls: str,
                  cost: int) -> None:
        i = self._n % self.capacity
        self._t[i] = now_ns
        ti = self._tenant_ix.get(tenant)
        if ti is None:
            ti = self._tenant_ix[tenant] = len(self._tenant_names)
            self._tenant_names.append(tenant)
        self._tenant[i] = ti
        self._cls[i] = self._cls_ix.get(cls, 1)
        self._cost[i] = cost
        self._n += 1

    # -- consumers -------------------------------------------------------

    @property
    def recorded(self) -> int:
        return self._n

    @property
    def dropped(self) -> int:
        """Arrivals that aged out of the ring (bounded retention)."""
        return max(0, self._n - self.capacity)

    def window(self, t0_ns: int | None = None,
               t1_ns: int | None = None) -> ShadowWindow:
        """The retained arrivals in capture order, optionally clipped
        to ``[t0_ns, t1_ns)``. Self-contained: the result carries the
        tenant contracts seen so far."""
        n = min(self._n, self.capacity)
        if n == 0:
            return ShadowWindow(t0_ns=0, t1_ns=0, arrivals=(),
                                tenants=dict(self.tenant_meta),
                                dropped=self.dropped)
        if self._n > self.capacity:
            head = self._n % self.capacity
            order = np.concatenate([np.arange(head, self.capacity),
                                    np.arange(0, head)])
        else:
            order = np.arange(0, n)
        ts = self._t[order]
        keep = np.ones(n, dtype=bool)
        if t0_ns is not None:
            keep &= ts >= int(t0_ns)
        if t1_ns is not None:
            keep &= ts < int(t1_ns)
        order = order[keep]
        ts = self._t[order]
        lo = int(ts[0]) if len(ts) else int(t0_ns or 0)
        lo = int(t0_ns) if t0_ns is not None else lo
        hi = int(t1_ns) if t1_ns is not None else \
            (int(ts[-1]) + 1 if len(ts) else lo)
        arrivals = tuple(
            (int(self._t[i]) - lo,
             self._tenant_names[int(self._tenant[i])],
             self._cls_names[int(self._cls[i])],
             int(self._cost[i]))
            for i in order.tolist())
        return ShadowWindow(t0_ns=lo, t1_ns=hi, arrivals=arrivals,
                            tenants=dict(self.tenant_meta),
                            dropped=self.dropped)
