"""The autopilot orchestrator: record → search → canary → converge.

Composes the three mechanisms into the paper's closed loop at serving
scale (ROADMAP 4; MaLV-OS arXiv 2508.03676 — background simulation as
the decision substrate, production only ever sees guarded deltas):

1. a :class:`~pbs_tpu.autopilot.recorder.ShadowRecorder` captures the
   federation's live traffic;
2. after ``min_record_ns`` of capture, :func:`~pbs_tpu.autopilot
   .shadow.shadow_search` proposes a candidate knob profile (tuned-
   profile space, paired seeds, margin against the live config);
3. a candidate clearing the margin gate rolls out through
   :class:`~pbs_tpu.autopilot.canary.CanaryRollout` — scoped push to a
   member subset, SLO-burn guard window, promote or automatic
   rollback.

The pilot is pumped from the owner's loop (``tick()`` after each
federation pump round), holds no thread, and consumes no randomness of
its own — every decision is a pure function of (captured traffic,
knob state, fault plan), which is what lets the chaos harness pin the
whole loop's response with golden digests. The **adversarial seam**
sits exactly where a buggy or compromised scorer would: after the
shadow search, the ``autopilot.candidate`` fault point may replace the
proposal with :data:`~pbs_tpu.autopilot.canary.PATHOLOGICAL_PARAMS`
claiming a winning margin — the registry cannot reject it (every value
is in-range), so the canary guard is the line that must hold, and the
chaos gate proves it does.
"""

from __future__ import annotations

import dataclasses

from pbs_tpu import knobs
from pbs_tpu.autopilot.canary import PATHOLOGICAL_PARAMS, CanaryRollout
from pbs_tpu.autopilot.recorder import ShadowRecorder
from pbs_tpu.autopilot.shadow import shadow_search
from pbs_tpu.faults import injector as _faults
from pbs_tpu.knobs.channel import KnobChannel
from pbs_tpu.knobs.profile import PARAM_KNOBS, knobs_to_params
from pbs_tpu.obs.trace import Ev


@dataclasses.dataclass
class AutopilotConfig:
    """Loop constants; defaults are the declared registry knobs
    (``autopilot.*``, docs/KNOBS.md) so a deployment retunes the loop
    the same way it retunes anything else."""

    policy: str = "feedback"
    # None = the declared registry default. None (not <=0) on purpose:
    # 0 is a VALID declared value for switch_cost_ns ("model off") and
    # burn_limit (strictest guard), and must stay reachable.
    min_record_ns: int | None = None
    guard_window_ns: int | None = None
    burn_limit: float | None = None
    score_margin_x1e6: int | None = None
    canary_members: int | None = None
    min_guard_samples: int | None = None
    switch_cost_ns: int | None = None
    quick: bool = True
    max_rounds: int = 1

    def __post_init__(self) -> None:
        d = knobs.default
        for field in ("min_record_ns", "guard_window_ns", "burn_limit",
                      "score_margin_x1e6", "canary_members",
                      "min_guard_samples", "switch_cost_ns"):
            if getattr(self, field) is None:
                setattr(self, field, d(f"autopilot.{field}"))


class Autopilot:
    """One self-tuning loop over one federation.

    ``channel`` is the WRITER end of the knob channel this loop owns —
    the only process allowed to push (the ``rollout-discipline`` pass
    enforces that every production push lives in the canary path).
    Arming wires the whole stack: shadow capture at the submit
    surface, per-member knob watchers (scoped canary adoption), and
    the member profile model.
    """

    def __init__(self, fed, channel: KnobChannel,
                 config: AutopilotConfig | None = None,
                 recorder: ShadowRecorder | None = None):
        self.fed = fed
        self.channel = channel
        self.config = config or AutopilotConfig()
        self.recorder = recorder or ShadowRecorder()
        fed.attach_shadow(self.recorder)
        # Members adopt through their own member-keyed watchers; the
        # profile model re-rates their backends on adoption.
        for gw in fed.members.values():
            gw.profile_switch_cost_ns = self.config.switch_cost_ns
        reader = KnobChannel.attach(channel.path)
        fed.attach_knobs(reader, per_member=True)
        self.canary = CanaryRollout(
            fed, channel, policy=self.config.policy,
            guard_window_ns=self.config.guard_window_ns,
            burn_limit=self.config.burn_limit,
            min_guard_samples=self.config.min_guard_samples,
            canary_members=self.config.canary_members)
        self.state = "recording"  # recording | canary | done
        self.rounds = 0
        self.history: list[dict] = []
        self._t0 = fed.clock.now_ns()

    # -- the pump --------------------------------------------------------

    def tick(self) -> dict | None:
        """One loop step on the federation's timeline; returns the
        decision event it produced this step (if any). Call after the
        federation's own ``tick()`` — candidates then see a settled
        pump round, and pushed knobs adopt at the members' next round
        (the KnobWatcher determinism contract)."""
        now = self.fed.clock.now_ns()
        # Late joiners (the rejoin path) must speak the profile model:
        # their watcher primed at attach, BEFORE this pilot could arm
        # the switch cost, so the prime adoption skipped the backend
        # re-rate. Arm the constant and re-apply the already-adopted
        # profile so the joiner carries the same overhead as its peers
        # from this tick on — otherwise it serves measurably faster
        # and skews any later guard evidence it hosts.
        for gw in self.fed.members.values():
            if gw.profile_switch_cost_ns != self.config.switch_cost_ns:
                gw.profile_switch_cost_ns = self.config.switch_cost_ns
                if gw.applied_knobs:
                    gw.apply_member_knobs(dict(gw.applied_knobs),
                                          dict(gw.applied_knobs))
        if self.state == "recording":
            if now - self._t0 < self.config.min_record_ns:
                return None
            return self._propose(now)
        if self.state == "canary":
            decision = self.canary.poll(now)
            if decision is None:
                return None
            self.history.append(decision)
            self.rounds += 1
            if self.rounds >= self.config.max_rounds:
                self.state = "done"
            else:
                self.state = "recording"
                self._t0 = now
            return decision
        return None

    def _live_params(self) -> dict:
        """What production currently runs: the channel's profile-knob
        values mapped back to constructor params."""
        _, values = self.channel.snapshot()
        names = set(PARAM_KNOBS[self.config.policy].values())
        return knobs_to_params(
            self.config.policy,
            {n: v for n, v in values.items() if n in names})

    def _propose(self, now: int) -> dict:
        window = self.recorder.window()
        proposal = shadow_search(
            window, live_params=self._live_params(),
            policy=self.config.policy, quick=self.config.quick)
        injected = False
        f = _faults.consult("autopilot.candidate", proposal["workload"])
        if f is not None and f.fault == "pathological":
            # The adversarial seam: a compromised scorer recommends a
            # catastrophic profile and LIES about its margin. Every
            # value is inside the registry's safe ranges — only the
            # canary guard stands between this and the fleet.
            injected = True
            claimed = (proposal["live_score_x1e6"]
                       + self.config.score_margin_x1e6 + 1)
            proposal = {
                **proposal,
                "candidate": dict(PATHOLOGICAL_PARAMS),
                "candidate_score_x1e6": claimed,
                "margin_x1e6": claimed - proposal["live_score_x1e6"],
            }
        event = {"event": "propose", "t_ns": int(now),
                 "injected": injected, **proposal}
        self.history.append(event)
        if self.fed.spans is not None:
            # Scores can be negative: the args ride the ring's u64
            # words as i64 two's complement (the EmitBatch mask), so
            # a decoder reading them signed recovers the real margin
            # — a losing candidate must not audit as a huge win.
            self.fed.spans.emit_event(
                int(now), Ev.AP_PROPOSE,
                proposal["candidate_score_x1e6"],
                proposal["live_score_x1e6"],
                proposal["margin_x1e6"],
                int(injected))
        if proposal["margin_x1e6"] <= self.config.score_margin_x1e6:
            # No measured win worth a rollout: stay on the live config
            # (the tuner's ties-to-reference discipline, applied live).
            self.history.append({"event": "hold", "t_ns": int(now),
                                 "margin_x1e6":
                                     proposal["margin_x1e6"]})
            self.rounds += 1
            self.state = ("done" if self.rounds >= self.config.max_rounds
                          else "recording")
            self._t0 = now
            return self.history[-1]
        canary_ev = self.canary.start(proposal["candidate"], now)
        if canary_ev is None:
            # No live member can host the canary (chaos drained or
            # partitioned everyone): defer — nothing was pushed,
            # production stays on the live config.
            self.history.append({"event": "hold", "t_ns": int(now),
                                 "reason": "no-canary-member"})
            self.rounds += 1
            self.state = ("done" if self.rounds >= self.config.max_rounds
                          else "recording")
            self._t0 = now
            return self.history[-1]
        self.history.append(canary_ev)
        self.state = "canary"
        return canary_ev

    # -- observability ---------------------------------------------------

    def report(self) -> dict:
        """Full loop report (the ``pbst autopilot run`` artifact):
        status + the decision history + per-member adopted knobs.
        Stable key order, ints and 4-dp floats only — byte-stable
        under ``json.dumps(sort_keys=True)`` for a seeded run."""
        return {
            "version": 1,
            "status": self.status(),
            "history": [dict(e) for e in self.history],
            "knob_adoptions": [dict(a) for a in
                               self.fed.knob_adoptions],
            "members": {
                name: dict(sorted(gw.applied_knobs.items()))
                for name, gw in sorted(self.fed.members.items())
            },
        }

    def status(self) -> dict:
        """Stable summary (the ``pbst autopilot status`` surface)."""
        decisions = [e["event"] for e in self.history]
        return {
            "state": self.state,
            "rounds": self.rounds,
            "recorded_arrivals": self.recorder.recorded,
            "dropped_arrivals": self.recorder.dropped,
            "decisions": decisions,
            "canary_members": list(self.canary.members),
            "reference": dict(self.canary.reference),
            "adoptions": len(self.fed.knob_adoptions),
        }


# -- the demo loop (pbst autopilot run --demo) -------------------------------


def run_autopilot_demo(seed: int = 0, ticks: int = 260,
                       tick_ns: int = 1_000_000,
                       pathological: bool = False) -> dict:
    """One self-contained, seeded end-to-end loop on a virtual clock:
    3-member federation, catalog-derived arrivals, shadow capture →
    quick search → canary → promote/hold (or, with ``pathological``,
    an injected bad candidate → guarded rollback). Deterministic:
    same args ⇒ byte-identical report. The tier-1 CLI smoke budget is
    ≤ 5 s; the quick search dominates (~1 s on the Python witness)."""
    import shutil
    import tempfile

    from pbs_tpu.faults import injector as faults_mod
    from pbs_tpu.faults.plan import FaultPlan, FaultSpec
    from pbs_tpu.gateway.chaos import (
        _federation_member,
        catalog_arrivals,
        draw_arrival,
        quota_for,
    )
    from pbs_tpu.gateway.federation import FederatedGateway
    from pbs_tpu.sim.workload import build_workload
    from pbs_tpu.utils.clock import VirtualClock

    plan = FaultPlan(seed=seed, specs=(
        (FaultSpec("autopilot.candidate", "pathological", p=1.0,
                   times=1),) if pathological else ()))
    faults_mod.install(plan)
    knob_dir = tempfile.mkdtemp(prefix="pbst-autopilot-demo-")
    try:
        clock = VirtualClock()
        members = [_federation_member(f"gw{i}", i, clock, tick_ns,
                                      seed, n_backends=2, n_tenants=4)
                   for i in range(3)]
        fed = FederatedGateway(members, clock=clock,
                               renew_period_ns=4 * tick_ns,
                               lease_ttl_ns=6 * tick_ns)
        tenants = build_workload("mixed", seed=seed, n_tenants=4)
        for t in tenants:
            fed.register_tenant(t.name,
                                quota_for(t.name, t.slo,
                                          t.params.weight))
        arrivals = catalog_arrivals(tenants, seed, tag=17)
        writer = KnobChannel.create(f"{knob_dir}/knobs.led")
        # The guard-sizing rule (docs/AUTOPILOT.md): the window must
        # exceed the tightest SLO target with real margin, or
        # in-window requests cannot age past it and burn evidence
        # starves.
        cfg = AutopilotConfig(
            min_record_ns=(ticks // 3) * tick_ns,
            guard_window_ns=(ticks // 3) * tick_ns,
            quick=True, max_rounds=1)
        pilot = Autopilot(fed, writer, config=cfg)
        for tick in range(int(ticks)):
            for t in tenants:
                fire, cost = draw_arrival(t, arrivals[t.name])
                if fire:
                    fed.submit(t.name, {"tick": tick}, cost=cost)
            fed.tick()
            pilot.tick()
            clock.advance(tick_ns)
        for _ in range(int(ticks) * 6):
            if not fed.busy():
                break
            fed.tick()
            pilot.tick()
            clock.advance(tick_ns)
        report = pilot.report()
        report["stats"] = {
            "admitted": fed.admitted, "completed": fed.completed,
            "drained": not fed.busy(),
        }
        report["pathological"] = bool(pathological)
        report["seed"] = int(seed)
        report["ticks"] = int(ticks)
        return report
    finally:
        faults_mod.uninstall()
        shutil.rmtree(knob_dir, ignore_errors=True)
