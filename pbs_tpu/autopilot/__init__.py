"""pbs_tpu.autopilot — shadow-replay self-tuning with SLO-guarded
canary knob rollout (docs/AUTOPILOT.md; ROADMAP 4).

The paper's feedback loop, closed at serving scale: continuously
record live gateway traffic (``recorder``), re-schedule captured
windows in background sim under candidate knob settings from the
tuned-profile space (``shadow``), and roll a winning candidate out
through the knob channel as a canary on a subset of federation
members — SLO-burn-rate guarded, automatically rolled back, every
decision span-traced and digest-covered (``canary``, ``pilot``).
Production only ever sees guarded deltas; a pathological
recommendation degrades to the reference profile, never to an outage
(the ``pbst chaos`` federation harness gates it).

jax-free and deterministic under injected clocks, like the gateway
tier it steers.
"""

from pbs_tpu.autopilot.canary import (  # noqa: F401
    PATHOLOGICAL_PARAMS,
    CanaryRollout,
)
from pbs_tpu.autopilot.pilot import (  # noqa: F401
    Autopilot,
    AutopilotConfig,
    run_autopilot_demo,
)
from pbs_tpu.autopilot.recorder import (  # noqa: F401
    ShadowRecorder,
    ShadowWindow,
)
from pbs_tpu.autopilot.shadow import (  # noqa: F401
    classify_window,
    reference_params,
    replay_window,
    shadow_search,
    window_seed,
)

__all__ = [
    "Autopilot", "AutopilotConfig", "CanaryRollout",
    "PATHOLOGICAL_PARAMS", "ShadowRecorder", "ShadowWindow",
    "classify_window", "reference_params", "replay_window",
    "run_autopilot_demo", "shadow_search", "window_seed",
]
