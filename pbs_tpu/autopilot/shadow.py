"""Shadow replay + candidate search: the background-sim half of the
autopilot loop (docs/AUTOPILOT.md).

Two instruments over a captured :class:`~pbs_tpu.autopilot.recorder
.ShadowWindow`:

- :func:`replay_window` — re-schedule the captured traffic through a
  fresh, stand-alone serving stack (Gateway + SimServeBackends on a
  virtual clock). Deterministic by construction: the window IS the
  workload, every noise source is a seeded generator, every float in
  the report is pre-rounded — so replaying the same window twice is
  byte-identical, and replaying a window captured from an identically
  configured gateway reproduces its admission/completion counts
  exactly (the record→replay roundtrip test pins both). ``knob_values``
  arms the member profile model, so "what would this window have
  looked like under candidate C" is a measurable what-if.
- :func:`shadow_search` — the candidate proposer: classify the window
  into a tuned workload class, run the ``sched/tune`` successive-
  halving search over that class (the tuned-profile space), then score
  the winner HEAD-TO-HEAD against the live config on one paired grid
  (``tune.evaluate_params``: cell seeds derive from workload identity
  only, so live and candidate replay the identical realization and
  the margin is pure policy signal). The proposal's base seed derives
  from the window digest — the whole search is a pure function of the
  captured traffic.

The canary controller (autopilot/canary.py) consumes the proposal; a
candidate only ever reaches production through its guarded rollout.
"""

from __future__ import annotations

import numpy as np

from pbs_tpu.autopilot.recorder import ShadowWindow
from pbs_tpu.gateway.admission import TenantQuota
from pbs_tpu.gateway.backends import SimServeBackend
from pbs_tpu.gateway.gateway import Gateway
from pbs_tpu.knobs.profile import PARAM_KNOBS, knobs_to_params
from pbs_tpu.sched import tune
from pbs_tpu.sim.sweep import seed_from_digest
from pbs_tpu.utils.clock import MS, VirtualClock


def window_seed(window: ShadowWindow, salt: int = 0) -> int:
    """Base seed derived from the capture itself (the sweep seed
    space): the shadow search is a pure function of the recorded
    traffic (same window ⇒ same candidate), independent of wall clock
    or host."""
    return seed_from_digest(window.digest(), salt)


def reference_params(policy: str = "feedback") -> dict:
    """The reference profile as constructor params: the registry's
    declared defaults mapped through the profile bijection — what the
    tree ships, and what a rollback restores."""
    from pbs_tpu.knobs import registry

    return knobs_to_params(policy, {
        k: registry.default(k) for k in PARAM_KNOBS[policy].values()})


# -- window replay -----------------------------------------------------------


def replay_window(window: ShadowWindow, seed: int = 0,
                  n_backends: int = 2, slots_per_backend: int = 2,
                  service_ns_per_cost: int = 3 * MS,
                  tick_ns: int = 1 * MS,
                  knob_values: dict | None = None,
                  switch_cost_ns: int = 0,
                  max_queued: int | None = None) -> dict:
    """Re-schedule a captured window through a stand-alone gateway sim;
    returns the byte-stable report (ints and pre-rounded floats only).

    The replay shape mirrors one federation member (the chaos
    harness's member geometry is the default); ``knob_values`` +
    ``switch_cost_ns`` arm the serving profile model exactly as a
    member adopting those knobs would (``Gateway.apply_member_knobs``),
    so candidate what-ifs and live members speak the same model."""
    clock = VirtualClock()
    backends = [
        SimServeBackend(f"sb{i}", n_slots=slots_per_backend,
                        service_ns_per_cost=service_ns_per_cost,
                        seed=int(seed) * 1009 + i)
        for i in range(max(1, int(n_backends)))
    ]
    n_tenants = max(1, len(window.tenants))
    gw = Gateway(backends, clock=clock,
                 max_queued=(max_queued if max_queued is not None
                             else 64 * n_tenants),
                 name="shadow")
    for tenant, m in sorted(window.tenants.items()):
        gw.register_tenant(tenant, TenantQuota(
            rate=m["rate"], burst=m["burst"], weight=m["weight"],
            slo=m["slo"], max_queued=m["max_queued"]), now_ns=0)
    if knob_values and switch_cost_ns > 0:
        gw.profile_switch_cost_ns = int(switch_cost_ns)
        gw.apply_member_knobs(dict(knob_values), dict(knob_values))

    horizon = max(int(window.t1_ns) - int(window.t0_ns), 1)
    n_ticks = -(-horizon // int(tick_ns))  # ceil
    arrivals = window.arrivals
    ai, n_arrivals = 0, len(arrivals)
    admitted = completed = shed = 0
    per_tenant: dict[str, dict[str, int]] = {
        t: {"admitted": 0, "completed": 0, "shed": 0}
        for t in sorted(window.tenants)}

    def _bump(tenant: str, key: str) -> None:
        row = per_tenant.get(tenant)
        if row is None:
            row = per_tenant[tenant] = {"admitted": 0, "completed": 0,
                                        "shed": 0}
        row[key] += 1

    for k in range(n_ticks):
        end = (k + 1) * int(tick_ns)
        while ai < n_arrivals and arrivals[ai][0] < end:
            _, tenant, cls, cost = arrivals[ai]
            r = gw.submit(tenant, None, cost=cost, slo=cls)
            if r.admitted:
                admitted += 1
                _bump(tenant, "admitted")
            else:
                shed += 1
                _bump(tenant, "shed")
            ai += 1
        for rid, info in gw.tick():
            completed += 1
            _bump(info["tenant"], "completed")
        clock.advance(int(tick_ns))

    # Drain (bounded): the captured window must account completely.
    for _ in range(max(64, n_ticks * 8)):
        if not gw.busy():
            break
        for rid, info in gw.tick():
            completed += 1
            _bump(info["tenant"], "completed")
        clock.advance(int(tick_ns))

    tenants_out = {}
    for tenant in sorted(per_tenant):
        m = window.tenants.get(tenant, {})
        cls = m.get("slo", "batch")
        tenants_out[tenant] = {
            **per_tenant[tenant],
            "e2e_p50_ns": gw.hist.quantile(tenant, cls, "e2e", 0.50),
            "e2e_p99_ns": gw.hist.quantile(tenant, cls, "e2e", 0.99),
        }
    return {
        "window_digest": window.digest(),
        "seed": int(seed),
        "arrivals": n_arrivals,
        "admitted": admitted,
        "completed": completed,
        "shed": shed,
        "drained": not gw.busy(),
        "tenants": tenants_out,
    }


# -- workload classification -------------------------------------------------


def classify_window(window: ShadowWindow) -> str:
    """Map a captured window onto the tuned workload class whose
    profile space the candidate search explores. First-order and
    deterministic (documented in docs/AUTOPILOT.md):

    - interactive-dominated traffic (≥ 75 % of arrivals) with bursty
      inter-arrivals (CV > 1.0) → ``serving``; steadier → ``stable``
    - batch-dominated traffic (≤ 25 % interactive) → ``contended``
      (sustained heavyweight work is the shrink-pressure class)
    - anything in between → ``mixed``

    An empty window is ``mixed`` (the widest profile).
    """
    arr = window.arrivals
    if not arr:
        return "mixed"
    n = len(arr)
    inter = sum(1 for _, _, cls, _ in arr if cls == "interactive")
    frac = inter / n
    ts = np.diff(np.array([t for t, _, _, _ in arr], dtype=np.int64))
    ts = ts[ts > 0]
    cv = (float(ts.std() / ts.mean()) if len(ts) and ts.mean() > 0
          else 0.0)
    if frac >= 0.75:
        return "serving" if cv > 1.0 else "stable"
    if frac <= 0.25:
        return "contended"
    return "mixed"


# -- candidate search --------------------------------------------------------


def shadow_search(window: ShadowWindow, live_params: dict | None = None,
                  policy: str = "feedback", quick: bool = True,
                  workers: int = 1, base_seed: int | None = None) -> dict:
    """Propose a candidate for a captured window; returns the proposal
    (all scores x1e6 ints — byte-stable). ``live_params`` is the
    config production currently runs (default: the reference profile);
    the ``margin_x1e6`` is candidate-minus-live on one paired grid, so
    a candidate only clears the rollout gate by beating the live
    config on the identical workload realization."""
    wl = classify_window(window)
    digest = window.digest()  # once: a full ring is a real hash
    if base_seed is None:
        base_seed = seed_from_digest(digest)
    live = dict(live_params) if live_params else reference_params(policy)
    space = (tune.QUICK_SPACE if quick else tune.SEARCH_SPACE)[policy]
    rungs = tune.QUICK_RUNGS if quick else tune.RUNGS
    frontier = tune.successive_halving(
        wl, policy=policy, configs=space, rungs=rungs,
        base_seed=base_seed, workers=workers)
    candidate = dict(frontier["winner"]["params"])
    live_score, cand_score = tune.evaluate_params(
        wl, policy, [live, candidate], base_seed=base_seed,
        workers=workers)
    return {
        "workload": wl,
        "policy": policy,
        "base_seed": int(base_seed),
        "window_digest": digest,
        "arrivals": len(window.arrivals),
        "candidate": candidate,
        "live": live,
        "candidate_score_x1e6": int(round(cand_score * 1e6)),
        "live_score_x1e6": int(round(live_score * 1e6)),
        "margin_x1e6": int(round((cand_score - live_score) * 1e6)),
    }
