"""SLO-guarded canary knob rollout: the only door to production.

A shadow-search candidate (autopilot/shadow.py) never touches every
member at once. It rolls out through the knob channel as a **scoped
push** to a canary subset of federation members
(``KnobChannel.push(..., scope=members)``; the per-member adoption
filter in ``KnobWatcher`` keeps it off everyone else), then the
controller watches **per-tenant SLO burn rate** at the canary members
over a guard window:

- every watched tenant stays under the burn limit → **promote**: one
  global push of the candidate values clears the scope and converges
  every member;
- any tenant burns past the limit (or a canary member disappears) →
  **rollback**: one global push of the *reference* values restores the
  canary members — non-canary members never adopted the candidate, so
  the same push is a no-op for them — and production is back on the
  profile it trusted.

Burn is measured delta-style from the canary members' own completion
records (``Gateway.completions`` — exact per-request e2e latencies,
not log2 buckets: a rollback tripwire must not let a pathology hide
inside the histogram bucket the target shares; the members' log2
histograms remain the cheap always-on surface, and
``LatencyHistograms.over_target`` its bucket-conservative reader).
The guard snapshots each member's completion count at rollout and
judges only what completed inside the window. Everything is
deterministic under a virtual clock: same seed ⇒ same burns ⇒ same
verdict, which is what lets the chaos harness pin rollback decisions
with golden digests.

This module is the sanctioned writer the ``rollout-discipline`` check
pass (docs/ANALYSIS.md) enforces: production code pushing knobs
anywhere else is a CI finding.
"""

from __future__ import annotations

from pbs_tpu import knobs
from pbs_tpu.knobs.profile import PARAM_KNOBS, params_to_knobs
from pbs_tpu.obs.spans import DEFAULT_SLO_TARGET_NS, SLO_OBJECTIVE
from pbs_tpu.obs.trace import Ev

#: Rollback reason codes (the AP_ROLLBACK trace arg).
ROLLBACK_BURN = 1
ROLLBACK_MEMBER_LOST = 2
ROLLBACK_NO_EVIDENCE = 3
_REASON_CODES = {"burn": ROLLBACK_BURN,
                 "member-lost": ROLLBACK_MEMBER_LOST,
                 "no-evidence": ROLLBACK_NO_EVIDENCE}

#: The adversarially bad profile the chaos gate injects through the
#: ``autopilot.candidate`` fault point: a collapsed 10 µs band (maximum
#: switch overhead under the member profile model — the paper's
#: short-slice pathology) with a hair-trigger window. Every value is
#: INSIDE the registry's declared safe ranges on purpose: the knob
#: registry cannot reject it, only the guarded rollout can.
PATHOLOGICAL_PARAMS = {
    "min_us": 10, "max_us": 10, "window": 1, "grow_step_us": 1,
    "qdelay_threshold_ns": 2_000_000, "gw_hot_after": 3,
}


class CanaryRollout:
    """One rollout at a time: ``start`` → guard window → ``poll``
    returns the promote/rollback decision. Owned and pumped by the
    :class:`~pbs_tpu.autopilot.pilot.Autopilot` on the federation's
    own timeline."""

    def __init__(self, fed, channel, policy: str = "feedback",
                 guard_window_ns: int | None = None,
                 burn_limit: float | None = None,
                 min_guard_samples: int | None = None,
                 canary_members: int | None = None):
        self.fed = fed
        self.channel = channel  # the WRITER end
        self.policy = policy
        self.guard_window_ns = int(
            guard_window_ns if guard_window_ns is not None
            else knobs.default("autopilot.guard_window_ns"))
        self.burn_limit = float(
            burn_limit if burn_limit is not None
            else knobs.default("autopilot.burn_limit"))
        self.min_guard_samples = int(
            min_guard_samples if min_guard_samples is not None
            else knobs.default("autopilot.min_guard_samples"))
        self.n_canary = int(
            canary_members if canary_members is not None
            else knobs.default("autopilot.canary_members"))
        #: The reference profile this rollout degrades to: the channel's
        #: profile-knob values at construction (what every member was
        #: primed with), captured ONCE so a mid-canary observer cannot
        #: move the rollback target.
        _, values = channel.snapshot()
        names = sorted(set(PARAM_KNOBS[self.policy].values()))
        self.reference = {n: values[n] for n in names if n in values}
        self.state = "idle"  # idle | canary
        self.members: list[str] = []
        self.candidate: dict = {}
        self._candidate_knobs: dict = {}
        self._guard_start_ns = 0
        self._guard_end_ns = 0
        self._baseline: dict[str, int] = {}

    # -- rollout ---------------------------------------------------------

    def _pick_members(self) -> list[str]:
        """The canary subset: live, unpartitioned members ranked by
        how many INTERACTIVE tenants the ring homes on them (name-
        tiebroken) — the guard judges SLO burn, so the canary must sit
        where the latency-sensitive traffic actually lands; a canary
        serving only batch tenants could never show a tight-target
        violation inside a short guard window. Deterministic function
        of membership + placement: same seed ⇒ same canary set."""
        live = [n for n in sorted(self.fed.members)
                if n not in self.fed._draining
                and n not in self.fed._partitioned]
        homes: dict[str, int] = {n: 0 for n in live}
        for tenant in sorted(self.fed.quotas):
            if self.fed.quotas[tenant].slo != "interactive":
                continue
            home = self.fed.ring.lookup(tenant)
            if home in homes:
                homes[home] += 1
        live.sort(key=lambda n: (-homes[n], n))
        return live[:max(1, self.n_canary)]

    def _tenant_target_ns(self, tenant: str) -> int:
        q = self.fed.quotas.get(tenant)
        cls = q.slo if q is not None else "batch"
        return DEFAULT_SLO_TARGET_NS.get(
            cls, DEFAULT_SLO_TARGET_NS["batch"])

    def _snapshot(self) -> dict[str, int]:
        """Per canary member completion count — the guard's delta
        baseline: only requests that complete INSIDE the window are
        evidence."""
        return {name: self.fed.members[name].completed
                for name in self.members if name in self.fed.members}

    def start(self, candidate_params: dict, now_ns: int) -> dict | None:
        """Push the candidate scoped to the canary subset and open the
        guard window. Returns the canary event record — or None when
        NO live, unpartitioned member exists to host the canary
        (chaos can drain/partition everyone at once): the rollout is
        deferred, nothing is pushed, production stays untouched."""
        if self.state != "idle":
            raise RuntimeError(f"canary already {self.state}")
        members = self._pick_members()
        if not members:
            return None
        self.candidate = dict(candidate_params)
        self._candidate_knobs = params_to_knobs(self.policy,
                                                self.candidate)
        self.members = members
        self.channel.push(dict(self._candidate_knobs),
                          scope=list(self.members))
        self._guard_start_ns = int(now_ns)
        self._guard_end_ns = int(now_ns) + self.guard_window_ns
        self._baseline = self._snapshot()
        self.state = "canary"
        self._emit(now_ns, Ev.AP_CANARY, len(self.members),
                   self.guard_window_ns)
        return {
            "event": "canary", "t_ns": int(now_ns),
            "members": list(self.members),
            "params": dict(self.candidate),
            "guard_end_ns": self._guard_end_ns,
        }

    # -- the guard -------------------------------------------------------

    def _burns(self, now_ns: int) -> dict[str, float]:
        """Per-tenant burn rate over the guard window at the canary
        members, normalized by the 1 % error budget, from EXACT
        per-request latencies — two evidence sources:

        - completions inside the window OF requests submitted inside
          the window (the member's completion records; each
          ``Gateway.completions`` deque holds 4096 entries, far
          beyond a guard window's worth), judged on their e2e
          latency — a pre-canary backlog request completing late
          inside the window carries pre-rollout queueing the
          candidate did not cause;
        - requests still queued or in flight at the member whose AGE
          already exceeds the tenant's target — they have provably
          missed it whether or not they ever complete. Without this a
          candidate that STRANGLES a tenant (the collapsed-band
          pathology: requests admitted, never finished) would leave
          no completion evidence while some healthier tenant's clean
          completions vouch for promotion. Only requests submitted
          INSIDE the guard window count: backlog predating the
          rollout (say, behind a just-healed partition) is not the
          candidate's doing and must not convict it.

        Stuck requests younger than the target are undecided and
        count as nothing. Tenants below ``min_guard_samples`` total
        judged requests carry no verdict."""
        agg: dict[str, tuple[int, int]] = {}

        def _judge(tenant: str | None, over: bool) -> None:
            if tenant is None:
                return
            ao, at = agg.get(tenant, (0, 0))
            agg[tenant] = (ao + int(over), at + 1)

        for name in self.members:
            gw = self.fed.members.get(name)
            if gw is None:
                continue
            fresh = gw.completed - self._baseline.get(name, 0)
            if fresh > 0:
                recent = list(gw.completions)[
                    -min(fresh, len(gw.completions)):]
                for _, info in recent:
                    tenant = info.get("tenant")
                    if tenant is None:
                        continue
                    if int(info.get("submit_ns", 0)) \
                            < self._guard_start_ns:
                        continue  # pre-canary backlog: not evidence
                    _judge(tenant,
                           int(info.get("latency_ns", 0))
                           > self._tenant_target_ns(tenant))
            stuck = list(gw.queue.pending()) + list(gw.inflight.values())
            for req in stuck:
                if req.submit_ns < self._guard_start_ns:
                    continue  # pre-canary backlog: not our evidence
                age = int(now_ns) - req.submit_ns + req.penalty_ns
                if age > self._tenant_target_ns(req.tenant):
                    _judge(req.tenant, True)
        # The SAME objective the SLO observability surface reports
        # against (`pbst slo report`) — guard verdicts and dashboards
        # must measure one thing.
        budget = 1.0 - SLO_OBJECTIVE
        return {
            tenant: round((do / dt) / budget, 4)
            for tenant, (do, dt) in sorted(agg.items())
            if dt >= self.min_guard_samples
        }

    def poll(self, now_ns: int) -> dict | None:
        """Advance the guard; returns the decision event when the
        window closes (or a canary member vanished), else None."""
        if self.state != "canary":
            return None
        if any(n not in self.fed.members for n in self.members):
            # The canary box died mid-guard (chaos is allowed to do
            # that): the experiment is void — degrade to reference.
            return self._rollback(now_ns, reason="member-lost",
                                  burns={})
        if int(now_ns) < self._guard_end_ns:
            return None
        burns = self._burns(now_ns)
        if not burns:
            # Promotion requires AFFIRMATIVE evidence of health: a
            # canary window in which no tenant completed enough
            # requests to judge is itself an alarm — the candidate may
            # have strangled throughput (the collapsed-band pathology
            # does exactly this), or chaos starved the member. Either
            # way the conservative verdict is the reference profile.
            return self._rollback(now_ns, reason="no-evidence",
                                  burns=burns)
        worst = max(burns.values())
        if worst > self.burn_limit:
            return self._rollback(now_ns, reason="burn", burns=burns)
        return self._promote(now_ns, burns)

    def _promote(self, now_ns: int, burns: dict) -> dict:
        # One global push: clears the canary scope and delivers the
        # candidate to every non-canary member (their last-adopted
        # view never saw it); the canary members are already there.
        self.channel.push(dict(self._candidate_knobs), scope=None)
        # The promoted candidate IS the new trusted profile: a later
        # round's rollback must degrade to it, not silently un-promote
        # a measured win back to the construction-time reference.
        self.reference.update(self._candidate_knobs)
        self.state = "idle"
        ev = {
            "event": "promote", "t_ns": int(now_ns),
            "members": list(self.members),
            "params": dict(self.candidate),
            "burns": burns,
        }
        self._emit(now_ns, Ev.AP_PROMOTE, len(self.members), 0)
        self.members = []
        return ev

    def _rollback(self, now_ns: int, reason: str, burns: dict) -> dict:
        # One global push of the REFERENCE values: clears the scope and
        # re-delivers reference to the canary members (their adopted
        # view moved to the candidate); everyone else never moved, so
        # it is a no-op there. Production degrades to the profile it
        # trusted — never to an outage.
        self.channel.push(dict(self.reference), scope=None)
        self.state = "idle"
        worst = max(burns.values(), default=0.0)
        ev = {
            "event": "rollback", "t_ns": int(now_ns),
            "members": list(self.members),
            "params": dict(self.candidate),
            "reason": reason,
            "burns": burns,
        }
        self._emit(now_ns, Ev.AP_ROLLBACK,
                   _REASON_CODES.get(reason, 0), int(worst * 1000))
        self.members = []
        return ev

    def _emit(self, now_ns: int, ev: int, *args: int) -> None:
        if self.fed.spans is not None:
            self.fed.spans.emit_event(int(now_ns), ev, *args)
