"""Bench matrix driver, stable JSON reports, baseline regression gate.

Report shape (``pbst perf --json``; "version" gates schema changes):

    {"version": 1, "quick": false, "native": false,
     "native_available": true, "native_mode": "python",
     "benches": {"trace.emit": {"ops": ..., "ns_per_op": ..., ...}}}

``baseline.json`` (checked in next to this module) holds FOUR bench
maps — ``benches``/``quick_benches`` for the pure-Python mode and
``native_benches``/``native_quick_benches`` for ``--native`` —
because quick runs carry systematic per-call-overhead offsets and the
two modes measure different implementations; the gate always compares
like-with-like, so a native regression fails CI exactly like a Python
one. It compares ns/op ratios and fails only on LARGE regressions
(default ≥2×): microbench noise across CI hosts is real, a 2× cliff
on a hot path is not noise — the same philosophy as ``pbst
selftest``'s order-of-magnitude canaries, but against refreshable
per-path numbers instead of fixed ceilings. The refresh procedure is
documented in docs/PERF.md ("Substrate microbenchmarks").
"""

from __future__ import annotations

import json
import os
import platform
import sys

from pbs_tpu.perf.bench import CHECK_THRESHOLDS, bench_names, run_bench

#: Fail --check only when ns/op worsens by at least this factor.
DEFAULT_THRESHOLD = 2.0

_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "baseline.json")


def baseline_path() -> str:
    return _BASELINE


def native_info() -> dict:
    """The mode/availability stamp every report (and the serving
    fallback in bench.py) carries, so BENCH_r* rounds stay comparable
    across machines with and without a toolchain. ``native_tier``
    says WHICH binding executed (fastcall needs Python.h at build
    time); ``native_error`` carries the cached build/load failure."""
    from pbs_tpu.runtime import native

    avail = native.available()
    tier = None
    if avail:
        tier = "fastcall" if native.fastcall() is not None else "ctypes"
    info = {"native_available": avail, "native_tier": tier}
    # last_failure (not unavailable_reason): a fastcall-tier failure on
    # a host whose base library loads fine must surface too — "why am
    # I on the ctypes tier" deserves an answer in the report.
    reason = (native.unavailable_reason() if not avail
              else (native.last_failure() if tier == "ctypes" else None))
    if reason is not None:
        info["native_error"] = reason
    return info


def run_benches(names: list[str] | None = None, quick: bool = False,
                native: bool = False) -> dict:
    picked = list(names) if names else bench_names(native=native)
    unknown = set(picked) - set(bench_names(native=native))
    if unknown:
        raise KeyError(
            f"unknown bench(es) {sorted(unknown)}; "
            f"available: {bench_names(native=native)}")
    doc = {
        "version": 1,
        "quick": bool(quick),
        "native": bool(native),
        "native_mode": "native" if native else "python",
        **native_info(),
        "benches": {n: run_bench(n, quick=quick, native=native).as_dict()
                    for n in picked},
    }
    return doc


def load_baseline(path: str | None = None) -> dict:
    with open(path or _BASELINE) as f:
        base = json.load(f)
    if not isinstance(base.get("benches"), dict):
        raise ValueError("baseline holds no 'benches' map")
    return base


def _baseline_key(quick: bool, native: bool) -> str:
    key = "quick_benches" if quick else "benches"
    return f"native_{key}" if native else key


def save_baseline(results: dict, path: str | None = None,
                  quick_results: dict | None = None) -> str:
    path = path or _BASELINE
    native = bool(results.get("native"))
    # Merge over any existing baseline: a partial refresh
    # (`--bench X --update-baseline`, or a native-only refresh) must
    # update those numbers, not silently delete every other entry
    # (compare_to_baseline skips missing benches, so a dropped entry
    # stops being gated).
    maps: dict[str, dict] = {k: {} for k in (
        "benches", "quick_benches", "native_benches",
        "native_quick_benches")}
    try:
        old = load_baseline(path)
        for k in maps:
            maps[k].update(old.get(k, {}))
    except (OSError, ValueError):
        pass  # no (or unreadable) prior baseline: write fresh
    maps[_baseline_key(False, native)].update(results["benches"])
    if quick_results is not None:
        maps[_baseline_key(True, native)].update(
            quick_results["benches"])
    doc = {
        "version": 1,
        "note": ("refreshed via `pbst perf --update-baseline` "
                 "(docs/PERF.md); 'benches'/'quick_benches' are the "
                 "pure-Python full/--quick numbers, 'native_*' the "
                 "--native mode — the gate compares like-with-like"),
        "host": {
            "python": platform.python_version(),
            "machine": platform.machine(),
        },
        "benches": maps["benches"],
    }
    for k in ("quick_benches", "native_benches",
              "native_quick_benches"):
        if maps[k]:
            doc[k] = maps[k]
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def baseline_benches_for(results: dict, baseline: dict) -> dict:
    """The like-with-like baseline map: quick results compare against
    the ``*quick_benches`` map when present (quick op counts carry
    systematic per-call-overhead offsets a full-matrix number would
    misjudge), and ``--native`` results only ever compare against the
    ``native_*`` maps."""
    key = _baseline_key(bool(results.get("quick")),
                        bool(results.get("native")))
    m = baseline.get(key)
    if isinstance(m, dict):
        return m
    if results.get("quick"):
        # No quick map for the mode: fall back to its full-matrix map
        # (the pre-dual-mode behavior; missing benches are skipped).
        m = baseline.get(_baseline_key(False, bool(results.get("native"))))
        if isinstance(m, dict):
            return m
    return {} if results.get("native") else baseline["benches"]


def compare_to_baseline(results: dict, baseline: dict,
                        threshold: float = DEFAULT_THRESHOLD) -> list[dict]:
    """Regressions only: benches whose ns/op worsened by >= threshold.
    Benches missing from either side are skipped (a new bench must be
    able to land before its baseline number does)."""
    out = []
    base_map = baseline_benches_for(results, baseline)
    for name, cur in results["benches"].items():
        base = base_map.get(name)
        if not base or not base.get("ns_per_op"):
            continue
        # Wall-clock-bound benches (CHECK_THRESHOLDS) get wider armor
        # than the CLI threshold — their run-to-run spread is OS
        # scheduler noise, not code.
        eff = max(threshold, CHECK_THRESHOLDS.get(name, 0.0))
        ratio = cur["ns_per_op"] / base["ns_per_op"]
        if ratio >= eff:
            out.append({
                "bench": name,
                "baseline_ns_per_op": base["ns_per_op"],
                "ns_per_op": cur["ns_per_op"],
                "ratio": round(ratio, 2),
                "threshold": eff,
            })
    return sorted(out, key=lambda r: -r["ratio"])


def format_report(results: dict, baseline: dict | None = None) -> str:
    lines = [
        f"{'bench':<18} {'ops':>8} {'ns/op':>10} {'ops/s':>12} "
        f"{'blk/op':>7} {'peak_kib':>9}" + ("   vs_base" if baseline else "")
    ]
    base_map = baseline_benches_for(results, baseline) if baseline else {}
    for name, r in results["benches"].items():
        row = (f"{name:<18} {r['ops']:>8} {r['ns_per_op']:>10.1f} "
               f"{r['ops_per_s']:>12.0f} {r['alloc_blocks_per_op']:>7.3f} "
               f"{r['alloc_peak_kib']:>9.1f}")
        if baseline:
            base = base_map.get(name, {})
            if base.get("ns_per_op"):
                row += f"   {r['ns_per_op'] / base['ns_per_op']:>7.2f}x"
            else:
                row += "        --"
        lines.append(row)
    return "\n".join(lines)


def main_check(results: dict, baseline_file: str | None,
               threshold: float) -> int:
    """Shared CLI/CI tail: print regressions, return the exit code.

    A bench over threshold is RE-MEASURED once before it fails the
    gate: a real regression reproduces, a scheduler/GC spike on a
    shared CI host does not (observed: a microsecond-scale bench can
    read 2-5x slow for one invocation under transient interference).
    Flake probability is thereby squared, and genuine cliffs still
    fail deterministically — both measurements would have to spike.

    All diagnostics go to stderr: ``--json --check`` must leave stdout
    holding exactly the JSON document for CI parsers.
    """
    stream = sys.stderr
    try:
        baseline = load_baseline(baseline_file)
    except (OSError, ValueError) as e:
        print(f"pbst: bad perf baseline: {e}", file=sys.stderr)
        return 2
    regressions = compare_to_baseline(results, baseline, threshold)
    if regressions:
        quick = bool(results.get("quick"))
        retry = run_benches([r["bench"] for r in regressions],
                            quick=quick,
                            native=bool(results.get("native")))
        confirmed = compare_to_baseline(retry, baseline, threshold)
        recovered = ({r["bench"] for r in regressions}
                     - {r["bench"] for r in confirmed})
        for name in sorted(recovered):
            print(f"perf: {name} over threshold once but fine on "
                  "re-measure — transient interference, not a "
                  "regression", file=stream)
        regressions = confirmed
    for r in regressions:
        print(f"PERF REGRESSION {r['bench']} (reproduced on "
              f"re-measure): {r['ns_per_op']:.1f} ns/op vs baseline "
              f"{r['baseline_ns_per_op']:.1f} "
              f"({r['ratio']}x >= {r['threshold']}x)", file=stream)
    return 1 if regressions else 0
