"""``pbs_tpu.perf`` — microbenchmark harness for the framework's own
hot paths (``pbst perf``; docs/PERF.md "Substrate microbenchmarks").

PBS's premise is that the feedback instrumentation is cheap enough to
run every millisecond on the hot path; this package makes that a
*measured, regression-gated* property instead of a hope. Named benches
cover the per-event/per-sample costs every layer pays (trace emit,
batched emit, vectorized drain, ledger sampling, fair-queue cycling,
the sim dispatch loop, an RPC loopback), emit stable JSON, and
``pbst perf --check`` fails CI only on large (default ≥2×) ns/op
regressions against the checked-in ``baseline.json`` — the
order-of-magnitude canary philosophy of ``pbst selftest`` extended to
a refreshable, per-path baseline.
"""

from pbs_tpu.perf.bench import (
    BENCHES,
    NATIVE_BENCHES,
    BenchResult,
    bench_names,
    run_bench,
)
from pbs_tpu.perf.report import (
    DEFAULT_THRESHOLD,
    baseline_path,
    compare_to_baseline,
    format_report,
    load_baseline,
    native_info,
    run_benches,
    save_baseline,
)

__all__ = [
    "BENCHES", "BenchResult", "DEFAULT_THRESHOLD", "NATIVE_BENCHES",
    "baseline_path", "bench_names", "compare_to_baseline",
    "format_report", "load_baseline", "native_info", "run_bench",
    "run_benches", "save_baseline",
]
