"""Named microbenchmarks of the framework's hot paths.

Each bench is a factory returning ``(run, reset, teardown)``:
``reset()`` restores pre-round state (drain the ring, refill the
slab), ``run()`` executes the timed ops and returns the op count, and
``teardown()`` releases external resources (sockets). The driver times
``rounds`` rounds and keeps the best ns/op (minimum — the standard
microbench estimator for the noise floor), then takes one tracemalloc
snapshot pass for allocation accounting.

Everything here runs jax-free and native-free (``native=False`` where
a native fast path exists): the harness pins the *pure-Python* hot
paths, so numbers are comparable across hosts with and without the
C++ runtime, and a regression in the fallback — what CI images and
laptops actually execute — can't hide behind the native library.
"""

from __future__ import annotations

import dataclasses
import gc
import time
import tracemalloc
from typing import Callable

import numpy as np

MS_NS = 1_000_000

#: (run, reset, teardown) — see module docstring.
BenchFns = tuple[Callable[[], int], Callable[[], None],
                 Callable[[], None] | None]


@dataclasses.dataclass
class BenchResult:
    name: str
    ops: int  # ops per timed round
    rounds: int
    ns_per_op: float
    ops_per_s: float
    #: Net allocated blocks per op across one traced round (tracemalloc
    #: snapshot diff) — catches per-op garbage accumulation and leaks.
    alloc_blocks_per_op: float
    #: High-water tracemalloc bytes over the traced round — catches
    #: transient per-op allocation storms.
    alloc_peak_kib: float

    def as_dict(self) -> dict:
        return {
            "ops": self.ops,
            "rounds": self.rounds,
            "ns_per_op": round(self.ns_per_op, 1),
            "ops_per_s": round(self.ops_per_s, 1),
            "alloc_blocks_per_op": round(self.alloc_blocks_per_op, 4),
            "alloc_peak_kib": round(self.alloc_peak_kib, 1),
        }


# -- bench factories --------------------------------------------------------


def _trace_emit(n: int) -> BenchFns:
    from pbs_tpu.obs.trace import Ev, TraceBuffer

    tb = TraceBuffer(capacity=n, native=False)
    ev = int(Ev.SCHED_PICK)

    def run() -> int:
        emit = tb.emit
        for i in range(n):
            emit(i, ev, 3, 200_000, 7)
        return n

    def reset() -> None:
        while tb.consume(4096).shape[0]:
            pass

    return run, reset, None


def _trace_emit_many(n: int) -> BenchFns:
    from pbs_tpu.obs.trace import TRACE_REC_WORDS, Ev, TraceBuffer

    batch = 256
    inner = max(1, n // batch)
    tb = TraceBuffer(capacity=inner * batch, native=False)
    recs = np.zeros((batch, TRACE_REC_WORDS), dtype="<u8")
    recs[:, 0] = np.arange(batch)
    recs[:, 1] = int(Ev.SCHED_DESCHED)
    recs[:, 2] = 7

    def run() -> int:
        emit_many = tb.emit_many
        for _ in range(inner):
            emit_many(recs)
        return inner * batch

    def reset() -> None:
        while tb.consume(4096).shape[0]:
            pass

    return run, reset, None


def _trace_consume(n: int) -> BenchFns:
    from pbs_tpu.obs.trace import TRACE_REC_WORDS, Ev, TraceBuffer

    tb = TraceBuffer(capacity=n, native=False)
    recs = np.zeros((n, TRACE_REC_WORDS), dtype="<u8")
    recs[:, 0] = np.arange(n)
    recs[:, 1] = int(Ev.SCHED_WAKE)

    def run() -> int:
        got = 0
        while got < n:
            chunk = tb.consume(1024).shape[0]
            if chunk == 0:
                break
            got += chunk
        return got or 1

    def reset() -> None:
        tb.consume(10**9)  # drop any leftovers, then refill
        tb.emit_many(recs)

    return run, reset, None


def _span_emit(n: int) -> BenchFns:
    """One SPAN_* lifecycle emit through the SpanRecorder's EmitBatch
    staging path (docs/TRACING.md): the cost every gateway dispatch
    pays when spans are armed, pinned so span overhead is regression-
    gated like the rest of the substrate."""
    from pbs_tpu.obs.spans import SpanRecorder
    from pbs_tpu.obs.trace import TraceBuffer

    ring = TraceBuffer(capacity=n + 512, native=False)
    rec = SpanRecorder(ring=ring)
    rec.dispatch(0, "r0", 1, 500, 1000, "gw")  # intern outside timing

    def run() -> int:
        dispatch = rec.dispatch
        for i in range(n):
            dispatch(i, "r0", 1, 500, 1000, "gw")
        rec.flush()
        return n

    def reset() -> None:
        rec.flush()
        while ring.consume(4096).shape[0]:
            pass

    return run, reset, None


def _hist_record(n: int) -> BenchFns:
    """One log2-histogram latency sample into a ledger slot
    (LatencyHistograms.record): the per-completion cost of the SLO
    observability layer."""
    from pbs_tpu.obs.spans import LatencyHistograms

    h = LatencyHistograms(num_slots=16)
    h.record("t0", "interactive", "queue", 1 << 12)  # intern the slot

    def run() -> int:
        record = h.record
        for i in range(n):
            record("t0", "interactive", "queue", 1 << (10 + (i & 15)))
        return n

    return run, lambda: None, None


def _ledger_sample(n: int) -> BenchFns:
    from pbs_tpu.telemetry.counters import NUM_COUNTERS
    from pbs_tpu.telemetry.ledger import Ledger

    slots = 64
    led = Ledger(slots, native=False)
    deltas = np.arange(NUM_COUNTERS, dtype="<u8")
    for s in range(slots):
        led.add_many(s, deltas)
    idx = list(range(slots))
    inner = max(1, n // slots)

    def run() -> int:
        sample = led.snapshot_many
        for _ in range(inner):
            sample(idx)
        return inner * slots

    return run, lambda: None, None


def _fairqueue_cycle(n: int) -> BenchFns:
    from pbs_tpu.gateway.admission import BATCH, INTERACTIVE
    from pbs_tpu.gateway.fairqueue import DeficitRoundRobin, Request

    q = DeficitRoundRobin()
    tenants = ["t0", "t1", "t2", "t3"]
    for t in tenants:
        q.set_weight(t, 256)

    def run() -> int:
        push, pop = q.push, q.pop
        for i in range(n):
            push(Request(
                rid=str(i), tenant=tenants[i & 3],
                slo=INTERACTIVE if i & 1 else BATCH, cost=1,
                payload=None, submit_ns=i))
        while pop() is not None:
            pass
        return n

    return run, lambda: None, None


def _sim_smoke(n: int) -> BenchFns:
    """End-to-end sanity point: virtual-time dispatch loop cost per
    quantum (engine + partition + credit/feedback stack). ``n`` scales
    the horizon in virtual milliseconds."""
    from pbs_tpu.sim.engine import SimEngine

    def run() -> int:
        eng = SimEngine(workload="stable", policy="feedback", seed=0,
                        n_tenants=2, horizon_ns=n * MS_NS, record=False)
        rep = eng.run()
        return max(1, int(rep["quanta"]))

    return run, lambda: None, None


def _rpc_roundtrip(n: int) -> BenchFns:
    from pbs_tpu.dist.rpc import RpcClient, RpcServer

    srv = RpcServer().start()
    srv.register("echo", lambda x=0: x)
    cli = RpcClient(srv.address)
    cli.call("echo", x=0)  # connect outside the timed region

    def run() -> int:
        call = cli.call
        for i in range(n):
            call("echo", x=i)
        return n

    def teardown() -> None:
        cli.close()
        srv.stop()

    return run, lambda: None, teardown


#: name -> (factory, full_n, quick_n). ns/op is per *op*: one record
#: for the trace benches, one slot sample, one queue cycle, one
#: dispatched quantum, one RPC call.
BENCHES: dict[str, tuple[Callable[[int], BenchFns], int, int]] = {
    "trace.emit": (_trace_emit, 50_000, 8_192),
    "trace.emit_many": (_trace_emit_many, 65_536, 8_192),
    "trace.consume": (_trace_consume, 65_536, 8_192),
    "span.emit": (_span_emit, 50_000, 8_192),
    "hist.record": (_hist_record, 50_000, 8_192),
    # quick keeps >=100 timed snapshot_many calls: fewer lets one
    # scheduler hiccup read as a 2x "regression" in the CI smoke.
    "ledger.sample": (_ledger_sample, 12_800, 6_400),
    "fairqueue.cycle": (_fairqueue_cycle, 10_000, 2_000),
    "sim.smoke": (_sim_smoke, 100, 25),
    "rpc.roundtrip": (_rpc_roundtrip, 300, 50),
}


#: Per-bench --check armor: effective threshold = max(CLI threshold,
#: this). The wall-clock-bound benches ride the OS scheduler — a
#: loopback RPC's socket+thread handoffs measure 2-3x apart run to run
#: on a healthy host, and the sim engine drags the whole runtime stack
#: — so their variance is environment, not code. The pure-compute
#: benches keep the tight default.
CHECK_THRESHOLDS: dict[str, float] = {
    "rpc.roundtrip": 4.0,
    "sim.smoke": 3.0,
}


def bench_names() -> list[str]:
    return list(BENCHES)


def run_bench(name: str, quick: bool = False,
              rounds: int = 5) -> BenchResult:
    try:
        factory, full_n, quick_n = BENCHES[name]
    except KeyError:
        raise KeyError(
            f"unknown bench {name!r}; available: {bench_names()}") from None
    run, reset, teardown = factory(quick_n if quick else full_n)
    try:
        # Warm round: first-touch, caches, lazy imports.
        reset()
        ops = run()
        best = float("inf")
        for _ in range(rounds):
            reset()
            # Collect BEFORE and pause cyclic GC DURING the timed
            # region: a collection pause landing inside a short round
            # reads as a phantom 2x regression (best-of-N can't save a
            # round-count of 1-3 from a determined GC).
            gc.collect()
            gc.disable()
            try:
                t0 = time.perf_counter_ns()
                ops = run()
                dt = time.perf_counter_ns() - t0
            finally:
                gc.enable()
            best = min(best, dt / ops)
        # Allocation pass, untimed (tracing skews timing 2-10x).
        reset()
        gc.collect()
        tracemalloc.start()
        try:
            before = tracemalloc.take_snapshot()
            tracemalloc.reset_peak()
            cur0, _ = tracemalloc.get_traced_memory()
            ops = run()
            _cur1, peak = tracemalloc.get_traced_memory()
            after = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        diff = after.compare_to(before, "filename")
        net_blocks = float(sum(d.count_diff for d in diff))
        return BenchResult(
            name=name, ops=ops, rounds=rounds, ns_per_op=best,
            ops_per_s=1e9 / best if best > 0 else 0.0,
            alloc_blocks_per_op=net_blocks / ops,
            alloc_peak_kib=max(0, peak - cur0) / 1024.0,
        )
    finally:
        if teardown is not None:
            teardown()
