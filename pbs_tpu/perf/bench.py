"""Named microbenchmarks of the framework's hot paths.

Each bench is a factory returning ``(run, reset, teardown)``:
``reset()`` restores pre-round state (drain the ring, refill the
slab), ``run()`` executes the timed ops and returns the op count, and
``teardown()`` releases external resources (sockets). The driver times
``rounds`` rounds and keeps the best ns/op (minimum — the standard
microbench estimator for the noise floor), then takes one tracemalloc
snapshot pass for allocation accounting.

Everything runs jax-free in one of TWO modes, compared against its
own baseline map (docs/PERF.md "Substrate microbenchmarks"):

- **python mode** (default, ``native=False`` everywhere): pins the
  pure-Python hot paths — the verified fallback CI images and
  laptops without a toolchain actually execute. A regression here
  can't hide behind the native library.
- **native mode** (``--native``; substrate benches only): the same
  benches over the C runtime (``native=True`` — fastcall tier when
  Python.h was available at build time, ctypes otherwise), so a
  native-path regression fails CI exactly like a Python one.
"""

from __future__ import annotations

import dataclasses
import gc
import time
import tracemalloc
from typing import Callable

import numpy as np

MS_NS = 1_000_000

#: (run, reset, teardown) — see module docstring.
BenchFns = tuple[Callable[[], int], Callable[[], None],
                 Callable[[], None] | None]


@dataclasses.dataclass
class BenchResult:
    name: str
    ops: int  # ops per timed round
    rounds: int
    ns_per_op: float
    ops_per_s: float
    #: Net allocated blocks per op across one traced round (tracemalloc
    #: snapshot diff) — catches per-op garbage accumulation and leaks.
    alloc_blocks_per_op: float
    #: High-water tracemalloc bytes over the traced round — catches
    #: transient per-op allocation storms.
    alloc_peak_kib: float

    def as_dict(self) -> dict:
        return {
            "ops": self.ops,
            "rounds": self.rounds,
            # Sub-ns/op benches (sim.sustained counts simulated ns as
            # ops) need more than one decimal or the regression ratio
            # quantizes to coarse steps.
            "ns_per_op": round(self.ns_per_op,
                               1 if self.ns_per_op >= 10 else 4),
            "ops_per_s": round(self.ops_per_s, 1),
            "alloc_blocks_per_op": round(self.alloc_blocks_per_op, 4),
            "alloc_peak_kib": round(self.alloc_peak_kib, 1),
        }


# -- bench factories --------------------------------------------------------


def _trace_emit(n: int, native: bool = False) -> BenchFns:
    from pbs_tpu.obs.trace import Ev, TraceBuffer

    tb = TraceBuffer(capacity=n, native=native)
    ev = int(Ev.SCHED_PICK)

    def run() -> int:
        emit = tb.emit
        for i in range(n):
            emit(i, ev, 3, 200_000, 7)
        return n

    def reset() -> None:
        while tb.consume(4096).shape[0]:
            pass

    return run, reset, None


def _trace_emit_many(n: int, native: bool = False) -> BenchFns:
    from pbs_tpu.obs.trace import TRACE_REC_WORDS, Ev, TraceBuffer

    batch = 256
    inner = max(1, n // batch)
    tb = TraceBuffer(capacity=inner * batch, native=native)
    recs = np.zeros((batch, TRACE_REC_WORDS), dtype="<u8")
    recs[:, 0] = np.arange(batch)
    recs[:, 1] = int(Ev.SCHED_DESCHED)
    recs[:, 2] = 7

    def run() -> int:
        emit_many = tb.emit_many
        for _ in range(inner):
            emit_many(recs)
        return inner * batch

    def reset() -> None:
        while tb.consume(4096).shape[0]:
            pass

    return run, reset, None


def _trace_consume(n: int, native: bool = False) -> BenchFns:
    from pbs_tpu.obs.trace import TRACE_REC_WORDS, Ev, TraceBuffer

    tb = TraceBuffer(capacity=n, native=native)
    recs = np.zeros((n, TRACE_REC_WORDS), dtype="<u8")
    recs[:, 0] = np.arange(n)
    recs[:, 1] = int(Ev.SCHED_WAKE)

    def run() -> int:
        got = 0
        while got < n:
            chunk = tb.consume(1024).shape[0]
            if chunk == 0:
                break
            got += chunk
        return got or 1

    def reset() -> None:
        tb.consume(10**9)  # drop any leftovers, then refill
        tb.emit_many(recs)

    return run, reset, None


def _span_emit(n: int, native: bool = False) -> BenchFns:
    """One SPAN_* lifecycle emit through the SpanRecorder's EmitBatch
    staging path (docs/TRACING.md): the cost every gateway dispatch
    pays when spans are armed, pinned so span overhead is regression-
    gated like the rest of the substrate."""
    from pbs_tpu.obs.spans import SpanRecorder
    from pbs_tpu.obs.trace import TraceBuffer

    ring = TraceBuffer(capacity=n + 512, native=native)
    rec = SpanRecorder(ring=ring)
    rec.dispatch(0, "r0", 1, 500, 1000, "gw")  # intern outside timing

    def run() -> int:
        dispatch = rec.dispatch
        for i in range(n):
            dispatch(i, "r0", 1, 500, 1000, "gw")
        rec.flush()
        return n

    def reset() -> None:
        rec.flush()
        while ring.consume(4096).shape[0]:
            pass

    return run, reset, None


def _hist_record(n: int, native: bool = False) -> BenchFns:
    """One log2-histogram latency sample into a ledger slot
    (LatencyHistograms.record — bucket + seqlock fused into one native
    call in native mode): the per-completion cost of the SLO
    observability layer."""
    from pbs_tpu.obs.spans import LatencyHistograms

    h = LatencyHistograms(num_slots=16, native=native)
    h.record("t0", "interactive", "queue", 1 << 12)  # intern the slot

    def run() -> int:
        record = h.record
        for i in range(n):
            record("t0", "interactive", "queue", 1 << (10 + (i & 15)))
        return n

    return run, lambda: None, None


def _hist_record_many(n: int, native: bool = False) -> BenchFns:
    """Batched histogram samples (LatencyHistograms.record_many, the
    HistBatch flush path of the gateway's batched pump): ns per staged
    sample when a tick's worth lands as one call."""
    from pbs_tpu.obs.spans import LatencyHistograms

    h = LatencyHistograms(num_slots=16, native=native)
    batch = 256
    inner = max(1, n // batch)
    slots = np.zeros(batch, dtype=np.int64)
    slots[:] = h.slot_of("t0", "interactive", "queue")
    values = (np.arange(batch, dtype="<u8") % 24 + 1) << 10

    def run() -> int:
        record_many = h.record_many
        for _ in range(inner):
            record_many(slots, values)
        return inner * batch

    return run, lambda: None, None


def _ledger_snapshot_many(n: int, native: bool = False) -> BenchFns:
    from pbs_tpu.telemetry.counters import NUM_COUNTERS
    from pbs_tpu.telemetry.ledger import Ledger

    slots = 64
    led = Ledger(slots, native=native)
    deltas = np.arange(NUM_COUNTERS, dtype="<u8")
    for s in range(slots):
        led.add_many(s, deltas)
    idx = list(range(slots))
    inner = max(1, n // slots)

    def run() -> int:
        sample = led.snapshot_many
        for _ in range(inner):
            sample(idx)
        return inner * slots

    return run, lambda: None, None


def _fairqueue_cycle(n: int) -> BenchFns:
    from pbs_tpu.gateway.admission import BATCH, INTERACTIVE
    from pbs_tpu.gateway.fairqueue import DeficitRoundRobin, Request

    q = DeficitRoundRobin()
    tenants = ["t0", "t1", "t2", "t3"]
    for t in tenants:
        q.set_weight(t, 256)

    def run() -> int:
        push, pop = q.push, q.pop
        for i in range(n):
            push(Request(
                rid=str(i), tenant=tenants[i & 3],
                slo=INTERACTIVE if i & 1 else BATCH, cost=1,
                payload=None, submit_ns=i))
        while pop() is not None:
            pass
        return n

    return run, lambda: None, None


def _sim_smoke(n: int) -> BenchFns:
    """End-to-end sanity point: virtual-time dispatch loop cost per
    quantum (engine + partition + credit/feedback stack, pinned to the
    pure-Python witness path). ``n`` scales the horizon in virtual
    milliseconds."""
    from pbs_tpu.sim.engine import SimEngine

    def run() -> int:
        eng = SimEngine(workload="stable", policy="feedback", seed=0,
                        n_tenants=2, horizon_ns=n * MS_NS, record=False,
                        native=False)
        rep = eng.run()
        return max(1, int(rep["quanta"]))

    return run, lambda: None, None


def _sim_sustained(n: int, native: bool = False) -> BenchFns:
    """The sweep-throughput headline (docs/SIM.md "Sweep + sustained
    throughput"): simulated-ns per wall-ns of one sweep-mode engine run
    (mixed workload, feedback armed — the exact configuration a `pbst
    tune` cell executes). ``n`` scales the horizon in virtual
    milliseconds; ops = simulated ns, so ns/op is wall-ns PER
    SIMULATED-ns (0.125 = the sim runs 8x faster than real time).
    Dual-mode: python mode pins the witness engine (``native=False``),
    native mode requires the C dispatch core — a regression in either
    fails ``pbst perf --check`` like-with-like."""
    from pbs_tpu.sim.engine import SimEngine

    def run() -> int:
        eng = SimEngine(workload="mixed", policy="feedback", seed=0,
                        n_tenants=4, horizon_ns=n * MS_NS, record=False,
                        native=native)  # bool: required OR pinned-off
        rep = eng.run()
        return max(1, int(rep["elapsed_ns"]))

    return run, lambda: None, None


def _sweep_cell(n: int, native: bool = False) -> BenchFns:
    """Per-cell cost of the parallel-sweep substrate (sim/sweep.py,
    inline worker path): seed derivation + sweep-mode engine + report
    reduction, over ``n`` 20 ms cells. Dual-mode like sim.sustained."""
    from pbs_tpu.sim.sweep import build_grid, run_cell

    cells = build_grid(["mixed"], ["feedback"], n_reps=n,
                       horizon_ns=20 * MS_NS)

    def run() -> int:
        for cell in cells:
            run_cell(cell, base_seed=0, native=native)
        return len(cells)

    return run, lambda: None, None


def _journal_append(n: int) -> BenchFns:
    """Write-ahead journal staging + group commit (gateway/journal.py,
    docs/DURABILITY.md): ns per intent record when a tick's worth (256)
    stages through the EmitBatch path and lands as ONE CRC'd frame
    write — the marginal cost every admitted request pays once the
    journal is armed."""
    import os
    import tempfile

    from pbs_tpu.gateway.journal import HEADER_WORDS, GatewayJournal

    d = tempfile.mkdtemp(prefix="pbst-jr-bench-")
    path = os.path.join(d, "bench.jrnl")
    j = GatewayJournal.create(path)
    for name in ("gw", "t0", "r0"):
        j.intern(name)  # steady state: names interned outside timing
    j.commit()
    batch = 256
    inner = max(1, n // batch)

    def run() -> int:
        admit = j.admit
        for _ in range(inner):
            for i in range(batch):
                admit(i, "gw", "r0", "t0", 0, 1, 1)
            j.commit()
        return inner * batch

    def reset() -> None:
        os.ftruncate(j._fd, HEADER_WORDS * 8)
        os.lseek(j._fd, 0, os.SEEK_END)

    def teardown() -> None:
        import shutil

        os.close(j._fd)
        shutil.rmtree(d, ignore_errors=True)

    return run, reset, teardown


def _gateway_pump(n: int) -> BenchFns:
    """Full gateway pump round-trip with the journal ARMED on top of
    the complete observability stack (spans + histograms + ledger +
    trace staging): wall-ns per completed request through submit →
    admit → dispatch → complete → group commit. The ISSUE 15 gate:
    this must stay within 2x of the PR 9 observability-armed pump
    (89 us/req on the reference container)."""
    import os
    import tempfile

    from pbs_tpu.gateway.admission import TenantQuota
    from pbs_tpu.gateway.backends import SimServeBackend
    from pbs_tpu.gateway.gateway import Gateway
    from pbs_tpu.gateway.journal import GatewayJournal
    from pbs_tpu.utils.clock import MS as _MS, VirtualClock

    d = tempfile.mkdtemp(prefix="pbst-pump-bench-")
    clock = VirtualClock()
    j = GatewayJournal.create(os.path.join(d, "gw.jrnl"))
    gw = Gateway(
        [SimServeBackend("b0", n_slots=8, service_ns_per_cost=_MS,
                         seed=0)],
        clock=clock, trace_capacity=4096,
        ledger_path=os.path.join(d, "gw.led"), journal=j,
        max_queued=1 << 16)
    gw.register_tenant("t0", TenantQuota(
        rate=1e9, burst=1e6, slo="interactive", max_queued=1 << 16))

    def run() -> int:
        done = 0
        submit, tick = gw.submit, gw.tick
        while done < n:
            for _ in range(8):
                submit("t0", None, cost=1)
            clock.advance(2 * _MS)
            done += len(tick())
        return max(1, done)

    def reset() -> None:
        # Drain the ring so staged observability never hits the
        # full-ring drop path mid-round.
        while gw.trace.consume(4096).shape[0]:
            pass

    def teardown() -> None:
        import shutil

        os.close(j._fd)
        shutil.rmtree(d, ignore_errors=True)

    return run, reset, teardown


def _hwtelem_sample(n: int) -> BenchFns:
    """One live counter-ladder sample (HwCounterSource.sample): the
    marginal cost every gateway tick pays once ``--hw`` is armed.
    Times whatever tier the box grants — perf_event read(2) per event
    on the reference container, getrusage at the ladder floor, the
    empty-dict fast path when no tier probes — so the gate pins the
    sampling seam, not one kernel interface."""
    from pbs_tpu.hwtelem.sources import HwCounterSource

    src = HwCounterSource(probe=True)
    src.sample()  # prime the delta baseline outside the timed region

    def run() -> int:
        sample = src.sample
        for _ in range(n):
            sample()
        return n

    def teardown() -> None:
        src.close()

    return run, lambda: None, teardown


def _rpc_roundtrip(n: int) -> BenchFns:
    from pbs_tpu.dist.rpc import RpcClient, RpcServer

    srv = RpcServer().start()
    srv.register("echo", lambda x=0: x)
    cli = RpcClient(srv.address, deadline_s=30.0)
    cli.call("echo", x=0)  # connect outside the timed region

    def run() -> int:
        call = cli.call
        for i in range(n):
            call("echo", x=i)
        return n

    def teardown() -> None:
        cli.close()
        srv.stop()

    return run, lambda: None, teardown


#: name -> (factory, full_n, quick_n). ns/op is per *op*: one record
#: for the trace benches, one slot sample, one queue cycle, one
#: dispatched quantum, one RPC call.
BENCHES: dict[str, tuple[Callable[..., BenchFns], int, int]] = {
    "trace.emit": (_trace_emit, 50_000, 8_192),
    "trace.emit_many": (_trace_emit_many, 65_536, 8_192),
    "trace.consume": (_trace_consume, 65_536, 8_192),
    "span.emit": (_span_emit, 50_000, 8_192),
    "hist.record": (_hist_record, 50_000, 8_192),
    "hist.record_many": (_hist_record_many, 65_536, 8_192),
    # quick keeps >=100 timed snapshot_many calls: fewer lets one
    # scheduler hiccup read as a 2x "regression" in the CI smoke.
    "ledger.snapshot_many": (_ledger_snapshot_many, 12_800, 6_400),
    "fairqueue.cycle": (_fairqueue_cycle, 10_000, 2_000),
    "journal.append": (_journal_append, 65_536, 8_192),
    # ops = completed requests; ns/op is the full armed-journal pump
    # round-trip per request (the ISSUE 15 2x-of-89us acceptance gate).
    "gateway.pump": (_gateway_pump, 2_000, 400),
    "sim.smoke": (_sim_smoke, 100, 25),
    # n is the horizon in virtual ms / the cell count; ns/op for
    # sim.sustained is wall-ns per simulated-ns (lower = faster sim).
    "sim.sustained": (_sim_sustained, 2_000, 250),
    "sweep.cell": (_sweep_cell, 24, 6),
    # ops = ladder samples; syscall-bound (one read(2) per armed
    # event) so the per-op cost tracks the kernel, not this code.
    "hwtelem.sample": (_hwtelem_sample, 20_000, 2_000),
    "rpc.roundtrip": (_rpc_roundtrip, 300, 50),
}

#: Benches with a native fast path — the ``--native`` matrix. The
#: rest (pure-Python data structures, sockets) have exactly one
#: implementation, so a second mode would gate nothing. sim.sustained
#: and sweep.cell ride the native sim dispatch core in native mode
#: (required, not best-effort) and pin the pure-Python witness engine
#: in python mode, so a regression on either tier fails
#: ``pbst perf --check`` like-with-like.
NATIVE_BENCHES = (
    "trace.emit", "trace.emit_many", "trace.consume", "span.emit",
    "hist.record", "hist.record_many", "ledger.snapshot_many",
    "sim.sustained", "sweep.cell",
)


#: Per-bench --check armor: effective threshold = max(CLI threshold,
#: this). The wall-clock-bound benches ride the OS scheduler — a
#: loopback RPC's socket+thread handoffs measure 2-3x apart run to run
#: on a healthy host, and the sim engine drags the whole runtime stack
#: — so their variance is environment, not code. The single-digit-
#: ns/op BULK-COPY benches are memory-bandwidth-bound: under a loaded
#: host (tier-1 runs the whole suite around them) a 2x swing is cache/
#: bandwidth contention, while a real regression (losing the
#: vectorized/native path) is 10-100x — 3x armor keeps the gate
#: meaningful without flaking. Applies in both modes. The pure-compute
#: benches keep the tight default.
CHECK_THRESHOLDS: dict[str, float] = {
    "rpc.roundtrip": 4.0,
    # Pure syscall round-trips: on a 1-vCPU container the kernel-side
    # cost swings with host load the same way the socket benches do.
    "hwtelem.sample": 4.0,
    # File I/O (page-cache writes) + whole-stack pump: wall-clock-
    # bound like the sim benches, same 3x host-variance armor.
    "journal.append": 3.0,
    "gateway.pump": 3.0,
    "sim.smoke": 3.0,
    "sim.sustained": 3.0,
    "sweep.cell": 3.0,
    "trace.consume": 3.0,
    "trace.emit_many": 3.0,
    "hist.record_many": 3.0,
    "ledger.snapshot_many": 3.0,
}


def bench_names(native: bool = False) -> list[str]:
    return list(NATIVE_BENCHES) if native else list(BENCHES)


def run_bench(name: str, quick: bool = False, rounds: int = 5,
              native: bool = False) -> BenchResult:
    try:
        factory, full_n, quick_n = BENCHES[name]
    except KeyError:
        raise KeyError(
            f"unknown bench {name!r}; available: {bench_names()}") from None
    if native and name not in NATIVE_BENCHES:
        raise KeyError(
            f"bench {name!r} has no native mode; native benches: "
            f"{list(NATIVE_BENCHES)}")
    n = quick_n if quick else full_n
    run, reset, teardown = (
        factory(n, native=True) if native else factory(n))
    try:
        # Warm round: first-touch, caches, lazy imports.
        reset()
        ops = run()
        best = float("inf")
        for _ in range(rounds):
            reset()
            # Collect BEFORE and pause cyclic GC DURING the timed
            # region: a collection pause landing inside a short round
            # reads as a phantom 2x regression (best-of-N can't save a
            # round-count of 1-3 from a determined GC).
            gc.collect()
            gc.disable()
            try:
                t0 = time.perf_counter_ns()
                ops = run()
                dt = time.perf_counter_ns() - t0
            finally:
                gc.enable()
            best = min(best, dt / ops)
        # Allocation pass, untimed (tracing skews timing 2-10x).
        reset()
        gc.collect()
        tracemalloc.start()
        try:
            before = tracemalloc.take_snapshot()
            tracemalloc.reset_peak()
            cur0, _ = tracemalloc.get_traced_memory()
            ops = run()
            _cur1, peak = tracemalloc.get_traced_memory()
            after = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        diff = after.compare_to(before, "filename")
        net_blocks = float(sum(d.count_diff for d in diff))
        return BenchResult(
            name=name, ops=ops, rounds=rounds, ns_per_op=best,
            ops_per_s=1e9 / best if best > 0 else 0.0,
            alloc_blocks_per_op=net_blocks / ops,
            alloc_peak_kib=max(0, peak - cur0) / 1024.0,
        )
    finally:
        if teardown is not None:
            teardown()
