"""Counter-API misuse pass.

Telemetry counters (``ExecutionContext.counters``) are monotonically
increasing totals folded in at quantum boundaries. The sanctioned ways
to consume them are:

- **deltas** against a window baseline: ``ctx.counters -
  ctx.prev_counters`` (what the feedback tick does), and
- **thresholds** through the :class:`telemetry.sampler.OverflowSampler`
  (arm/fire/rearm — the i-mode perfctr contract), which owns the
  window bookkeeping.

What breaks is a consumer *raw-reading* a counter and carrying that
raw value across a window boundary itself: totals survive job
migration/restore and sampler rearm resets the baseline, so ad-hoc
caching silently double-counts or goes negative. Two rules, scoped to
consumer code (the windowing machinery in ``telemetry/`` and ``obs/``
is exempt — it *implements* the contract):

- ``counter-raw-cache``: a raw ``.counters[...]`` read stored on
  ``self`` — a cross-call cache of an absolute counter value.
- ``counter-raw-threshold``: a comparison of a raw ``.counters[...]``
  read against a non-counter operand — an inline threshold check that
  should be an armed sampler sample.

A read that participates in the delta idiom (the same expression also
touches ``prev_counters``) is clean. Raw reads into *local* state
(formatting a dump row, summing a report) never cross a window
boundary and are not flagged.
"""

from __future__ import annotations

import ast

from pbs_tpu.analysis.core import CheckContext, Finding, Pass, SourceFile

#: Module path fragments that implement the windowing contract.
MACHINERY = ("/telemetry/", "/obs/")


def _is_machinery(rel_path: str) -> bool:
    p = "/" + rel_path.replace("\\", "/")
    return any(m in p for m in MACHINERY)


def _raw_counter_read(node: ast.AST) -> bool:
    """True when node is ``<x>.counters[...]`` (or bare
    ``counters[...]``)."""
    if not isinstance(node, ast.Subscript):
        return False
    v = node.value
    return (isinstance(v, ast.Attribute) and v.attr == "counters") or \
        (isinstance(v, ast.Name) and v.id == "counters")


def _contains_raw_read(node: ast.AST) -> bool:
    return any(_raw_counter_read(sub) for sub in ast.walk(node))


def _contains_prev(node: ast.AST) -> bool:
    # The delta idiom specifically: a prev_counters-style baseline in
    # the same expression. An arbitrary name merely containing "prev"
    # (preview, prevent_flag, ...) is NOT a window baseline.
    for sub in ast.walk(node):
        ident = sub.attr if isinstance(sub, ast.Attribute) else \
            sub.id if isinstance(sub, ast.Name) else ""
        if "prev" in ident and "counter" in ident:
            return True
    return False


class _CounterScan(ast.NodeVisitor):
    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self" \
                    and _contains_raw_read(node.value) \
                    and not _contains_prev(node.value):
                self.findings.append(Finding(
                    "counter-raw-cache", self.src.rel_path, node.lineno,
                    node.col_offset,
                    f"raw counter read cached on self.{t.attr} — absolute "
                    "counter values must not cross a window boundary",
                    hint="consume deltas (counters - prev_counters) or arm "
                         "an OverflowSampler sample (telemetry/sampler.py)"))
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        operands = [node.left, *node.comparators]
        raws = [_contains_raw_read(o) and not _contains_prev(o)
                for o in operands]
        if any(raws) and not all(raws):
            # raw counter vs an unrelated operand = inline threshold.
            if not _contains_prev(node):
                self.findings.append(Finding(
                    "counter-raw-threshold", self.src.rel_path, node.lineno,
                    node.col_offset,
                    "threshold comparison against a raw counter read — "
                    "window bookkeeping belongs to the sampler",
                    hint="arm an OverflowSampler sample "
                         "(telemetry/sampler.py) and consume the "
                         "overflow event instead"))
        self.generic_visit(node)


class CounterApiPass(Pass):
    id = "counter-api"
    rules = ("counter-raw-cache", "counter-raw-threshold")
    description = ("telemetry counters consumed as deltas via the "
                   "sampler; raw reads must not cross window "
                   "boundaries in consumer code")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None or _is_machinery(src.rel_path):
            return []
        scan = _CounterScan(src)
        scan.visit(src.tree)
        return scan.findings
