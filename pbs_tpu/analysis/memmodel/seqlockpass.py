"""Seqlock write/read protocol checker (C side + Python mirror).

The protocol being enforced is the one the repo already ships
(native/pbst_runtime.cc, telemetry/ledger.py, knobs/channel.py): a
writer brackets every payload store between a version-word increment
to odd (``__ATOMIC_RELEASE`` store) and a release-fenced increment
back to even; a reader retry loop takes two acquire loads of the
version word, rejects odd, fences the payload copy with acquires on
both sides, and re-checks ``v0 == v1``; a lockless ring publishes its
head word with release ordering only AFTER the payload memcpy. Six
rules:

- ``seqlock-missing-release``: a ``write_begin``/``write_end`` helper
  whose body lacks the release-ordered version store or the release
  fence — the bracket exists but orders nothing.
- ``seqlock-plain-store``: a store through a slot pointer (a
  ``slot_ptr(...)`` / ``buf + slot * kSlotWords`` derived variable)
  outside a ``write_begin``/``write_end`` bracket — a torn read
  waiting for a concurrent snapshot.
- ``seqlock-unbalanced``: a function whose ``write_begin`` and
  ``write_end`` call counts differ — some path leaves the version
  word odd forever (readers spin their whole retry budget).
- ``seqlock-reader-protocol``: a retry loop (two version-word loads
  of the same buffer) missing any leg of the read protocol: acquire
  ordering on the loads, the odd check, the ``v0 == v1`` re-check, or
  the two acquire fences around the payload copy.
- ``seqlock-ring-publish``: a function that both plain-stores payload
  into a buffer and atomically publishes a word of the same buffer
  must publish with ``__ATOMIC_RELEASE``, and no payload store may
  follow the publish — the consumer would read records the head does
  not cover yet.
- ``seqlock-raw-py-write``: the Python mirror — ``struct.pack_into``,
  ``os.pwrite``, or the private seqlock writer helpers
  (``._begin``/``._end``/``._store``) used outside the sanctioned
  writer modules. Everything else goes through Ledger/TraceRing/
  KnobChannel/IntentJournal APIs, which own the version-word
  discipline.

All C scans run over comment-and-string-blanked text (ctokens), so a
commented-out store or a protocol keyword in a docstring never fires.
"""

from __future__ import annotations

import re

from pbs_tpu.analysis.core import (
    CheckContext,
    CSourceFile,
    Finding,
    Pass,
    SourceFile,
    qualified_name,
)
from pbs_tpu.analysis.memmodel import ctokens

#: Python modules that own raw seqlock/journal writes; everything
#: else must go through their APIs (paths anchored below pbs_tpu/).
SANCTIONED_WRITERS = frozenset({
    "knobs/channel.py",
    "telemetry/ledger.py",
    "obs/trace.py",
    "gateway/journal.py",
    "runtime/doorbell.py",
})

#: Private writer-helper method names (the seqlock bracket + store
#: primitives of ledger.py / channel.py).
_WRITER_HELPERS = frozenset({"_begin", "_end", "_store"})

_BEGIN_RE = re.compile(r"\bwrite_begin\s*\(\s*(\w+)\s*\)")
_END_RE = re.compile(r"\bwrite_end\s*\(\s*(\w+)\s*\)")

#: A slot-pointer derivation: the two shapes the tree uses.
_SLOT_DECL_RE = re.compile(
    r"(?:const\s+)?uint64_t\s*\*\s*(\w+)\s*=\s*"
    r"(?:slot_ptr\s*\(|\w+\s*\+\s*\w+\s*\*\s*kSlotWords)")

#: A pointer alias via arithmetic: ``uint64_t* rec = buf + ...``.
_ALIAS_DECL_RE = re.compile(
    r"(?:const\s+)?uint64_t\s*\*\s*(\w+)\s*=\s*(\w+)\s*\+")

#: A version-word load inside a reader loop: ``v = __atomic_load_n(
#: &base[0], ORDER)``.
_VLOAD_RE = re.compile(
    r"(?:(\w+)\s*=\s*)?__atomic_load_n\s*\(\s*&\s*(\w+)\s*\[\s*0\s*\]"
    r"\s*,\s*(__ATOMIC_\w+)")


def _anchored(rel_path: str) -> str:
    parts = rel_path.replace("\\", "/").split("/")
    if "pbs_tpu" in parts:
        parts = parts[parts.index("pbs_tpu") + 1:]
    return "/".join(parts)


def _is_test(rel_path: str) -> bool:
    norm = rel_path.replace("\\", "/")
    return "tests/" in norm or norm.rsplit("/", 1)[-1].startswith("test_")


class SeqlockDisciplinePass(Pass):
    id = "seqlock-discipline"
    rules = ("seqlock-missing-release", "seqlock-plain-store",
             "seqlock-unbalanced", "seqlock-reader-protocol",
             "seqlock-ring-publish", "seqlock-raw-py-write")
    description = (
        "the file-backed seqlock memory model stays well-formed on "
        "both sides of the language boundary: C writers bracket every "
        "slot store in release-ordered write_begin/write_end pairs, "
        "reader retry loops carry acquire loads + fences + the "
        "v0==v1-and-even re-check, ring heads publish with release "
        "after the payload memcpy, and Python code outside the "
        "sanctioned writer modules never raw-writes a seqlock-backed "
        "buffer (struct.pack_into / os.pwrite / ._begin/._end/._store)")

    # -- C side ----------------------------------------------------------

    def run_c(self, csrc: CSourceFile, ctx: CheckContext) -> list[Finding]:
        text = ctokens.nocomment_text(csrc)
        out: list[Finding] = []
        for fn in ctokens.functions(text):
            if fn.name in ("write_begin", "write_end"):
                out.extend(self._check_helper(csrc, text, fn))
                continue
            out.extend(self._check_brackets(csrc, text, fn))
            out.extend(self._check_readers(csrc, text, fn))
            out.extend(self._check_publish(csrc, text, fn))
        return out

    def _check_helper(self, csrc, text, fn) -> list[Finding]:
        """write_begin/write_end must release-store the version word
        and carry a release fence."""
        out = []
        stores = [m for m in ctokens.ATOMIC_STORE_RE.finditer(fn.body)
                  if m.group(2).strip() == "0"]
        if not any(m.group(3) == "__ATOMIC_RELEASE" for m in stores):
            out.append(Finding(
                "seqlock-missing-release", csrc.rel_path, fn.line, 0,
                f"{fn.name} does not store the version word with "
                "__ATOMIC_RELEASE — the odd/even bracket orders "
                "nothing and readers can observe torn payloads",
                hint="__atomic_store_n(&s[0], v + 1, __ATOMIC_RELEASE)"))
        fences = [m.group(1)
                  for m in ctokens.FENCE_RE.finditer(fn.body)]
        if "__ATOMIC_RELEASE" not in fences:
            out.append(Finding(
                "seqlock-missing-release", csrc.rel_path, fn.line, 0,
                f"{fn.name} has no __atomic_thread_fence("
                "__ATOMIC_RELEASE) — payload stores can reorder "
                "across the version-word flip",
                hint="fence between the version store and the payload "
                     "(write_begin: after the store; write_end: "
                     "before it)"))
        return out

    def _check_brackets(self, csrc, text, fn) -> list[Finding]:
        """Stores through slot pointers stay inside write_begin/
        write_end brackets; bracket calls balance per function."""
        out = []
        guarded = {m.group(1)
                   for m in _SLOT_DECL_RE.finditer(fn.body)}
        begins = [(m.start(), m.group(1))
                  for m in _BEGIN_RE.finditer(fn.body)]
        ends = [(m.start(), m.group(1))
                for m in _END_RE.finditer(fn.body)]
        if len(begins) != len(ends):
            out.append(Finding(
                "seqlock-unbalanced", csrc.rel_path, fn.line, 0,
                f"{fn.name} calls write_begin {len(begins)}x but "
                f"write_end {len(ends)}x — some path leaves the "
                "version word odd and readers spin forever",
                hint="every write_begin(s) needs exactly one "
                     "write_end(s) on every path"))
        if not guarded:
            return out
        events = sorted(
            [(off, "begin", var) for off, var in begins]
            + [(off, "end", var) for off, var in ends]
            + [(off, "store", var)
               for off, var in ctokens.plain_stores(fn.body)
               if var in guarded])
        depth: dict[str, int] = {}
        for off, kind, var in events:
            if kind == "begin":
                depth[var] = depth.get(var, 0) + 1
            elif kind == "end":
                depth[var] = depth.get(var, 0) - 1
            elif depth.get(var, 0) <= 0:
                line = ctokens.line_of(text, fn.body_start + 1 + off)
                out.append(Finding(
                    "seqlock-plain-store", csrc.rel_path, line, 0,
                    f"{fn.name} stores into seqlock slot {var!r} "
                    "outside a write_begin/write_end bracket — a "
                    "concurrent snapshot reads the torn payload as "
                    "consistent",
                    hint=f"bracket the store: write_begin({var}); "
                         f"... write_end({var});"))
        return out

    def _check_readers(self, csrc, text, fn) -> list[Finding]:
        """Every retry loop (>= 2 version-word loads of one buffer)
        carries the full read protocol."""
        out = []
        for loop_off, lbody in ctokens.loops(fn.body):
            by_base: dict[str, list] = {}
            for m in _VLOAD_RE.finditer(lbody):
                by_base.setdefault(m.group(2), []).append(m)
            line = ctokens.line_of(text, fn.body_start + 1 + loop_off)
            for base, loads in sorted(by_base.items()):
                if len(loads) < 2:
                    continue
                for m in loads:
                    if m.group(3) != "__ATOMIC_ACQUIRE":
                        out.append(Finding(
                            "seqlock-reader-protocol", csrc.rel_path,
                            line, 0,
                            f"{fn.name}: retry-loop version load of "
                            f"{base}[0] uses {m.group(3)} — both "
                            "loads must be __ATOMIC_ACQUIRE or the "
                            "payload copy can hoist above them",
                            hint="__atomic_load_n(&s[0], "
                                 "__ATOMIC_ACQUIRE)"))
                names = [m.group(1) for m in loads if m.group(1)]
                if not any(re.search(rf"\b{re.escape(nm)}\s*&\s*1\b",
                                     lbody) for nm in names):
                    out.append(Finding(
                        "seqlock-reader-protocol", csrc.rel_path,
                        line, 0,
                        f"{fn.name}: retry loop over {base} never "
                        "rejects odd versions — it can copy a "
                        "half-written slot while the writer is inside "
                        "the bracket",
                        hint="if (v0 & 1) continue;  before the "
                             "payload copy"))
                recheck = any(
                    re.search(rf"\b{re.escape(a)}\s*[!=]=\s*"
                              rf"{re.escape(b)}\b", lbody)
                    for a in names for b in names if a != b)
                if len(names) >= 2 and not recheck:
                    out.append(Finding(
                        "seqlock-reader-protocol", csrc.rel_path,
                        line, 0,
                        f"{fn.name}: retry loop over {base} never "
                        "compares the two version reads — a write "
                        "completing mid-copy goes unnoticed",
                        hint="if (v0 == v1) return;  else retry"))
                acq_fences = [m for m in ctokens.FENCE_RE.finditer(lbody)
                              if m.group(1) == "__ATOMIC_ACQUIRE"]
                if len(acq_fences) < 2:
                    out.append(Finding(
                        "seqlock-reader-protocol", csrc.rel_path,
                        line, 0,
                        f"{fn.name}: retry loop over {base} has "
                        f"{len(acq_fences)} acquire fence(s) — the "
                        "payload copy needs one on each side to pair "
                        "with the writer's release fences",
                        hint="__atomic_thread_fence(__ATOMIC_ACQUIRE) "
                             "before and after the memcpy"))
        return out

    def _check_publish(self, csrc, text, fn) -> list[Finding]:
        """Ring-head publication: payload first, release-store last."""
        out = []
        alias = {m.group(1): m.group(2)
                 for m in _ALIAS_DECL_RE.finditer(fn.body)}

        def resolve(var: str) -> str:
            seen = set()
            while var in alias and var not in seen:
                seen.add(var)
                var = alias[var]
            return var

        plain: dict[str, list[int]] = {}
        for off, var in ctokens.plain_stores(fn.body):
            plain.setdefault(resolve(var), []).append(off)
        atomics: dict[str, list] = {}
        for m in ctokens.ATOMIC_STORE_RE.finditer(fn.body):
            atomics.setdefault(resolve(m.group(1)), []).append(m)
        for base in sorted(set(plain) & set(atomics)):
            for m in atomics[base]:
                if m.group(3) != "__ATOMIC_RELEASE":
                    line = ctokens.line_of(
                        text, fn.body_start + 1 + m.start())
                    out.append(Finding(
                        "seqlock-ring-publish", csrc.rel_path, line, 0,
                        f"{fn.name} publishes {base}[{m.group(2).strip()}]"
                        f" with {m.group(3)} while plain-storing "
                        "payload into the same buffer — consumers can "
                        "read records the head does not cover",
                        hint="publish with __ATOMIC_RELEASE after the "
                             "payload stores"))
            last_pub = max(m.start() for m in atomics[base])
            for off in plain[base]:
                if off > last_pub:
                    line = ctokens.line_of(
                        text, fn.body_start + 1 + off)
                    out.append(Finding(
                        "seqlock-ring-publish", csrc.rel_path, line, 0,
                        f"{fn.name} stores payload into {base} AFTER "
                        "publishing its head/version word — the "
                        "publish covers bytes not yet written",
                        hint="move every payload store before the "
                             "__ATOMIC_RELEASE publish"))
        return out

    # -- Python mirror ---------------------------------------------------

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        import ast

        if src.tree is None or _is_test(src.rel_path):
            return []
        anchored = _anchored(src.rel_path)
        if anchored in SANCTIONED_WRITERS or \
                anchored.startswith("analysis/"):
            return []
        out: list[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            qn = qualified_name(node.func) or ""
            attr = node.func.attr \
                if isinstance(node.func, ast.Attribute) else ""
            if qn.endswith(".pack_into") or qn == "pack_into":
                what = "struct.pack_into"
            elif qn == "os.pwrite" or qn.endswith(".pwrite"):
                what = "os.pwrite"
            elif attr in _WRITER_HELPERS:
                what = f".{attr}()"
            else:
                continue
            out.append(Finding(
                "seqlock-raw-py-write", src.rel_path, node.lineno,
                node.col_offset,
                f"raw seqlock-buffer write ({what}) outside the "
                "sanctioned writer modules — the version-word "
                "discipline lives in Ledger/TraceRing/KnobChannel/"
                "IntentJournal, and a bypass writes torn bytes no "
                "reader can detect",
                hint="go through the owning writer API (telemetry/"
                     "ledger.py, obs/trace.py, knobs/channel.py, "
                     "gateway/journal.py, runtime/doorbell.py)"))
        return out
