"""Lightweight C/C++ source scanning for the memmodel passes.

Deliberately NOT a C parser: the passes need exactly four shapes out
of ``native/*.cc`` — function bodies, integer constants (``static
const``/``constexpr``/enums), ``extern "C"`` prototypes with arities,
and token positions inside a body — and the repo's C style (clang
-format'd, no macros-defining-functions, no templates in signatures)
makes a tokenizing scan exact for them. Anything the scanner cannot
resolve it SKIPS (returns nothing) rather than guesses; the honest-
about-limits rule of docs/ANALYSIS.md applies here with force, since
a false "drift" finding against working C would teach people to
suppress the pass.

All scans run over :attr:`CSourceFile.code` with ``//`` comments
stripped (:func:`nocomment_text`), so prose like "// 38 words" never
matches a layout literal.
"""

from __future__ import annotations

import ast
import bisect
import dataclasses
import re

from pbs_tpu.analysis.core import CSourceFile

#: Control keywords that look like ``name (...) {`` but aren't
#: function definitions.
_NOT_FUNCS = frozenset({
    "if", "for", "while", "switch", "catch", "do", "else", "return",
    "sizeof",
})

_FUNC_RE = re.compile(
    r"([A-Za-z_]\w*)\s*\(([^;{}()]*(?:\([^()]*\)[^()]*)*)\)\s*(?:const\s*)?\{")

_CONST_RE = re.compile(
    r"(?:static\s+)?(?:const|constexpr)\s+"
    r"(?:unsigned\s+|signed\s+)?(?:u?int\d*_t|int|long|size_t)\s+"
    r"([A-Za-z_]\w*)\s*=\s*([^;]+);")

_ENUM_RE = re.compile(r"\benum\b[^{;]*\{([^}]*)\}", re.S)


def nocomment_text(csrc: CSourceFile) -> str:
    """The file's code with strings blanked AND // comments stripped,
    newline structure preserved (offsets map to lines)."""
    return "\n".join(csrc.code_lines())


def line_of(text: str, pos: int) -> int:
    """1-based line number of character offset ``pos`` in ``text``."""
    starts = _line_starts(text)
    return bisect.bisect_right(starts, pos)


def _line_starts(text: str) -> list[int]:
    starts = [0]
    for i, ch in enumerate(text):
        if ch == "\n":
            starts.append(i + 1)
    return starts


@dataclasses.dataclass
class CFunc:
    name: str
    params: str
    line: int          # header line (1-based)
    body_start: int    # offset of the opening { in the scan text
    body_end: int      # offset just past the closing }
    body: str          # body text between the braces


def _match_brace(text: str, open_pos: int) -> int:
    """Offset just past the } matching the { at ``open_pos``, or -1."""
    depth = 0
    for i in range(open_pos, len(text)):
        c = text[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                return i + 1
    return -1


def param_count(params: str) -> int:
    """Arity of a C parameter list (top-level comma split; ``void``
    and empty count 0)."""
    p = params.strip()
    if not p or p == "void":
        return 0
    depth = 0
    n = 1
    for c in p:
        if c in "([":
            depth += 1
        elif c in ")]":
            depth -= 1
        elif c == "," and depth == 0:
            n += 1
    return n


def functions(text: str) -> list[CFunc]:
    """Every function (or method) definition in ``text`` (the
    no-comment scan text). Bodies nested inside other bodies (lambdas
    don't exist here) are not re-reported: matches that fall inside a
    previously-matched body are skipped, so ``if (...) {`` inside a
    function never shadows it."""
    out: list[CFunc] = []
    covered_until = -1
    for m in _FUNC_RE.finditer(text):
        if m.start() < covered_until:
            continue
        name = m.group(1)
        if name in _NOT_FUNCS:
            continue
        open_pos = m.end() - 1
        end = _match_brace(text, open_pos)
        if end < 0:
            continue
        # `struct X {`-style matches can't occur (no parens); but an
        # initializer like `= {` preceded by a call match can't reach
        # here because the regex requires `)` immediately before `{`.
        out.append(CFunc(
            name=name, params=m.group(2), line=line_of(text, m.start()),
            body_start=open_pos, body_end=end,
            body=text[open_pos + 1:end - 1]))
        covered_until = end
    return out


def eval_int_expr(expr: str, env: dict[str, int]) -> int | None:
    """Integer value of a C constant expression, or None. Handles the
    repo's idioms: decimal/hex literals with ' digit separators and
    U/L suffixes, +-*/ arithmetic, parens, references to earlier
    constants (via ``env``), and unary minus. Python's own expression
    grammar covers all of that once suffixes are stripped."""
    s = expr.strip().replace("'", "")
    s = re.sub(r"\b(0[xX][0-9a-fA-F]+|\d+)[uUlL]{0,3}\b", r"\1", s)
    # C casts like (int64_t)x would confuse ast.parse; the repo's
    # layout constants don't use them — bail if present.
    try:
        node = ast.parse(s, mode="eval")
    except SyntaxError:
        return None
    return _eval_node(node.body, env)


def _eval_node(node: ast.AST, env: dict[str, int]) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.UnaryOp):
        v = _eval_node(node.operand, env)
        if v is None:
            return None
        if isinstance(node.op, ast.USub):
            return -v
        if isinstance(node.op, ast.UAdd):
            return v
        return None
    if isinstance(node, ast.BinOp):
        a = _eval_node(node.left, env)
        b = _eval_node(node.right, env)
        if a is None or b is None:
            return None
        if isinstance(node.op, ast.Add):
            return a + b
        if isinstance(node.op, ast.Sub):
            return a - b
        if isinstance(node.op, ast.Mult):
            return a * b
        if isinstance(node.op, ast.FloorDiv):
            return a // b if b else None
        if isinstance(node.op, ast.Div):
            return a // b if b and a % b == 0 else None
        if isinstance(node.op, ast.LShift):
            return a << b
        if isinstance(node.op, ast.BitOr):
            return a | b
        return None
    return None


def constants(text: str) -> tuple[dict[str, int], dict[str, int],
                                  set[int]]:
    """(env, def_lines, excluded_lines) for ``text``: every integer
    constant the file declares (static const / constexpr / enum
    members), the line each was declared on, and the full set of lines
    occupied by those declarations (the magic-literal rule must not
    flag a constant's own initializer)."""
    env: dict[str, int] = {}
    def_lines: dict[str, int] = {}
    excluded: set[int] = set()
    for m in _CONST_RE.finditer(text):
        name, expr = m.group(1), m.group(2)
        ln = line_of(text, m.start())
        excluded.update(range(ln, line_of(text, m.end()) + 1))
        val = eval_int_expr(expr, env)
        if val is not None:
            env[name] = val
            def_lines[name] = ln
    for m in _ENUM_RE.finditer(text):
        first = line_of(text, m.start())
        last = line_of(text, m.end())
        excluded.update(range(first, last + 1))
        nxt = 0
        for item in m.group(1).split(","):
            item = item.strip()
            if not item:
                continue
            if "=" in item:
                name, expr = item.split("=", 1)
                name = name.strip()
                val = eval_int_expr(expr, env)
                if val is None:
                    # Unresolvable member poisons the auto-increment
                    # chain that follows; stop rather than guess.
                    break
                nxt = val
            else:
                name, val = item, nxt
            if re.fullmatch(r"[A-Za-z_]\w*", name):
                env[name] = val
                def_lines[name] = first
                nxt = val + 1
    return env, def_lines, excluded


#: A store through an indexed lvalue: ``base[i] = / += / ...``. The
#: (?!=) guard keeps ``==`` comparisons out; ``!=``/``<=``/``>=``
#: never match because their first char isn't an assignment op.
STORE_RE = re.compile(
    r"\b([A-Za-z_]\w*)\s*\[[^\]]+\]\s*(?:[+\-|&^]|<<|>>)?=(?!=)")

#: memcpy/memset destination base variable (first identifier of the
#: first argument).
MEM_DST_RE = re.compile(
    r"\b(?:std::)?mem(?:cpy|set)\s*\(\s*&?\s*([A-Za-z_]\w*)")

ATOMIC_STORE_RE = re.compile(
    r"__atomic_store_n\s*\(\s*&\s*([A-Za-z_]\w*)\s*\[([^\]]+)\]\s*,"
    r"[^;]*?(__ATOMIC_\w+)\s*\)")

ATOMIC_LOAD_RE = re.compile(
    r"(?:\b([A-Za-z_]\w*)\s*=\s*)?__atomic_load_n\s*\(\s*&\s*"
    r"(?:\(([^()]*)\))?\s*([A-Za-z_]\w*)\s*[\[)]?[^,]*,\s*(__ATOMIC_\w+)")

FENCE_RE = re.compile(r"__atomic_thread_fence\s*\(\s*(__ATOMIC_\w+)\s*\)")


def plain_stores(body: str) -> list[tuple[int, str]]:
    """(offset-in-body, base-var) for every plain (non-atomic) store:
    indexed assignments and memcpy/memset destinations."""
    out = [(m.start(), m.group(1)) for m in STORE_RE.finditer(body)]
    out += [(m.start(), m.group(1)) for m in MEM_DST_RE.finditer(body)]
    return sorted(out)


def loops(body: str) -> list[tuple[int, str]]:
    """(offset, loop-body-text) for every for/while loop directly or
    transitively inside ``body`` — each loop's FULL body, so nested
    retry shapes are still seen as one loop."""
    out = []
    for m in re.finditer(r"\b(?:for|while)\s*\(", body):
        # Find the { after the closing paren of the loop head.
        close = _match_paren(body, m.end() - 1)
        if close < 0:
            continue
        rest = body[close:]
        bm = re.match(r"\s*\{", rest)
        if not bm:
            continue
        open_pos = close + bm.end() - 1
        end = _match_brace(body, open_pos)
        if end < 0:
            continue
        out.append((m.start(), body[open_pos + 1:end - 1]))
    return out


def _match_paren(text: str, open_pos: int) -> int:
    depth = 0
    for i in range(open_pos, len(text)):
        c = text[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return i + 1
    return -1
