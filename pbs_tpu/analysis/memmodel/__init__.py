"""Cross-language shared-memory protocol checkers (``pbst check``).

Every production layer since the telemetry ledger rides the same
file-backed seqlock protocol, implemented twice: numpy/``struct`` in
Python and ``__atomic_*`` discipline in C (native/pbst_runtime.cc).
The only guard used to be after-the-fact golden digests; these passes
make the memory model *statically checkable* the way the knob registry
made tunables checkable:

- :class:`SeqlockDisciplinePass` — the write/read protocol over
  ``native/*.cc`` (release-ordered odd/even version brackets, acquire
  retry loops, publish-after-payload ring heads) plus the Python
  mirror (no raw writes to seqlock-backed buffers outside the
  sanctioned writer modules).
- :class:`AbiLayoutDriftPass` — slot word counts, magic/ABI versions
  and field offsets diffed across the language boundary, ctypes
  binding arity cross-checked against the C prototypes, and hardcoded
  layout literals flagged — a word added on one side is a finding,
  not a torn read in production.
- :class:`DeterminismDisciplinePass` — wall-clock reads, unseeded RNG
  construction and set-iteration-order dependence inside the
  digest-covered subsystems ("same seed, same digest" is the repo
  contract; goldens only catch the bug after it ships).

See docs/ANALYSIS.md for rule tables and fix hints.
"""

from pbs_tpu.analysis.memmodel.abipass import AbiLayoutDriftPass
from pbs_tpu.analysis.memmodel.detpass import DeterminismDisciplinePass
from pbs_tpu.analysis.memmodel.seqlockpass import SeqlockDisciplinePass

#: Python modules the cross-language passes diff C layout against.
#: ``pbst check --changed`` pulls these into the scan set whenever a
#: .cc file changed, so an ABI edit is checked against its mirrors
#: even in incremental mode (paths are git-toplevel-relative).
CROSS_LANG_PY_ANCHORS = (
    "pbs_tpu/telemetry/counters.py",
    "pbs_tpu/telemetry/ledger.py",
    "pbs_tpu/obs/trace.py",
    "pbs_tpu/runtime/doorbell.py",
    "pbs_tpu/runtime/native.py",
    "pbs_tpu/sim/native_core.py",
    "pbs_tpu/knobs/channel.py",
    "pbs_tpu/gateway/journal.py",
)

__all__ = [
    "AbiLayoutDriftPass",
    "CROSS_LANG_PY_ANCHORS",
    "DeterminismDisciplinePass",
    "SeqlockDisciplinePass",
]
