"""ABI layout drift checker across the Python/C boundary.

knob-native-drift checks ONE table (the feedback marshalling words);
this pass generalizes the idea to the whole shared-memory ABI: every
slot word count, header magic and field offset that exists on both
sides of the boundary is extracted from BOTH sources and diffed, the
ctypes binding arity is cross-checked against the C prototypes, and
hardcoded layout literals are flagged — so a word added on one side
is a finding at check time, not a torn read in production. Seven
rules:

- ``abi-const-drift``: a contract pair (C constant expression vs
  Python mirror constant) with different values, including the
  auto-matched sim-core enum names shared between
  ``native/pbst_runtime.cc`` and ``sim/native_core.py``.
- ``abi-missing-const``: a contract pair declared on one side only.
- ``abi-magic-literal``: a bare integer literal (>= 16) in a ``.cc``
  file equal to a named layout constant — ``38`` instead of
  ``kSlotWords`` keeps compiling after the layout changes.
- ``abi-binding-arity``: a ``lib.X.argtypes`` list in
  ``runtime/native.py`` whose length differs from the C prototype's
  parameter count — ctypes would silently marshal garbage.
- ``abi-unknown-symbol``: the binding layer names a ``pbst_*`` symbol
  no scanned C source defines (stale binding or typo; at runtime this
  is an AttributeError only on the declaring path).
- ``abi-unbound-export``: a C ``pbst_*`` export no scanned Python
  source references — dead ABI surface, or a binding someone forgot.
- ``abi-fastcall-table``: the METH_FASTCALL method table must map
  every entry to an ``fc_<name>`` handler, and the required-symbol
  tuple in ``runtime/native.py`` must be a subset of the table (a
  stale table makes ``fastcall()`` raise and silently drop the tier).

Python constants are resolved across modules (``from pbs_tpu.x
import NAME``) with a bounded fixpoint; anything unresolvable is
skipped, never guessed. Cross-language rules only arm when both
sides are in the scan set, so ``--changed`` runs on a .py-only diff
stay cheap and a .cc diff pulls the declared anchor modules in
(runner ``changed_check_files``).
"""

from __future__ import annotations

import ast
import re

from pbs_tpu.analysis.core import (
    CheckContext,
    CSourceFile,
    Finding,
    Pass,
    SourceFile,
)
from pbs_tpu.analysis.memmodel import ctokens

#: The explicit cross-language constant contract:
#: (C expression, anchored Python module, Python name). The C side is
#: evaluated against the union constant environment of every scanned
#: .cc file (pbst_fastcall.cc #includes pbst_runtime.cc, so constants
#: span files).
CONTRACT = (
    ("kNumCounters", "telemetry/counters.py", "NUM_COUNTERS"),
    ("kHeaderWords", "telemetry/ledger.py", "HEADER_WORDS"),
    ("kSlotWords", "telemetry/ledger.py", "SLOT_WORDS"),
    ("kSlotWords * 8", "telemetry/ledger.py", "SLOT_BYTES"),
    ("kHeaderWords", "telemetry/ledger.py", "_SUMS"),
    ("kHeaderWords + kNumCounters", "telemetry/ledger.py", "_START"),
    ("kTraceHeaderWords", "obs/trace.py", "TRACE_HEADER_WORDS"),
    ("kTraceRecWords", "obs/trace.py", "TRACE_REC_WORDS"),
    ("kDoorbellHeaderWords", "runtime/doorbell.py", "HEADER_WORDS"),
    ("kDoorbellMagic", "runtime/doorbell.py", "_MAGIC"),
    ("C_STEPS", "sim/native_core.py", "_C_STEPS"),
    ("C_DEV", "sim/native_core.py", "_C_DEV"),
    ("C_HBM", "sim/native_core.py", "_C_HBM"),
    ("C_STALL", "sim/native_core.py", "_C_STALL"),
    ("C_COLL", "sim/native_core.py", "_C_COLL"),
    ("C_FLOPS", "sim/native_core.py", "_C_FLOPS"),
    ("C_TOKENS", "sim/native_core.py", "_C_TOKENS"),
    ("C_SCHED_COUNT", "sim/native_core.py", "_C_SCHED"),
    ("C_NUM", "sim/native_core.py", "_NUM_COUNTERS"),
)

#: The module whose SAME-NAMED constants are auto-diffed against the C
#: environment (GS_*/J_*/JF_*/GF_*/*_WORDS/TK_*/POL_*/SIM_ABI_VERSION
#: — the sim-core layout mirrors, declared "keep in lockstep" in both
#: files). Names on one side only are fine here (each side has private
#: helpers); value disagreement on a shared name is drift.
AUTO_MIRROR = "sim/native_core.py"

#: The ctypes/fastcall binding module (anchored).
BINDING_MOD = "runtime/native.py"

#: Bare-literal threshold: small structural numbers (0/1/2/8...) are
#: everywhere legitimately; layout constants the rule cares about
#: (word counts, arities, magics) are >= 16 in this tree.
MAGIC_MIN = 16

_INT_LIT_RE = re.compile(
    r"(?<![\w.])(0[xX][0-9a-fA-F']+|\d[\d']*)[uUlL]{0,3}(?![\w.])")


def _is_layout_name(name: str) -> bool:
    """Constants the magic-literal rule guards: layout/arity/magic
    names (kFoo, *_WORDS, C_NUM, *ABI*, *MAGIC*). Field-index enum
    members (GS_MIN_US, J_ENQ_TS, ...) are excluded — a loop bound or
    buffer index that merely equals one of those is not layout math."""
    upper = name.upper()
    return ((name[:1] == "k" and name[1:2].isupper())
            or name.endswith("_WORDS")
            or "ABI" in upper or "MAGIC" in upper
            or name == "C_NUM")

_ARGTYPES_SYM_RE = re.compile(r"^pbst_\w+$")


def _anchored(rel_path: str) -> str:
    parts = rel_path.replace("\\", "/").split("/")
    if "pbs_tpu" in parts:
        parts = parts[parts.index("pbs_tpu") + 1:]
    return "/".join(parts)


def _is_test(rel_path: str) -> bool:
    norm = rel_path.replace("\\", "/")
    return "tests/" in norm or norm.rsplit("/", 1)[-1].startswith("test_")


# -- Python constant environments -------------------------------------------


def _py_assigns(tree: ast.AST):
    """Module-level (name, value-node, line) triples plus range-tuple
    unpacks, and the from-import alias map."""
    assigns: list[tuple[str, ast.AST, int]] = []
    ranges: list[tuple[list[str], ast.AST, int]] = []
    imports: dict[str, tuple[str, str]] = {}
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                node.module.startswith("pbs_tpu.") and node.level == 0:
            below = node.module.removeprefix("pbs_tpu.")
            mod_path = below.replace(".", "/") + ".py"
            for alias in node.names:
                imports[alias.asname or alias.name] = (mod_path,
                                                       alias.name)
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if isinstance(target, ast.Name):
            assigns.append((target.id, node.value, node.lineno))
        elif isinstance(target, ast.Tuple) and \
                all(isinstance(e, ast.Name) for e in target.elts):
            names = [e.id for e in target.elts]
            if isinstance(node.value, ast.Call) and \
                    isinstance(node.value.func, ast.Name) and \
                    node.value.func.id == "range" and \
                    len(node.value.args) == 1:
                ranges.append((names, node.value.args[0], node.lineno))
            elif isinstance(node.value, ast.Tuple) and \
                    len(node.value.elts) == len(names):
                for nm, val in zip(names, node.value.elts):
                    assigns.append((nm, val, node.lineno))
    return assigns, ranges, imports


def _py_int(node: ast.AST, lookup) -> int | None:
    if isinstance(node, ast.Constant):
        if isinstance(node.value, bool) or \
                not isinstance(node.value, int):
            return None
        return node.value
    if isinstance(node, ast.Name):
        return lookup(node.id)
    if isinstance(node, ast.UnaryOp) and \
            isinstance(node.op, (ast.USub, ast.UAdd)):
        v = _py_int(node.operand, lookup)
        if v is None:
            return None
        return -v if isinstance(node.op, ast.USub) else v
    if isinstance(node, ast.BinOp):
        a = _py_int(node.left, lookup)
        b = _py_int(node.right, lookup)
        if a is None or b is None:
            return None
        if isinstance(node.op, ast.Add):
            return a + b
        if isinstance(node.op, ast.Sub):
            return a - b
        if isinstance(node.op, ast.Mult):
            return a * b
        if isinstance(node.op, ast.FloorDiv):
            return a // b if b else None
        if isinstance(node.op, ast.LShift):
            return a << b
        if isinstance(node.op, ast.BitOr):
            return a | b
    return None


def _resolve_envs(modules: dict):
    """Fixpoint constant resolution across the scanned modules.
    ``modules``: anchored path -> (assigns, ranges, imports). Returns
    anchored path -> {name: (value, line)}."""
    envs: dict[str, dict[str, tuple[int, int]]] = {
        mod: {} for mod in modules}
    for _ in range(4):  # import chains in this tree are depth <= 2
        changed = False
        for mod, (assigns, ranges, imports) in modules.items():
            env = envs[mod]

            def lookup(name, _env=env, _imports=imports):
                if name in _env:
                    return _env[name][0]
                imp = _imports.get(name)
                if imp is not None and imp[0] in envs:
                    got = envs[imp[0]].get(imp[1])
                    return got[0] if got else None
                return None

            for name, value, line in assigns:
                if name in env:
                    continue
                v = _py_int(value, lookup)
                if v is not None:
                    env[name] = (v, line)
                    changed = True
            for names, arg, line in ranges:
                if names[0] in env:
                    continue
                n = _py_int(arg, lookup)
                if n is not None and n == len(names):
                    for i, nm in enumerate(names):
                        env[nm] = (i, line)
                    changed = True
        if not changed:
            break
    return envs


# -- binding-layer extraction -----------------------------------------------


def _argtypes_len(node: ast.AST) -> int | None:
    """Statically-known length of an argtypes expression: a list, a
    concatenation of lists, or ``list * int``."""
    if isinstance(node, (ast.List, ast.Tuple)):
        return len(node.elts)
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.Add):
            a = _argtypes_len(node.left)
            b = _argtypes_len(node.right)
            return None if a is None or b is None else a + b
        if isinstance(node.op, ast.Mult):
            for seq, k in ((node.left, node.right),
                           (node.right, node.left)):
                n = _argtypes_len(seq)
                if n is not None and isinstance(k, ast.Constant) and \
                        isinstance(k.value, int):
                    return n * k.value
    return None


def _binding_decls(tree: ast.AST):
    """From runtime/native.py: ``lib.NAME.argtypes = [...]`` arities,
    every ``lib.NAME`` attribute touched, every pbst_* string literal,
    and the required-fastcall-symbol tuple (the For that iterates a
    tuple of identifier strings and ``hasattr``-probes each one — the
    restype loops in _declare() iterate symbol tuples too, but only
    the fastcall gate probes with hasattr)."""
    arities: list[tuple[str, int | None, int]] = []
    symbols: list[tuple[str, int]] = []
    required: list[tuple[str, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Attribute) and t.attr == "argtypes" \
                    and isinstance(t.value, ast.Attribute) and \
                    isinstance(t.value.value, ast.Name) and \
                    t.value.value.id == "lib":
                arities.append((t.value.attr, _argtypes_len(node.value),
                                node.lineno))
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "lib" and \
                node.attr.startswith("pbst_"):
            symbols.append((node.attr, node.lineno))
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                _ARGTYPES_SYM_RE.match(node.value):
            symbols.append((node.value, node.lineno))
        if isinstance(node, ast.For) and \
                isinstance(node.iter, ast.Tuple) and node.iter.elts and \
                all(isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                    and e.value.isidentifier()
                    for e in node.iter.elts) and \
                any(isinstance(inner, ast.Call)
                    and isinstance(inner.func, ast.Name)
                    and inner.func.id == "hasattr"
                    for stmt in node.body
                    for inner in ast.walk(stmt)):
            for e in node.iter.elts:
                required.append((e.value, node.lineno))
    return arities, symbols, required


_FC_TABLE_RE = re.compile(r'\{\s*"(\w+)"\s*,\s*\(PyCFunction\)')


class AbiLayoutDriftPass(Pass):
    id = "abi-layout-drift"
    rules = ("abi-const-drift", "abi-missing-const", "abi-magic-literal",
             "abi-binding-arity", "abi-unknown-symbol",
             "abi-unbound-export", "abi-fastcall-table")
    description = (
        "the shared-memory ABI agrees across the language boundary: "
        "slot word counts, header magics and field offsets extracted "
        "from native/*.cc match their declared Python mirrors "
        "(telemetry/obs/runtime/sim anchor modules), ctypes argtypes "
        "arity matches the C prototypes, the fastcall method table is "
        "complete, no C export is left unbound, and no .cc file "
        "hardcodes a layout constant as a bare literal")

    # -- collection ------------------------------------------------------

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None or _is_test(src.rel_path):
            return []
        anchored = _anchored(src.rel_path)
        state = ctx.state.setdefault("abi", {
            "py_modules": {}, "py_srcs": {}, "binding": None,
            "py_texts": [],
        })
        state["py_texts"].append(src.text)
        contract_mods = {c[1] for c in CONTRACT} | {AUTO_MIRROR}
        if anchored in contract_mods:
            state["py_modules"][anchored] = _py_assigns(src.tree)
            state["py_srcs"][anchored] = src
        if anchored == BINDING_MOD:
            state["binding"] = (src, _binding_decls(src.tree))
        return []

    # -- cross-language diff ---------------------------------------------

    def finalize(self, ctx: CheckContext) -> list[Finding]:
        if not ctx.c_files:
            return []
        state = ctx.state.get("abi") or {
            "py_modules": {}, "py_srcs": {}, "binding": None,
            "py_texts": [],
        }
        out: list[Finding] = []

        # Union C constant environment + per-file definition lines.
        c_env: dict[str, int] = {}
        c_lines: dict[str, tuple[str, int]] = {}
        per_file: list[tuple[CSourceFile, str, set[int]]] = []
        for csrc in ctx.c_files:
            text = ctokens.nocomment_text(csrc)
            env, def_lines, excluded = ctokens.constants(text)
            for name, val in env.items():
                c_env.setdefault(name, val)
                c_lines.setdefault(name,
                                   (csrc.rel_path, def_lines[name]))
            per_file.append((csrc, text, excluded))

        envs = _resolve_envs(state["py_modules"])
        out.extend(self._contract(state, c_env, c_lines, envs))
        out.extend(self._auto_mirror(state, c_env, c_lines, envs))
        out.extend(self._magic_literals(per_file, c_env))
        out.extend(self._bindings(state, ctx))
        out.extend(self._fastcall_table(state, ctx))
        return out

    def _contract(self, state, c_env, c_lines, envs) -> list[Finding]:
        out = []
        for c_expr, mod, py_name in CONTRACT:
            if mod not in state["py_modules"]:
                continue  # mirror not in scan set: nothing to diff
            src = state["py_srcs"][mod]
            c_val = ctokens.eval_int_expr(c_expr, c_env)
            py = envs.get(mod, {}).get(py_name)
            c_names = re.findall(r"[A-Za-z_]\w+", c_expr)
            anchor = next((c_lines[n] for n in c_names if n in c_lines),
                          None)
            if c_val is None and py is not None:
                out.append(Finding(
                    "abi-missing-const", src.rel_path, py[1], 0,
                    f"{py_name} mirrors C expression {c_expr!r} but "
                    "no scanned .cc file declares it — the layouts "
                    "can no longer be diffed",
                    hint="declare the constant in native/*.cc (or "
                         "update the contract table in "
                         "analysis/memmodel/abipass.py)"))
            elif c_val is not None and py is None:
                line = anchor[1] if anchor else 1
                path = anchor[0] if anchor else src.rel_path
                out.append(Finding(
                    "abi-missing-const", path, line, 0,
                    f"C layout constant {c_expr!r} (= {c_val}) has no "
                    f"Python mirror {py_name} in {mod} — one side of "
                    "the ABI is unchecked",
                    hint=f"declare {py_name} in {mod} (or update the "
                         "contract table)"))
            elif c_val is not None and py is not None and \
                    c_val != py[0]:
                out.append(Finding(
                    "abi-const-drift", src.rel_path, py[1], 0,
                    f"{mod}:{py_name} = {py[0]} but the C side says "
                    f"{c_expr} = {c_val} "
                    f"({anchor[0]}:{anchor[1] if anchor else '?'}) — "
                    "every reader of the shared buffer tears on this "
                    "disagreement",
                    hint="change BOTH sides together; the layout "
                         "tables are declared lockstep mirrors"))
        return out

    def _auto_mirror(self, state, c_env, c_lines, envs) -> list[Finding]:
        out = []
        if AUTO_MIRROR not in state["py_modules"]:
            return out
        src = state["py_srcs"][AUTO_MIRROR]
        env = envs.get(AUTO_MIRROR, {})
        for name in sorted(set(env) & set(c_env)):
            py_val, line = env[name]
            if py_val != c_env[name]:
                cpath, cline = c_lines[name]
                out.append(Finding(
                    "abi-const-drift", src.rel_path, line, 0,
                    f"sim-core layout word {name}: Python says "
                    f"{py_val}, C says {c_env[name]} "
                    f"({cpath}:{cline}) — the marshalled state block "
                    "and the C core disagree on where this word lives",
                    hint="the two enums are declared lockstep mirrors "
                         "(sim/native_core.py <-> "
                         "native/pbst_runtime.cc); change both"))
        return out

    def _magic_literals(self, per_file, c_env) -> list[Finding]:
        out = []
        by_val: dict[int, list[str]] = {}
        for name, val in c_env.items():
            if val >= MAGIC_MIN and _is_layout_name(name):
                by_val.setdefault(val, []).append(name)
        if not by_val:
            return out
        for csrc, text, excluded in per_file:
            for i, ln in enumerate(csrc.code_lines()):
                line_no = i + 1
                if line_no in excluded:
                    continue
                for m in _INT_LIT_RE.finditer(ln):
                    lit = m.group(1).replace("'", "")
                    val = int(lit, 16) if lit[:2].lower() == "0x" \
                        else int(lit)
                    names = by_val.get(val)
                    if not names:
                        continue
                    out.append(Finding(
                        "abi-magic-literal", csrc.rel_path, line_no,
                        m.start(),
                        f"bare literal {m.group(1)} duplicates layout "
                        f"constant {' / '.join(sorted(names))} — it "
                        "keeps compiling after the layout changes and "
                        "the buffer math silently shears",
                        hint=f"spell it {sorted(names)[0]}"))
        return out

    def _bindings(self, state, ctx) -> list[Finding]:
        out = []
        if state["binding"] is None:
            return out
        src, (arities, symbols, required) = state["binding"]
        protos: dict[str, tuple[int, str, int]] = {}
        for csrc in ctx.c_files:
            text = ctokens.nocomment_text(csrc)
            for fn in ctokens.functions(text):
                if fn.name.startswith("pbst_"):
                    protos.setdefault(
                        fn.name, (ctokens.param_count(fn.params),
                                  csrc.rel_path, fn.line))
        for name, arity, line in arities:
            proto = protos.get(name)
            if proto is None:
                continue  # abi-unknown-symbol covers it below
            if arity is not None and arity != proto[0]:
                out.append(Finding(
                    "abi-binding-arity", src.rel_path, line, 0,
                    f"lib.{name}.argtypes declares {arity} argument(s) "
                    f"but the C prototype takes {proto[0]} "
                    f"({proto[1]}:{proto[2]}) — ctypes marshals "
                    "garbage into the extra/missing slots without a "
                    "peep",
                    hint="mirror the C parameter list exactly"))
        # A .cc file's stem doubles as its CPython module name
        # (spec_from_file_location("pbst_fastcall", ...)) — not a
        # symbol the binding layer resolves against the .so.
        module_names = {
            csrc.rel_path.replace("\\", "/").rsplit("/", 1)[-1]
            .removesuffix(".cc")
            for csrc in ctx.c_files}
        for name, line in sorted(set(symbols)):
            if name in module_names:
                continue
            if name not in protos:
                out.append(Finding(
                    "abi-unknown-symbol", src.rel_path, line, 0,
                    f"binding layer references {name} but no scanned "
                    ".cc file defines it — a stale binding or a typo "
                    "(AttributeError only on the path that touches "
                    "it)",
                    hint="fix the name or add the C entry point"))
        referenced = [t for t in state["py_texts"]]
        for name in sorted(protos):
            if not any(name in t for t in referenced):
                _, cpath, cline = protos[name]
                out.append(Finding(
                    "abi-unbound-export", cpath, cline, 0,
                    f"C export {name} is referenced by no scanned "
                    "Python source — dead ABI surface, or a binding "
                    "someone forgot to declare",
                    hint="declare it in runtime/native.py _declare() "
                         "(restype/argtypes) or retire the export"))
        return out

    def _fastcall_table(self, state, ctx) -> list[Finding]:
        out = []
        table: dict[str, tuple[str, int]] = {}
        handlers: set[str] = set()
        fc_src = None
        for csrc in ctx.c_files:
            text = ctokens.nocomment_text(csrc)
            for fn in ctokens.functions(text):
                if fn.name.startswith("fc_"):
                    handlers.add(fn.name)
            # The table names live in string literals, which the scan
            # text blanks — extract from the RAW text. Entries wrap
            # (clang-format splits long ones), so match across lines.
            for m in _FC_TABLE_RE.finditer(csrc.text):
                line = csrc.text.count("\n", 0, m.start()) + 1
                table.setdefault(m.group(1), (csrc.rel_path, line))
                fc_src = csrc
        if fc_src is None:
            return out  # no fastcall table in the scan set
        for name, (path, line) in sorted(table.items()):
            if f"fc_{name}" not in handlers:
                out.append(Finding(
                    "abi-fastcall-table", path, line, 0,
                    f"method table entry {name!r} has no fc_{name} "
                    "handler in the scanned .cc sources — the module "
                    "would not compile, or the entry points at the "
                    "wrong function",
                    hint="keep the kMethods name and the fc_ handler "
                         "in lockstep"))
        if state["binding"] is not None:
            src, (_, _, required) = state["binding"]
            for name, line in sorted(set(required)):
                if name not in table:
                    out.append(Finding(
                        "abi-fastcall-table", src.rel_path, line, 0,
                        f"runtime/native.py requires fastcall symbol "
                        f"{name!r} but the method table does not "
                        "export it — fastcall() raises on import and "
                        "the whole tier silently degrades to ctypes",
                        hint="add the kMethods entry (or drop the "
                             "requirement)"))
        return out
