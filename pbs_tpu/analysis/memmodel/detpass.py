"""Determinism discipline for the digest-covered subsystems.

"Same seed, same digest" is the repo's replay contract: sim traces,
chaos schedules, scenario corpora and autopilot decisions are pinned
by golden SHA-256 digests, and CI replays them byte-for-byte. A
wall-clock read or an unseeded RNG inside one of those subsystems
breaks the contract *silently* — the digest only catches it after the
nondeterminism ships and the golden churns. This pass moves the check
to source level. Four rules, all scoped to the covered subsystems
(sim/, gateway/, scenarios/, faults/, autopilot/, serve/):

- ``det-wallclock``: ``time.time()``/``perf_counter()``/
  ``datetime.now()`` and friends. Real-clock seams are fine at the
  edges (gateway admission stamps wall time) — but they must be
  *declared*: a module-level ``REAL_CLOCK_SEAM = "<why>"`` string
  exempts the module from this rule and documents the seam.
- ``det-unseeded-rng``: ``random.Random()`` / ``default_rng()`` with
  no seed argument, ``random.SystemRandom``, and draws from the
  module-global ``random.*`` / legacy ``np.random.*`` state — all of
  which key off OS entropy or interpreter-global state the replay
  can't pin.
- ``det-urandom``: direct entropy taps — ``os.urandom``,
  ``uuid.uuid4``/``uuid1``, ``secrets.*``.
- ``det-set-iteration``: iterating a set (or joining/listing one)
  where the order can reach output — set iteration order depends on
  insertion history and hash randomization unless PYTHONHASHSEED is
  pinned, which the replay harness does not require.

The pass deliberately does NOT chase values through variables (a set
stored then sorted later is fine and common); it flags only the
syntactic shapes where the unordered iteration is direct. Honest
about limits: what it can't see, it skips.
"""

from __future__ import annotations

import ast

from pbs_tpu.analysis.core import CheckContext, Finding, Pass, SourceFile

#: First path components (under pbs_tpu/) whose behaviour is pinned by
#: golden digests. Everything else may read clocks freely.
COVERED = frozenset({
    "sim", "gateway", "scenarios", "faults", "autopilot", "serve",
})

#: Module-level ``REAL_CLOCK_SEAM = "<why>"`` declares a sanctioned
#: wall-clock seam and exempts the module from det-wallclock only.
SEAM_MARKER = "REAL_CLOCK_SEAM"

_TIME_FUNCS = frozenset({
    "time", "time_ns", "perf_counter", "perf_counter_ns",
    "monotonic", "monotonic_ns", "process_time", "process_time_ns",
    "clock_gettime", "clock_gettime_ns",
})
_DATETIME_FUNCS = frozenset({"now", "utcnow", "today"})
_GLOBAL_DRAWS = frozenset({
    "random", "randint", "randrange", "uniform", "gauss", "normalvariate",
    "choice", "choices", "sample", "shuffle", "betavariate",
    "expovariate", "lognormvariate", "vonmisesvariate", "paretovariate",
    "weibullvariate", "triangular", "getrandbits", "randbytes",
})
_NP_GLOBAL_DRAWS = frozenset({
    "random", "rand", "randn", "randint", "random_sample", "ranf",
    "sample", "uniform", "normal", "choice", "shuffle", "permutation",
    "standard_normal", "exponential", "poisson", "beta", "gamma",
    "binomial", "bytes", "seed",
})


def _anchored(rel_path: str) -> str:
    parts = rel_path.replace("\\", "/").split("/")
    if "pbs_tpu" in parts:
        parts = parts[parts.index("pbs_tpu") + 1:]
    return "/".join(parts)


def _is_test(rel_path: str) -> bool:
    norm = rel_path.replace("\\", "/")
    return "tests/" in norm or norm.rsplit("/", 1)[-1].startswith("test_")


def _qualname(node: ast.AST) -> str:
    """Dotted name of a Name/Attribute chain, '' if not a plain chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_setlike(node: ast.AST) -> bool:
    """Syntactically-definitely-a-set expression: a set display, a set
    comprehension, or a direct set()/frozenset() call."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("set", "frozenset"):
        return True
    return False


class DeterminismDisciplinePass(Pass):
    id = "determinism-discipline"
    rules = ("det-wallclock", "det-unseeded-rng", "det-urandom",
             "det-set-iteration")
    description = (
        "the digest-covered subsystems (sim/gateway/scenarios/faults/"
        "autopilot/serve) stay replayable: no wall-clock reads outside "
        "declared REAL_CLOCK_SEAM modules, no unseeded or global-state "
        "RNG, no direct entropy taps, no set-iteration-order "
        "dependence — 'same seed, same digest' checked at source "
        "level, not after the golden churns")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None or _is_test(src.rel_path):
            return []
        anchored = _anchored(src.rel_path)
        first = anchored.split("/", 1)[0]
        if first not in COVERED:
            return []
        out: list[Finding] = []

        # from-imports of clock functions: `from time import monotonic`.
        time_aliases: dict[str, str] = {}
        seam = False
        for node in ast.iter_child_nodes(src.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                for alias in node.names:
                    if alias.name in _TIME_FUNCS:
                        time_aliases[alias.asname or alias.name] = \
                            alias.name
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == SEAM_MARKER \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str) \
                    and node.value.value.strip():
                seam = True

        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                out.extend(self._check_call(node, src, time_aliases,
                                            seam))
            if isinstance(node, (ast.For, ast.AsyncFor)):
                out.extend(self._check_iter(node.iter, node.lineno,
                                            node.col_offset, src,
                                            "for loop iterates"))
            if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                 ast.GeneratorExp)):
                for gen in node.generators:
                    out.extend(self._check_iter(
                        gen.iter, gen.iter.lineno, gen.iter.col_offset,
                        src, "comprehension iterates"))
        return out

    # -- calls -----------------------------------------------------------

    def _check_call(self, node: ast.Call, src: SourceFile,
                    time_aliases: dict[str, str],
                    seam: bool) -> list[Finding]:
        out: list[Finding] = []
        q = _qualname(node.func)
        if not q:
            return out
        head, _, tail = q.rpartition(".")

        # det-wallclock ---------------------------------------------------
        if not seam:
            clock = None
            if head == "time" and tail in _TIME_FUNCS:
                clock = q
            elif not head and tail in time_aliases:
                clock = f"time.{time_aliases[tail]}"
            elif tail in _DATETIME_FUNCS and head.rpartition(".")[2] in \
                    ("datetime", "date"):
                clock = q
            if clock is not None:
                out.append(Finding(
                    "det-wallclock", src.rel_path, node.lineno,
                    node.col_offset,
                    f"{clock}() inside a digest-covered subsystem — "
                    "wall time differs every replay, so the digest "
                    "contract breaks silently",
                    hint="thread the virtual clock / recorded "
                         "timestamp through instead; if this module "
                         "really is a real-clock seam, declare "
                         'REAL_CLOCK_SEAM = "<why>" at module level'))

        # det-unseeded-rng ------------------------------------------------
        unseeded_ctor = (
            q == "random.Random" or
            (tail == "default_rng" and
             head.rpartition(".")[2] in ("random", "")))
        if unseeded_ctor and not node.args and not node.keywords:
            out.append(Finding(
                "det-unseeded-rng", src.rel_path, node.lineno,
                node.col_offset,
                f"{q}() constructed without a seed — keys off OS "
                "entropy, so two replays of the same scenario "
                "diverge",
                hint="pass the run's seed (every covered "
                     "subsystem threads one)"))
        if tail == "SystemRandom":
            out.append(Finding(
                "det-unseeded-rng", src.rel_path, node.lineno,
                node.col_offset,
                f"{q} draws from OS entropy by construction — "
                "unreplayable",
                hint="use random.Random(seed)"))
        if head == "random" and tail in _GLOBAL_DRAWS:
            out.append(Finding(
                "det-unseeded-rng", src.rel_path, node.lineno,
                node.col_offset,
                f"{q}() draws from the interpreter-global RNG — any "
                "other import can perturb the stream between replays",
                hint="draw from a locally-seeded random.Random"))
        if head in ("np.random", "numpy.random") and \
                tail in _NP_GLOBAL_DRAWS:
            out.append(Finding(
                "det-unseeded-rng", src.rel_path, node.lineno,
                node.col_offset,
                f"{q}() uses numpy's legacy global state — seeding it "
                "is process-wide action at a distance",
                hint="use np.random.default_rng(seed) held by the "
                     "caller"))

        # det-urandom -----------------------------------------------------
        if q in ("os.urandom",) or \
                (head == "uuid" and tail in ("uuid1", "uuid4")) or \
                head == "secrets" or head.startswith("secrets."):
            out.append(Finding(
                "det-urandom", src.rel_path, node.lineno,
                node.col_offset,
                f"{q}() taps OS entropy directly inside a "
                "digest-covered subsystem — ids/bytes differ every "
                "replay",
                hint="derive ids from the run seed (e.g. a counter or "
                     "a seeded Random's getrandbits)"))
        return out

    # -- set iteration ---------------------------------------------------

    def _check_iter(self, it: ast.AST, line: int, col: int,
                    src: SourceFile, what: str) -> list[Finding]:
        # Direct wrappers whose output order follows iteration order.
        target = it
        via = ""
        if isinstance(it, ast.Call):
            q = _qualname(it.func)
            if isinstance(it.func, ast.Name) and \
                    it.func.id in ("list", "tuple", "enumerate", "iter") \
                    and it.args:
                target = it.args[0]
                via = f" via {it.func.id}()"
            elif isinstance(it.func, ast.Attribute) and \
                    it.func.attr == "join" and it.args:
                target = it.args[0]
                via = " via str.join()"
            elif q in ("sorted",):
                return []  # sorted() launders the order — fine
        if not _is_setlike(target):
            return []
        return [Finding(
            "det-set-iteration", src.rel_path, line, col,
            f"{what} a set{via} — iteration order depends on hash "
            "randomization and insertion history, so anything derived "
            "from the order breaks the digest contract",
            hint="sort it first (sorted(...)) or use a list/dict, "
                 "which preserve insertion order")]
