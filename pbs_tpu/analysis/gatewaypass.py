"""Gateway-discipline pass.

Serving traffic enters through exactly one door: :class:`pbs_tpu
.gateway.Gateway`, which owns admission (tenant quotas, backpressure,
explicit shed), fair queueing across tenants, and routing with the
drain/requeue guarantee (docs/GATEWAY.md). What breaks is code
submitting straight into an engine or dispatching straight onto a
backend — that traffic is invisible to every one of those guarantees:
no quota charges it, no fairness schedules it, and a backend loss
silently drops it. Two rules, scoped to the package tree minus the
machinery (``pbs_tpu/gateway/`` implements the door; ``models/
serving.py`` implements the engine the door fronts) and tests:

- ``gw-direct-submit``: ``.submit(...)`` on an object constructed from
  ``ContinuousBatcher``/``SpeculativeBatcher`` in the same module
  (including ``self.x = ContinuousBatcher(...)`` attributes) — an
  admission bypass.
- ``gw-direct-dispatch``: a call to a backend's ``dispatch_request``
  — dispatch without routing, so nothing requeues it on backend loss.
- ``gw-lease-bypass``: a write to a token bucket's ``.level`` outside
  the gateway machinery. Under federation (docs/GATEWAY.md
  "Federation") admission state is REPLICATED: bucket levels are
  slices of one global bank, and every level change must go through
  the lease path (``LeaseBroker.grant``/``deposit``,
  ``LeasedBucket.credit``/``take``) or the federation's global-rate
  contract silently desyncs — a hand-topped bucket is minting tokens
  nobody audited.
"""

from __future__ import annotations

import ast

from pbs_tpu.analysis.core import (
    CheckContext,
    Finding,
    Pass,
    SourceFile,
    qualified_name,
)

#: Engine constructors whose instances must be fed via the gateway.
ENGINE_CTORS = {"ContinuousBatcher", "SpeculativeBatcher"}

#: Bucket constructors whose ``.level`` is replicated admission state.
BUCKET_CTORS = {"TokenBucket", "LeasedBucket", "GlobalBucket"}

#: Modules that ARE the machinery (relative to the package root).
#: The two serve backend modules (docs/SERVING.md) qualify file-by-
#: file: their engine submits happen INSIDE dispatch_request / the
#: KV-handoff path, on the far side of admission — the exact seam
#: gateway/backends.py is exempt for. The rest of serve/ is NOT
#: machinery and stays covered.
MACHINERY = ("gateway", "models/serving.py", "serve/backend.py",
             "serve/disagg.py")


def _anchored(rel_path: str) -> list[str]:
    parts = rel_path.replace("\\", "/").split("/")
    if "pbs_tpu" in parts:
        parts = parts[parts.index("pbs_tpu") + 1:]
    return parts


def _exempt(rel_path: str) -> bool:
    parts = _anchored(rel_path)
    if not parts:
        return True
    joined = "/".join(parts)
    if parts[0] == "gateway" or joined in (
            "models/serving.py", "serve/backend.py", "serve/disagg.py"):
        return True
    # Tests drive engines directly on purpose (parity/latency pins).
    norm = rel_path.replace("\\", "/")
    return "tests/" in norm or norm.rsplit("/", 1)[-1].startswith("test_")


def _ctor_name(node: ast.AST) -> str | None:
    """Last dotted segment of a Call's callee, if resolvable."""
    if not isinstance(node, ast.Call):
        return None
    qual = qualified_name(node.func)
    if qual is None:
        return None
    return qual.rsplit(".", 1)[-1]


class _EngineNames(ast.NodeVisitor):
    """First sweep: names/attributes bound to engine (and bucket)
    constructions."""

    def __init__(self) -> None:
        self.names: set[str] = set()
        self.buckets: set[str] = set()

    def _record(self, ctor: str | None, targets: list[ast.AST]) -> None:
        if ctor not in ENGINE_CTORS and ctor not in BUCKET_CTORS:
            return
        into = self.names if ctor in ENGINE_CTORS else self.buckets
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                into.add(tgt.id)
            elif isinstance(tgt, ast.Attribute):
                into.add(tgt.attr)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._record(_ctor_name(node.value), node.targets)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record(_ctor_name(node.value), [node.target])
        self.generic_visit(node)


class _GatewayScan(ast.NodeVisitor):
    def __init__(self, src: SourceFile, engine_names: set[str],
                 bucket_names: set[str]):
        self.src = src
        self.engine_names = engine_names
        self.bucket_names = bucket_names
        self.findings: list[Finding] = []

    def _base_name(self, node: ast.Attribute) -> str | None:
        base = node.value
        if isinstance(base, ast.Subscript):
            base = base.value  # buckets["t"].level — name the mapping
        if isinstance(base, ast.Name):
            return base.id
        if isinstance(base, ast.Attribute):
            return base.attr
        return None

    def _flag_level_write(self, target: ast.AST, node: ast.AST) -> None:
        if not (isinstance(target, ast.Attribute)
                and target.attr == "level"):
            return
        base = self._base_name(target)
        if base is None:
            return
        if base not in self.bucket_names and "bucket" not in base.lower():
            return
        self.findings.append(Finding(
            "gw-lease-bypass", self.src.rel_path,
            node.lineno, node.col_offset,
            "token-bucket level written outside the lease path — "
            "replicated admission state changes only through lease "
            "grant/renew/deposit, or the federation's global-rate "
            "contract silently desyncs",
            hint="route through LeaseBroker.grant/deposit or "
                 "LeasedBucket.credit (pbs_tpu.gateway.federation); "
                 "spend via the bucket's own take()"))

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._flag_level_write(tgt, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._flag_level_write(node.target, node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr == "submit":
                base = self._base_name(func)
                qual = qualified_name(func) or ""
                owner = qual.rsplit(".", 2)
                if (base in self.engine_names
                        or (len(owner) >= 2 and owner[-2] in ENGINE_CTORS)):
                    self.findings.append(Finding(
                        "gw-direct-submit", self.src.rel_path,
                        node.lineno, node.col_offset,
                        "direct engine submit bypasses the gateway — no "
                        "admission (quota/backpressure), no fair queue, "
                        "no requeue on backend loss",
                        hint="route requests through Gateway.submit "
                             "(pbs_tpu.gateway); wrap the engine in a "
                             "BatcherBackend"))
            elif func.attr == "dispatch_request":
                self.findings.append(Finding(
                    "gw-direct-dispatch", self.src.rel_path,
                    node.lineno, node.col_offset,
                    "direct backend dispatch skips routing — nothing "
                    "drains or requeues this request if the backend "
                    "dies, and no queue-delay sample is taken",
                    hint="let the gateway pump dispatch (Gateway.tick); "
                         "backends are routed least-loaded and "
                         "breaker-vetted there"))
        self.generic_visit(node)


class GatewayDisciplinePass(Pass):
    id = "gateway-discipline"
    rules = ("gw-direct-submit", "gw-direct-dispatch", "gw-lease-bypass")
    description = ("serving requests enter through the gateway front "
                   "door (admission, fair queue, routed dispatch) and "
                   "replicated admission state moves only through the "
                   "lease path; direct engine submits, backend "
                   "dispatches, and bucket-level writes outside "
                   "pbs_tpu/gateway/ are flagged")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None or _exempt(src.rel_path):
            return []
        names = _EngineNames()
        names.visit(src.tree)
        scan = _GatewayScan(src, names.names, names.buckets)
        scan.visit(src.tree)
        return scan.findings
