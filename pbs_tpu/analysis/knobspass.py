"""Knob-discipline pass: the registry stays authoritative.

The typed knob registry (``pbs_tpu/knobs/registry.py``) only means
anything if bypassing it is a CI failure — a tunable constant that
quietly reverts to a module literal is invisible to ``pbst knobs``,
to tuned-profile loads, and to hot-reload, exactly the drift Xkernel's
declared-tunable model exists to prevent (docs/KNOBS.md). Five rules:

- ``knob-unrouted``: a module-level tunable constant (UPPERCASE,
  unit-suffixed or tunable-hinted name, defined as a bare numeric
  literal — including ``N * MS`` forms) consumed inside a **hot-path
  body** (``do_schedule`` / ``wake`` / ``tick`` / ``admit`` /
  ``dispatch`` functions and their ``_``-prefixed / suffixed
  variants). The sanctioned form is
  ``NAME = knobs.default("<subsystem>...")``. Resolution follows
  ``from pbs_tpu.x import NAME`` and ``module.NAME`` references
  across the scanned tree.
- ``knob-inline-tunable``: a ``<literal> * US|MS|SEC`` expression
  inside a hot-path body — an inline magic duration no registry entry
  governs (the ``50 * MS`` retry-hint class of constant).
- ``knob-unknown``: ``knobs.default("...")`` / ``knobs.get("...")``
  naming a knob the registry does not declare — a typo that would
  otherwise surface as a KeyError at import time on some other host.
- ``knob-unit-drift``: a routed constant whose ``_ns/_us/_ms`` name
  suffix disagrees with the registry's declared unit (the time-units
  machinery applied at the registry boundary: the suffix is what the
  unit-mix checker trusts downstream, so it must match the
  declaration).
- ``knob-native-drift``: the cross-language mirror. The policy's
  ``TUNABLE_PARAMS``, the knob mapping (knobs/profile.py
  ``PARAM_KNOBS``), the registry's declared ``native=`` symbols, the
  marshalling table in ``sim/native_core.py`` (``gs[GS_X] =
  fb.param``), and the symbols in ``native/pbst_runtime.cc`` must
  agree — a knob added on one side of the C ABI without the other is
  a static finding, not a silent drift.

The pass imports ``pbs_tpu.knobs`` (stdlib-only by contract) but
nothing heavier — ``pbst check`` still runs on bare CI images.
"""

from __future__ import annotations

import ast
import os

from pbs_tpu.analysis.core import (
    CheckContext,
    Finding,
    Pass,
    SourceFile,
    qualified_name,
    unit_of_identifier,
)

#: Hot-path function-name roots (the ISSUE's inventory surface):
#: scheduler dispatch edges, pump ticks, admission, dispatch bodies.
HOT_ROOTS = ("do_schedule", "wake", "tick", "admit", "dispatch")

#: Name tokens that mark an UPPERCASE constant as a tunable even
#: without a time-unit suffix (window depths, rates, weights, ...).
TUNABLE_HINTS = frozenset({
    "WINDOW", "THRESHOLD", "RATE", "BURST", "QUANTUM", "PERIOD",
    "TTL", "BACKOFF", "WATERMARK", "RETRIES", "MARGIN", "ALPHA",
    "WEIGHT", "FRAC", "SCALE", "SLOTS", "CREDIT", "STALL",
})

#: Clock-unit names whose product with a literal is an inline duration.
CLOCK_UNITS = frozenset({"US", "MS", "SEC", "NS"})

#: The registry accessor attributes that route a constant.
ROUTE_CALLS = frozenset({"default", "get"})

#: native_core attribute -> TUNABLE_PARAMS name, where they differ.
ATTR_PARAMS = {"window_len": "window"}

#: Anchored path of the C-ABI marshaller and the policy module.
NATIVE_CORE = "sim/native_core.py"
FEEDBACK_MOD = "sched/feedback.py"


def _anchored(rel_path: str) -> str:
    parts = rel_path.replace("\\", "/").split("/")
    if "pbs_tpu" in parts:
        parts = parts[parts.index("pbs_tpu") + 1:]
    return "/".join(parts)


def _is_test(rel_path: str) -> bool:
    norm = rel_path.replace("\\", "/")
    return "tests/" in norm or norm.rsplit("/", 1)[-1].startswith("test_")


def _module_of(rel_path: str) -> str:
    """Dotted module key for cross-file resolution, anchored below the
    pbs_tpu package root so fixture trees resolve like the real one."""
    return _anchored(rel_path).removesuffix(".py").replace("/", ".")


def _is_upper(name: str) -> bool:
    return bool(name) and name[0].isalpha() and name == name.upper() \
        and any(c.isalpha() for c in name)


def tunable_shaped(name: str) -> bool:
    """Does this constant's NAME claim to be a tunable? Unit-suffixed,
    or carrying a tunable hint token."""
    if not _is_upper(name):
        return False
    if unit_of_identifier(name) is not None:
        return True
    return bool(set(name.split("_")) & TUNABLE_HINTS)


def _routed_call(node: ast.AST) -> str | None:
    """The knob name when ``node`` is a registry accessor call
    (``knobs.default("x")`` / ``knobs.get("x")`` / ``registry.default``
    / bare ``default("x")`` after a from-import), else None ("" when
    the name argument is dynamic)."""
    if not isinstance(node, ast.Call) or not node.args:
        return None
    fn = node.func
    if isinstance(fn, ast.Attribute):
        if fn.attr not in ROUTE_CALLS:
            return None
        recv = qualified_name(fn.value) or ""
        if not (recv == "knobs" or recv.endswith(".knobs")
                or recv == "registry" or recv.endswith(".registry")):
            return None
    elif isinstance(fn, ast.Name):
        # ``from pbs_tpu.knobs import default`` — rare but sanctioned.
        if fn.id not in ROUTE_CALLS:
            return None
    else:
        return None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return ""  # dynamic name: routed, but unverifiable statically


def _literal_numeric(node: ast.AST) -> bool:
    """A compile-time numeric expression: literals, +/-/* / ** trees of
    literals and unit-constant names (``500 * US``) — the module-
    constant shapes the registry exists to absorb."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool)
    if isinstance(node, ast.UnaryOp) and \
            isinstance(node.op, (ast.USub, ast.UAdd)):
        return _literal_numeric(node.operand)
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Mult, ast.Div, ast.FloorDiv, ast.Add,
                      ast.Sub, ast.Pow, ast.LShift, ast.RShift)):
        return _literal_factor(node.left) and _literal_factor(node.right)
    return False


def _literal_factor(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        # US/MS/SEC and fellow UPPERCASE constants keep the expression
        # a compile-time number.
        return _is_upper(node.id)
    return _literal_numeric(node)


def hot_function(name: str) -> bool:
    base = name.lstrip("_")
    if base in HOT_ROOTS:
        return True
    return any(base.startswith(r + "_") or base.endswith("_" + r)
               for r in HOT_ROOTS)


class _FileScan(ast.NodeVisitor):
    """One file: module-constant definitions, import aliases, hot-body
    constant uses, and the per-file rules (unknown/unit-drift/inline)."""

    def __init__(self, src: SourceFile, registry):
        self.src = src
        self.registry = registry
        self.findings: list[Finding] = []
        #: NAME -> ("literal"|"routed"|"other", knob_name|None, line)
        self.defs: dict[str, tuple[str, str | None, int]] = {}
        #: local alias -> (module, original name|None). None original =
        #: a module alias (``from pbs_tpu.sched import base``).
        self.imports: dict[str, tuple[str, str | None]] = {}
        #: (line, col, target module|None, NAME) consts read in hot
        #: bodies; module None = this file.
        self.hot_uses: list[tuple[int, int, str | None, str]] = []
        self._fn_depth = 0
        self._hot_depth = 0

    # -- module-level defs + imports -------------------------------------

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module and node.module.startswith("pbs_tpu.") \
                and node.level == 0:
            below = node.module.removeprefix("pbs_tpu.")
            for alias in node.names:
                local = alias.asname or alias.name
                if _is_upper(alias.name):
                    self.imports[local] = (below, alias.name)
                else:
                    # Possibly a module import: ``from pbs_tpu.sched
                    # import base`` — record as a module alias.
                    self.imports[local] = (f"{below}.{alias.name}", None)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._fn_depth == 0 and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                _is_upper(node.targets[0].id):
            name = node.targets[0].id
            knob_name = _routed_call(node.value)
            if knob_name is not None:
                self.defs[name] = ("routed", knob_name or None,
                                   node.lineno)
                self._check_routed(node, name, knob_name)
            elif _literal_numeric(node.value):
                self.defs[name] = ("literal", None, node.lineno)
            else:
                self.defs[name] = ("other", None, node.lineno)
        self.generic_visit(node)

    def _check_routed(self, node: ast.AST, const_name: str,
                      knob_name: str) -> None:
        if not knob_name:
            return  # dynamic name: nothing to check statically
        if not self.registry.exists(knob_name):
            self.findings.append(Finding(
                "knob-unknown", self.src.rel_path, node.lineno,
                node.col_offset,
                f"{const_name} routes through undeclared knob "
                f"{knob_name!r}",
                hint="declare it in pbs_tpu/knobs/registry.py (name, "
                     "type, unit, safe range, default, subsystem) or "
                     "fix the name"))
            return
        declared = self.registry.knob(knob_name).unit
        name_unit = unit_of_identifier(const_name)
        declared_time = declared if declared in ("ns", "us", "ms") \
            else None
        if name_unit != declared_time:
            self.findings.append(Finding(
                "knob-unit-drift", self.src.rel_path, node.lineno,
                node.col_offset,
                f"{const_name} (suffix: {name_unit or 'none'}) is "
                f"routed through {knob_name!r} declared in "
                f"{declared or 'unitless'} — downstream unit-mix "
                "checking trusts the suffix, so they must agree",
                hint="rename the constant so its _ns/_us/_ms suffix "
                     "matches the declared unit (or fix the "
                     "declaration)"))

    # -- hot bodies ------------------------------------------------------

    def _visit_fn(self, node) -> None:
        hot = hot_function(node.name)
        self._fn_depth += 1
        if hot:
            self._hot_depth += 1
        self.generic_visit(node)
        if hot:
            self._hot_depth -= 1
        self._fn_depth -= 1

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Name(self, node: ast.Name) -> None:
        if self._hot_depth > 0 and isinstance(node.ctx, ast.Load) and \
                tunable_shaped(node.id):
            self.hot_uses.append((node.lineno, node.col_offset,
                                  None, node.id))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self._hot_depth > 0 and isinstance(node.ctx, ast.Load) and \
                tunable_shaped(node.attr) and \
                isinstance(node.value, ast.Name):
            alias = self.imports.get(node.value.id)
            if alias is not None and alias[1] is None:
                # module-qualified constant: base.TSLICE_MIN_US
                self.hot_uses.append((node.lineno, node.col_offset,
                                      alias[0], node.attr))
        self.generic_visit(node)

    def visit_BinOp(self, node: ast.BinOp) -> None:
        if self._hot_depth > 0 and isinstance(node.op, ast.Mult):
            lit, unit = None, None
            for a, b in ((node.left, node.right),
                         (node.right, node.left)):
                if isinstance(a, ast.Constant) and \
                        isinstance(a.value, (int, float)) and \
                        isinstance(b, ast.Name) and b.id in CLOCK_UNITS:
                    lit, unit = a.value, b.id
            if lit is not None:
                self.findings.append(Finding(
                    "knob-inline-tunable", self.src.rel_path,
                    node.lineno, node.col_offset,
                    f"inline duration {lit} * {unit} inside a hot-path "
                    "body — a magic tunable no registry entry governs",
                    hint="declare it in pbs_tpu/knobs/registry.py and "
                         "route a module constant through "
                         "knobs.default(...) (docs/KNOBS.md)"))
        self.generic_visit(node)


class _NativeCoreScan:
    """The marshalling table of sim/native_core.py: which
    ``fb.<attr>`` values land in which GS_*/GF_* words (one level of
    local indirection followed, for the ``wlen = fb.window_len`` /
    ``gs[GS_WINDOW_LEN] = wlen`` shape)."""

    def __init__(self, tree: ast.AST):
        #: param name -> marshalling symbol
        self.pairs: dict[str, str] = {}
        var_attr: dict[str, str] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target, value = node.targets[0], node.value
            attr = self._fb_attr(value)
            if isinstance(target, ast.Name) and attr is not None:
                var_attr[target.id] = attr
            if isinstance(target, ast.Subscript) and \
                    isinstance(target.value, ast.Name) and \
                    target.value.id in ("gs", "gf") and \
                    isinstance(target.slice, ast.Name):
                sym = target.slice.id
                a = self._fb_attr(value)
                if a is None and isinstance(value, ast.Name):
                    a = var_attr.get(value.id)
                if a is not None:
                    self.pairs[ATTR_PARAMS.get(a, a)] = sym

    @staticmethod
    def _fb_attr(node: ast.AST) -> str | None:
        if isinstance(node, ast.IfExp):
            return _NativeCoreScan._fb_attr(node.body)
        if isinstance(node, ast.Call) and len(node.args) == 1:
            return _NativeCoreScan._fb_attr(node.args[0])
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "fb":
            return node.attr
        return None


def _tunable_params_of(tree: ast.AST) -> tuple[list[str], int] | None:
    """The FeedbackPolicy.TUNABLE_PARAMS tuple (statically), with its
    line, or None when the module doesn't carry one."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and \
                node.name == "FeedbackPolicy":
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and \
                        len(stmt.targets) == 1 and \
                        isinstance(stmt.targets[0], ast.Name) and \
                        stmt.targets[0].id == "TUNABLE_PARAMS" and \
                        isinstance(stmt.value, (ast.Tuple, ast.List)):
                    out = [e.value for e in stmt.value.elts
                           if isinstance(e, ast.Constant)
                           and isinstance(e.value, str)]
                    return out, stmt.lineno
    return None


class KnobDisciplinePass(Pass):
    id = "knob-discipline"
    rules = ("knob-unrouted", "knob-inline-tunable", "knob-unknown",
             "knob-unit-drift", "knob-native-drift")
    description = ("hot-path tunables route through the typed knob "
                   "registry: no literal-defined tunable constants or "
                   "inline N*MS durations in "
                   "do_schedule/wake/tick/admit/dispatch bodies, "
                   "routed constants name declared knobs with "
                   "matching unit suffixes, and the TUNABLE_PARAMS "
                   "C-ABI marshalling table (sim/native_core.py + "
                   "native/pbst_runtime.cc) mirrors the registry's "
                   "native= declarations exactly")

    def __init__(self) -> None:
        from pbs_tpu.knobs import registry

        self.registry = registry

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None or _is_test(src.rel_path):
            return []
        anchored = _anchored(src.rel_path)
        if anchored.startswith("knobs/") or anchored.startswith("analysis/"):
            return []  # the registry/checker machinery itself
        state = ctx.state.setdefault("knobs", {
            "defs": {}, "uses": [], "native": None, "tunable": None,
        })
        scan = _FileScan(src, self.registry)
        scan.visit(src.tree)
        mod = _module_of(src.rel_path)
        state["defs"][mod] = (src, scan.defs, scan.imports)
        for line, col, target_mod, name in scan.hot_uses:
            state["uses"].append(
                (src, line, col, target_mod or mod, mod, name))
        if anchored == NATIVE_CORE:
            state["native"] = (src, _NativeCoreScan(src.tree))
        if anchored == FEEDBACK_MOD:
            state["tunable"] = (src, _tunable_params_of(src.tree))
        return scan.findings

    # -- cross-file rules -------------------------------------------------

    def finalize(self, ctx: CheckContext) -> list[Finding]:
        state = ctx.state.get("knobs")
        if not state:
            return []
        findings: list[Finding] = []
        findings.extend(self._unrouted(state))
        findings.extend(self._native_drift(state))
        return findings

    def _resolve(self, state, mod: str, name: str, hops: int = 0):
        """(def_mod, kind, line) for constant ``name`` as seen from
        ``mod``, following from-imports across scanned files."""
        entry = state["defs"].get(mod)
        if entry is None or hops > 4:
            return None
        _, defs, imports = entry
        if name in defs:
            kind, _, line = defs[name]
            # A from-imported name shadows nothing here: local def wins
            # (python semantics: last binding, but module constants are
            # defined once).
            return mod, kind, line
        imp = imports.get(name)
        if imp is not None and imp[1] is not None:
            return self._resolve(state, imp[0], imp[1], hops + 1)
        return None

    def _unrouted(self, state) -> list[Finding]:
        out: list[Finding] = []
        seen: set[tuple] = set()
        for src, line, col, target_mod, use_mod, name in state["uses"]:
            resolved = self._resolve(state, target_mod, name)
            if resolved is None:
                # Not a scanned module constant: a local, a builtin,
                # or a definition outside the scanned tree.
                continue
            def_mod, kind, def_line = resolved
            if kind != "literal":
                continue
            key = (src.rel_path, line, col, name)
            if key in seen:
                continue
            seen.add(key)
            out.append(Finding(
                "knob-unrouted", src.rel_path, line, col,
                f"hot-path body reads tunable constant {name} defined "
                f"as a bare literal ({def_mod}:{def_line}) — invisible "
                "to the knob registry, pbst knobs, and hot-reload",
                hint="declare it in pbs_tpu/knobs/registry.py and "
                     f"define {name} = knobs.default(\"...\") "
                     "(docs/KNOBS.md)"))
        return out

    def _native_drift(self, state) -> list[Finding]:
        native = state.get("native")
        if native is None:
            return []  # no marshaller in this tree: nothing to mirror
        from pbs_tpu.knobs.profile import PARAM_KNOBS

        nsrc, nscan = native
        mapping = PARAM_KNOBS["feedback"]
        out: list[Finding] = []

        tunable = state.get("tunable")
        if tunable is not None and tunable[1] is not None:
            tsrc, (params, tline) = tunable
            for p in params:
                if p not in mapping:
                    out.append(Finding(
                        "knob-native-drift", tsrc.rel_path, tline, 0,
                        f"TUNABLE_PARAMS entry {p!r} has no knob "
                        "mapping (knobs/profile.py PARAM_KNOBS) — the "
                        "param is tunable but invisible to the "
                        "registry and knob files",
                        hint="declare the knob and add the param to "
                             "PARAM_KNOBS for every policy family"))
            for p in mapping:
                if p not in params:
                    out.append(Finding(
                        "knob-native-drift", tsrc.rel_path, tline, 0,
                        f"PARAM_KNOBS maps {p!r} but FeedbackPolicy."
                        "TUNABLE_PARAMS does not declare it — the "
                        "registry advertises a tunable the policy "
                        "cannot take",
                        hint="add the constructor param or drop the "
                             "mapping"))

        # Registry native= symbols <-> marshalling table.
        for p, knob_name in sorted(mapping.items()):
            if not self.registry.exists(knob_name):
                continue  # knob-unknown fires at the routed def site
            sym = self.registry.knob(knob_name).native
            got = nscan.pairs.get(p)
            if sym is not None and got is None:
                out.append(Finding(
                    "knob-native-drift", nsrc.rel_path, 1, 0,
                    f"registry declares native symbol {sym} for "
                    f"{knob_name!r} (param {p!r}) but the marshalling "
                    "table does not move fb."
                    f"{self._attr_of(p)} into it — the C core would "
                    "run a stale constant",
                    hint="marshal the param in sim/native_core.py (and "
                         "consume it in native/pbst_runtime.cc) or "
                         "declare the knob native=None"))
            elif sym is None and got is not None:
                out.append(Finding(
                    "knob-native-drift", nsrc.rel_path, 1, 0,
                    f"marshalling table moves param {p!r} into {got} "
                    f"but the registry declares {knob_name!r} with no "
                    "native symbol — a knob added on one side of the "
                    "C ABI",
                    hint=f"declare native=\"{got}\" on the knob (and "
                         "mirror it in native/pbst_runtime.cc)"))
            elif sym is not None and got != sym:
                out.append(Finding(
                    "knob-native-drift", nsrc.rel_path, 1, 0,
                    f"param {p!r} marshals into {got} but "
                    f"{knob_name!r} declares native={sym}",
                    hint="make the registry declaration and the "
                         "marshalling table agree"))

        # The C side: every declared symbol must exist in the .cc.
        cc = os.path.join(os.path.dirname(os.path.abspath(nsrc.path)),
                          os.pardir, os.pardir, "native",
                          "pbst_runtime.cc")
        if os.path.isfile(cc):
            try:
                with open(cc, encoding="utf-8", errors="replace") as f:
                    cc_text = f.read()
            except OSError:
                cc_text = None
            if cc_text is not None:
                for p, knob_name in sorted(mapping.items()):
                    if not self.registry.exists(knob_name):
                        continue
                    sym = self.registry.knob(knob_name).native
                    if sym is not None and sym not in cc_text:
                        out.append(Finding(
                            "knob-native-drift", nsrc.rel_path, 1, 0,
                            f"native symbol {sym} ({knob_name!r}) is "
                            "absent from native/pbst_runtime.cc — the "
                            "Python side marshals a word the C side "
                            "never reads",
                            hint="consume the word in the C core or "
                                 "retire the declaration on both "
                                 "sides"))
        return out

    @staticmethod
    def _attr_of(param: str) -> str:
        for attr, p in ATTR_PARAMS.items():
            if p == param:
                return attr
        return param
