"""Lock-discipline pass: the static half of lockprof/lockdep.

Three rules over the hot-path packages (``runtime/``, ``store/``,
``dist/`` — where :class:`pbs_tpu.obs.lockprof.ProfiledLock` is the
policy):

- ``lock-raw``: a raw ``threading.Lock()`` / ``threading.RLock()``
  in a hot-path module. Raw locks are invisible to lockprof contention
  stats and lockdep order validation; every framework lock must be a
  *named* ``ProfiledLock`` (or ``OrderedLock``) so the dynamic side
  can see it.
- ``lock-order``: nested ``with lock:`` acquisitions are extracted
  into a *static* lock-order graph (edge A->B = "B taken while A
  held", the same encoding ``obs.lockdep`` builds at runtime). A
  static edge that closes a cycle — against other static edges or
  against the dynamic graph exported by ``pbst lockdep --dump-graph``
  — is an AB-BA inversion reported at review time, before any thread
  ever interleaves.
- ``lock-blocking``: a blocking call (``time.sleep``, subprocess,
  socket connect, file ``open``, RPC ``.call``) inside a held-lock
  region. This is the lock-holder-preemption shape the paper's
  scheduler work exists to mitigate — holding a lock across a block
  turns every waiter into a convoy.

Static name resolution is deliberately simple: a lock is "known" when
it is assigned from a ``ProfiledLock("name")`` / ``OrderedLock("name")``
constructor to ``self.<attr>`` (class scope) or a module-level name.
``with`` items that don't resolve to a known lock are ignored — the
dynamic lockdep still covers them.
"""

from __future__ import annotations

import ast

from pbs_tpu.analysis.core import (
    CheckContext,
    Finding,
    Pass,
    SourceFile,
    qualified_name,
)

#: Packages where raw threading locks are banned (ProfiledLock policy).
HOT_PACKAGES = ("runtime", "store", "dist")

#: Constructors that produce a *named*, observability-visible lock.
NAMED_LOCK_TYPES = ("ProfiledLock", "OrderedLock")

#: Qualified call names that block the calling thread.
BLOCKING_CALLS = {
    "time.sleep": "time.sleep",
    "os.system": "subprocess spawn",
    "subprocess.run": "subprocess spawn",
    "subprocess.call": "subprocess spawn",
    "subprocess.check_call": "subprocess spawn",
    "subprocess.check_output": "subprocess spawn",
    "subprocess.Popen": "subprocess spawn",
    "socket.create_connection": "socket connect",
    "open": "file I/O",
}

#: Method names that are blocking RPC/service calls when invoked on
#: anything (the RpcClient surface is ``cli.call(...)``).
BLOCKING_METHODS = {"call": "RPC round-trip"}


def _hot_module(rel_path: str) -> bool:
    parts = rel_path.replace("\\", "/").split("/")
    if "pbs_tpu" in parts:
        parts = parts[parts.index("pbs_tpu") + 1:]
    return bool(parts) and parts[0] in HOT_PACKAGES


def _lock_ctor_name(node: ast.AST) -> str | None:
    """'name' when node is ProfiledLock("name")/OrderedLock("name")."""
    if not isinstance(node, ast.Call):
        return None
    callee = qualified_name(node.func)
    if callee is None or callee.split(".")[-1] not in NAMED_LOCK_TYPES:
        return None
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    for kw in node.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


def _is_raw_lock_ctor(node: ast.Call, raw_aliases: set[str]) -> bool:
    callee = qualified_name(node.func)
    return callee in ("threading.Lock", "threading.RLock") or \
        (callee in raw_aliases)


class _FileScan(ast.NodeVisitor):
    """Single-file scan: lock name table, with-nesting edges, raw
    ctors, blocking calls under held locks."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []
        # (scope, ident) -> lock class name; scope is the enclosing
        # class name for self-attrs, "" for module-level names.
        self.lock_names: dict[tuple[str, str], str] = {}
        # Static order edges: (outer, inner) -> (line, col).
        self.edges: dict[tuple[str, str], tuple[int, int]] = {}
        self._class_stack: list[str] = []
        self._held: list[str] = []  # named locks held at this point
        # Local names bound to threading.Lock/RLock via
        # `from threading import Lock [as L]`.
        self._raw_aliases: set[str] = set()

    # -- name table ------------------------------------------------------

    def _record_ctor(self, target: ast.AST, value: ast.AST) -> None:
        name = _lock_ctor_name(value)
        if name is None:
            return
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self" and self._class_stack:
            self.lock_names[(self._class_stack[-1], target.attr)] = name
        elif isinstance(target, ast.Name):
            self.lock_names[("", target.id)] = name

    def _resolve_lock(self, expr: ast.AST) -> str | None:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and self._class_stack:
            return self.lock_names.get((self._class_stack[-1], expr.attr))
        if isinstance(expr, ast.Name):
            return self.lock_names.get(("", expr.id))
        return None

    # -- visitors --------------------------------------------------------

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "threading":
            for alias in node.names:
                if alias.name in ("Lock", "RLock"):
                    self._raw_aliases.add(alias.asname or alias.name)
        self.generic_visit(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_ctor(t, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_ctor(node.target, node.value)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if _is_raw_lock_ctor(node, self._raw_aliases) \
                and _hot_module(self.src.rel_path):
            self.findings.append(Finding(
                "lock-raw", self.src.rel_path, node.lineno, node.col_offset,
                "raw threading lock in a hot-path module is invisible to "
                "lockprof/lockdep",
                hint='use a named ProfiledLock("<class-name>") '
                     "(pbs_tpu.obs.lockprof) so it participates in "
                     "contention stats and order validation"))
        if self._held:
            self._check_blocking(node)
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        acquired: list[str] = []
        for item in node.items:
            # The item expression evaluates while earlier items (and
            # any enclosing with) are already held — `with lock:` then
            # `with open(...)` is file I/O under the lock.
            self.visit(item.context_expr)
            name = self._resolve_lock(item.context_expr)
            if name is None:
                continue
            if self._held and self._held[-1] != name and name not in self._held:
                edge = (self._held[-1], name)
                self.edges.setdefault(
                    edge, (item.context_expr.lineno,
                           item.context_expr.col_offset))
            self._held.append(name)
            acquired.append(name)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self._held.pop()

    visit_AsyncWith = visit_With  # same acquisition semantics

    def _visit_deferred(self, node) -> None:
        # A function/lambda BODY defined under a with-lock runs when
        # called, not here — its calls are not "under the lock".
        saved, self._held = self._held, []
        self.generic_visit(node)
        self._held = saved

    visit_FunctionDef = _visit_deferred
    visit_AsyncFunctionDef = _visit_deferred
    visit_Lambda = _visit_deferred

    # -- blocking-in-lock ------------------------------------------------

    def _check_blocking(self, node: ast.Call) -> None:
        callee = qualified_name(node.func)
        kind = BLOCKING_CALLS.get(callee or "")
        if kind is None and isinstance(node.func, ast.Attribute) \
                and node.func.attr in BLOCKING_METHODS \
                and not isinstance(node.func.value, ast.Attribute):
            # Bare ``<obj>.call(...)`` — the RpcClient idiom. Attribute
            # chains (``self.timer.call``) are too ambiguous to flag.
            kind = BLOCKING_METHODS[node.func.attr]
        if kind is None:
            return
        self.findings.append(Finding(
            "lock-blocking", self.src.rel_path, node.lineno, node.col_offset,
            f"blocking call ({kind}: {callee or node.func.attr}) while "
            f"holding lock {self._held[-1]!r}",
            hint="move the blocking work outside the critical section; a "
                 "lock held across a block convoys every waiter "
                 "(lock-holder preemption)"))


def _has_path(edges: dict[str, set[str]], src: str, dst: str) -> list[str] | None:
    """DFS path src -> dst (same search obs.lockdep runs at runtime)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in sorted(edges.get(node, ())):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


class LockDisciplinePass(Pass):
    id = "lock-discipline"
    rules = ("lock-raw", "lock-order", "lock-blocking")
    description = ("raw locks in hot paths, static AB-BA order "
                   "inversions (cross-checked against the dynamic "
                   "lockdep graph), blocking calls under a held lock")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None:
            return []
        scan = _FileScan(src)
        scan.visit(src.tree)
        ctx.state.setdefault("static_lock_edges", {}).update({
            edge: (src.rel_path, pos) for edge, pos in scan.edges.items()
        })
        return scan.findings

    def finalize(self, ctx: CheckContext) -> list[Finding]:
        static: dict[tuple[str, str], tuple[str, tuple[int, int]]] = \
            ctx.state.get("static_lock_edges", {})
        findings: list[Finding] = []
        # The established graph = dynamic + static edges, built once.
        # The edge under test may stay in: a->b leaves a, and the
        # inversion search (path b -> a) terminates on reaching a, so
        # the edge can never witness its own cycle.
        graph: dict[str, set[str]] = {}
        for (x, y) in list(ctx.dynamic_lock_edges) + list(static):
            graph.setdefault(x, set()).add(y)
        for edge in sorted(static):
            a, b = edge
            cycle = _has_path(graph, b, a)
            if cycle is None:
                continue
            path, (line, col) = static[edge]
            findings.append(Finding(
                "lock-order", path, line, col,
                f"taking {b!r} while holding {a!r} inverts the "
                f"established lock order {' -> '.join(cycle)} "
                "(AB-BA deadlock possible)",
                hint="acquire these locks in one global order; see "
                     "obs/lockdep.py and docs/ANALYSIS.md"))
        return findings
