"""Hardware-counter-plane discipline pass (docs/HWTELEM.md).

PR 19 added ``pbs_tpu.hwtelem``: real kernel counter sources behind a
probed degradation ladder (perf_event → cgroup → rusage), recorded
windows, and deterministic replay. Three invariants keep that plane
honest, each mirroring a rule the tree already enforces elsewhere:

- ``hw-raw-syscall``: a raw ``perf_event_open``/``syscall(...)``
  invocation outside ``hwtelem/sources.py``. The ladder is the single
  owner of the perf ABI — attr packing, fd lifecycle, per-event errno
  interpretation, the ``PBST_HWTELEM_DISABLE`` kill switch all live
  there; a second site re-doing the syscall skips all of it (the
  counter-api single-owner rule, applied to the kernel boundary).
- ``hw-unguarded-probe``: a ``pick_tier(...)`` result consumed
  without a ``None`` branch. The ladder is OPTIONAL by contract —
  locked-down containers (``perf_event_paranoid``, missing cgroup
  controllers) legitimately yield no tier, and ``pick_tier`` returns
  None exactly there; unguarded consumers crash on the hosts the
  rusage floor exists for (the perf-native-unchecked rule, applied to
  counter tiers). Guards are recognized the same way: the bound name
  (or ``self`` attribute) in an ``if``/``while``/ternary/``assert``
  test or an ``is [not] None`` compare — per function for locals, per
  class for attributes.
- ``hw-wallclock``: a ``time.*`` clock read inside ``hwtelem/``
  outside a declared ``REAL_CLOCK_SEAM`` module. hwtelem is replay
  infrastructure — recorded windows must replay byte-identically, so
  only modules that DECLARE their live edge (the det-discipline seam
  marker, same detection) may touch the wall clock; everything else
  is handed timestamps or advances a VirtualClock.
"""

from __future__ import annotations

import ast

from pbs_tpu.analysis.core import CheckContext, Finding, Pass, SourceFile
from pbs_tpu.analysis.perfpass import (
    _anchored,
    _is_test,
    _none_guard_idents,
)

#: The one sanctioned owner of the raw perf ABI.
SYSCALL_MACHINERY = ("hwtelem/sources.py",)

#: Call names that constitute a raw perf/syscall invocation.
RAW_SYSCALLS = ("syscall", "perf_event_open")

#: The ladder probes whose result is None on locked-down hosts.
PROBE_CALLS = ("pick_tier",)

#: The det-discipline seam marker (memmodel/detpass.py): a module-level
#: non-empty string assignment to this name declares the live edge.
SEAM_MARKER = "REAL_CLOCK_SEAM"

#: Wall-clock reads (the det-wallclock set): any of these off a
#: ``time.``-rooted receiver is a live clock read.
TIME_FUNCS = frozenset({
    "time", "time_ns", "monotonic", "monotonic_ns", "perf_counter",
    "perf_counter_ns", "process_time", "process_time_ns",
    "clock_gettime", "clock_gettime_ns",
})

#: The package the wallclock rule covers.
HW_PACKAGE = "hwtelem/"


def _call_tail(func: ast.AST) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _declares_seam(tree: ast.AST) -> bool:
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == SEAM_MARKER \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str) \
                and node.value.value.strip():
            return True
    return False


def _time_aliases(tree: ast.AST) -> dict[str, str]:
    """``from time import monotonic [as m]`` bindings in this module."""
    out: dict[str, str] = {}
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name in TIME_FUNCS:
                    out[alias.asname or alias.name] = alias.name
    return out


def _is_probe_call(node: ast.Call) -> bool:
    return _call_tail(node.func) in PROBE_CALLS


class _ProbeScan:
    """hw-unguarded-probe: the _NativeScan shape (perfpass) against
    ``pick_tier`` — locals per function, ``self.X`` per class, plus
    attribute rides directly off the call."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            "hw-unguarded-probe", self.src.rel_path, node.lineno,
            node.col_offset,
            f"{what} — pick_tier() returns None when NO ladder tier "
            "works (perf_event_paranoid, missing cgroup controllers, "
            "PBST_HWTELEM_DISABLE), and this site crashes exactly on "
            "the locked-down hosts the degradation ladder exists for",
            hint="branch on the result (`if tier is not None: ...`) "
                 "and keep the no-counters path working "
                 "(hwtelem/sources.py, docs/HWTELEM.md)"))

    def scan(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Call) and \
                    _is_probe_call(node.value):
                self._flag(node, "attribute access directly on a "
                                 "pick_tier() result")
        for scope in ast.walk(tree):
            if isinstance(scope, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                self._scan_scope(scope, attr_scope=False)
            elif isinstance(scope, ast.ClassDef):
                self._scan_scope(scope, attr_scope=True)

    def _scan_scope(self, scope: ast.AST, attr_scope: bool) -> None:
        guarded = None  # lazy: most scopes never probe
        for sub in ast.walk(scope):
            if not (isinstance(sub, ast.Assign)
                    and isinstance(sub.value, ast.Call)
                    and _is_probe_call(sub.value)
                    and len(sub.targets) == 1):
                continue
            target = sub.targets[0]
            if attr_scope:
                if not (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    continue
                ident = target.attr
                what = (f"pick_tier() result stashed on self.{ident} "
                        "with no None branch anywhere in this class")
            else:
                if not isinstance(target, ast.Name):
                    continue  # self.X handled at class level
                ident = target.id
                what = (f"pick_tier() result bound to {ident!r} with "
                        "no None branch in this function")
            if guarded is None:
                guarded = _none_guard_idents(scope)
            if ident not in guarded:
                self._flag(sub, what)


class HwDisciplinePass(Pass):
    id = "hw-discipline"
    rules = ("hw-raw-syscall", "hw-unguarded-probe", "hw-wallclock")
    description = ("the hardware-counter plane stays honest: the perf "
                   "ABI has one owner (hwtelem/sources.py — no raw "
                   "perf_event_open/syscall elsewhere), every "
                   "pick_tier() consumer handles the None/locked-down "
                   "branch, and hwtelem modules read the wall clock "
                   "only behind a declared REAL_CLOCK_SEAM")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None or _is_test(src.rel_path):
            return []
        anchored = _anchored(src.rel_path)
        findings: list[Finding] = []

        if anchored not in SYSCALL_MACHINERY:
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Call) and \
                        _call_tail(node.func) in RAW_SYSCALLS:
                    findings.append(Finding(
                        "hw-raw-syscall", src.rel_path, node.lineno,
                        node.col_offset,
                        f"raw {_call_tail(node.func)}(...) outside the "
                        "ladder — hwtelem/sources.py is the single "
                        "owner of the perf ABI (attr packing, fd "
                        "lifecycle, per-event errno reasons, the "
                        "disable kill switch); a second syscall site "
                        "skips all of it",
                        hint="go through hwtelem.sources: pick_tier() "
                             "/ HwCounterSource, or extend a "
                             "CounterTier there (docs/HWTELEM.md)"))

        pscan = _ProbeScan(src)
        pscan.scan(src.tree)
        findings.extend(pscan.findings)

        if anchored.startswith(HW_PACKAGE) and \
                not _declares_seam(src.tree):
            aliases = _time_aliases(src.tree)
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                clock = None
                if isinstance(func, ast.Attribute) and \
                        isinstance(func.value, ast.Name) and \
                        func.value.id == "time" and \
                        func.attr in TIME_FUNCS:
                    clock = f"time.{func.attr}"
                elif isinstance(func, ast.Name) and func.id in aliases:
                    clock = f"time.{aliases[func.id]}"
                if clock is not None:
                    findings.append(Finding(
                        "hw-wallclock", src.rel_path, node.lineno,
                        node.col_offset,
                        f"{clock}() in an hwtelem module with no "
                        "declared REAL_CLOCK_SEAM — recorded windows "
                        "must replay byte-identically, so only "
                        "modules that declare their live sampling "
                        "edge may read the wall clock",
                        hint="take timestamps as arguments / advance "
                             "a VirtualClock from recorded deltas, or "
                             "declare the seam: REAL_CLOCK_SEAM = "
                             "\"<why this module reads live time>\" "
                             "(hwtelem/sources.py, docs/HWTELEM.md)"))
        return findings
