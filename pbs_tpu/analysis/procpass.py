"""Process-discipline pass.

Process mode (docs/GATEWAY.md "Process mode") concentrates every raw
process primitive in ONE module: ``gateway/supervisor.py`` owns the
spawn (:class:`~pbs_tpu.gateway.supervisor.ProcessHandle`), the
``SIGKILL``, and the reap. Everything else holds handles and speaks
rpc. What breaks when that discipline slips:

- a stray ``os.kill``/``signal`` call is an unsupervised death — the
  liveness state machine never records it, so no restart, no drain,
  no handoff, and the member's journal fd may stay held by a
  half-dead process;
- a spawned process that is never joined lingers as a zombie on the
  1-vCPU CI box until the parent exits (and its exit code — the
  SIGKILL evidence — is lost);
- an :class:`~pbs_tpu.dist.rpc.RpcClient` built without ``deadline_s``
  has per-attempt timeouts but NO bound on the whole retry loop — a
  flaky peer can pin a supervision pump for minutes
  (``federation.proc.rpc_deadline_ns`` exists precisely so every
  parent→member op sheds instead of hanging).

Three rules, tree-wide (the supervisor module is the machinery
exemption for the first two; ``dist/rpc.py`` implements the client and
is exempt from the third):

- ``proc-raw-kill``: ``os.kill`` / ``os.killpg`` / ``os.fork`` /
  ``signal.signal`` / ``signal.pthread_kill`` outside the supervisor.
- ``proc-unreaped-spawn``: a ``subprocess.Popen`` / ``Process(...)``
  spawn in a function that never joins/waits/reaps the handle.
- ``proc-undeadlined-client``: an ``RpcClient(...)`` construction
  without an explicit ``deadline_s=``.
"""

from __future__ import annotations

import ast

from pbs_tpu.analysis.core import (
    CheckContext,
    Finding,
    Pass,
    SourceFile,
    qualified_name,
)

#: The one module allowed to touch raw process primitives.
MACHINERY = ("gateway/supervisor.py",)

#: The transport implementation (deadline plumbing lives here).
RPC_MACHINERY = ("dist/rpc.py",)

#: Raw signal/fork primitives and why each is unsupervised.
RAW_KILL_CALLS = {
    "os.kill": "a signal the supervisor never records",
    "os.killpg": "a process-group signal the supervisor never records",
    "os.fork": "a fork outside the spawn-context discipline (inherits "
               "the parent's threads and locks)",
    "signal.signal": "a handler installed behind the supervisor's back",
    "signal.pthread_kill": "a thread signal the supervisor never "
                           "records",
}

#: Spawn constructors that hand back a process handle needing a reap.
SPAWN_CALLS = ("subprocess.Popen",)

#: Method/attr names that count as reaping a spawned handle.
REAP_NAMES = {"join", "wait", "communicate", "reap", "kill9"}


def _anchored(rel_path: str) -> list[str]:
    parts = rel_path.replace("\\", "/").split("/")
    if "pbs_tpu" in parts:
        parts = parts[parts.index("pbs_tpu") + 1:]
    return parts


def _is_machinery(rel_path: str, machinery: tuple[str, ...]) -> bool:
    return "/".join(_anchored(rel_path)) in machinery


def _is_spawn(node: ast.Call) -> bool:
    qual = qualified_name(node.func)
    if qual in SPAWN_CALLS:
        return True
    # mp_context.Process(...) / multiprocessing.Process(...): spawn by
    # any name — the ctor attribute is the stable signature.
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr == "Process")


class _Scan(ast.NodeVisitor):
    def __init__(self, src: SourceFile, skip_raw: bool):
        self.src = src
        self.skip_raw = skip_raw
        self.findings: list[Finding] = []
        #: Spawn call sites within the current function scope.
        self._spawns: list[list[ast.Call]] = []
        #: Did the current function scope reap anything?
        self._reaps: list[bool] = []

    # -- function scopes -------------------------------------------------

    def _visit_func(self, node) -> None:
        self._spawns.append([])
        self._reaps.append(False)
        self.generic_visit(node)
        spawns = self._spawns.pop()
        reaped = self._reaps.pop()
        if not reaped:
            for call in spawns:
                self.findings.append(Finding(
                    "proc-unreaped-spawn", self.src.rel_path,
                    call.lineno, call.col_offset,
                    "spawned process handle is never joined/waited in "
                    "this function — it lingers as a zombie and its "
                    "exit code is lost",
                    hint="hold a gateway.supervisor.ProcessHandle and "
                         "reap() it, or join()/wait() the handle on "
                         "every path"))

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- calls -----------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        qual = qualified_name(node.func)
        if not self.skip_raw and qual in RAW_KILL_CALLS:
            self.findings.append(Finding(
                "proc-raw-kill", self.src.rel_path, node.lineno,
                node.col_offset,
                f"raw process primitive {qual}() outside the "
                f"supervisor — {RAW_KILL_CALLS[qual]}",
                hint="route process lifecycle through gateway."
                     "supervisor.ProcessHandle (kill9/reap); it is "
                     "the one module allowed raw primitives"))
        if not self.skip_raw and self._spawns and _is_spawn(node):
            self._spawns[-1].append(node)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in REAP_NAMES and self._reaps:
            self._reaps[-1] = True
        if (qual or "").split(".")[-1] == "RpcClient":
            has_deadline = any(
                kw.arg == "deadline_s" or kw.arg is None  # **kwargs
                for kw in node.keywords)
            if not has_deadline:
                self.findings.append(Finding(
                    "proc-undeadlined-client", self.src.rel_path,
                    node.lineno, node.col_offset,
                    "RpcClient built without deadline_s — per-attempt "
                    "timeouts bound one try, nothing bounds the whole "
                    "retry loop",
                    hint="pass deadline_s= (knob federation.proc."
                         "rpc_deadline_ns for supervision paths) or "
                         "an explicit per-call _deadline at every "
                         "call site"))
        self.generic_visit(node)


class ProcessDisciplinePass(Pass):
    id = "process-discipline"
    rules = ("proc-raw-kill", "proc-unreaped-spawn",
             "proc-undeadlined-client")
    description = ("raw process primitives live in gateway/supervisor "
                   "only; spawned handles must be reaped; RpcClient "
                   "constructions carry a whole-call deadline")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None:
            return []
        skip_raw = _is_machinery(src.rel_path, MACHINERY)
        if _is_machinery(src.rel_path, RPC_MACHINERY):
            return []
        scan = _Scan(src, skip_raw)
        scan.visit(src.tree)
        return scan.findings
