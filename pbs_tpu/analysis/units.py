"""Time-unit consistency pass.

The repo's convention (utils/clock.py): all times are integer
nanoseconds; names carry their unit as a suffix — ``RUNQ_WAIT_NS``,
``CSCHED_DEFAULT_TSLICE_US``, ``acct_period_us``, ``timeout_ms`` — and
conversions go through the named constants ``US``/``MS``/``SEC`` (or an
explicit numeric factor). A ``_ns`` value added to a ``_us`` value with
no conversion in sight is a silent 1000x bug; this pass catches it at
review time.

Rule ``unit-mix`` fires when two operands whose *names* carry different
unit suffixes meet in an add/subtract, a comparison (including
``min``/``max`` arguments), an assignment, or a keyword argument —
**unless** the mixing expression contains an explicit conversion (a
multiply/divide by ``US``/``MS``/``SEC``/``NS_PER_*`` or a numeric
literal), which marks the conversion as deliberate.

The checker infers units, it does not track them through data flow: a
converted value stored under the right suffix (``ran_us = ran_ns / US``)
is clean by construction, which is exactly the convention the codebase
already follows.
"""

from __future__ import annotations

import ast
import re

from pbs_tpu.analysis.core import (
    CheckContext,
    Finding,
    Pass,
    SourceFile,
    identifier_of,
    unit_of_identifier,
)

#: Names whose presence in a multiply/divide marks an explicit
#: conversion (utils/clock.py constants + the *_PER_* idiom).
_CONVERSION_NAME = re.compile(
    r"^(NS|US|MS|SEC|SECS?|HZ)$|_PER_|^(NSEC|USEC|MSEC)S?$")


def _is_conversion_factor(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        return node.value not in (0,)  # *1 is still a declared factor
    ident = identifier_of(node)
    if ident is not None and _CONVERSION_NAME.search(ident):
        return True
    if isinstance(node, ast.BinOp):
        return _is_conversion_factor(node.left) or \
            _is_conversion_factor(node.right)
    return False


def unit_of_expr(node: ast.AST) -> str | None:
    """Best-effort unit of an expression; None = unknown/converted."""
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, (ast.Mult, ast.Div, ast.FloorDiv, ast.Mod)):
            # A multiply/divide is where conversions happen; once a
            # factor is involved the result's unit is declared by
            # whatever name it lands in, not inferred here.
            if _is_conversion_factor(node.left) or \
                    _is_conversion_factor(node.right):
                return None
            return unit_of_expr(node.left) or unit_of_expr(node.right)
        if isinstance(node.op, (ast.Add, ast.Sub)):
            return unit_of_expr(node.left) or unit_of_expr(node.right)
        return None
    if isinstance(node, ast.UnaryOp):
        return unit_of_expr(node.operand)
    if isinstance(node, ast.Constant):
        return None
    if isinstance(node, ast.Call):
        # int(x_ns), float(x_ns), np.uint64(x_ns): unit-preserving casts.
        fn = node.func
        cast = (isinstance(fn, ast.Name) and fn.id in ("int", "float", "abs")) \
            or (isinstance(fn, ast.Attribute)
                and fn.attr in ("uint64", "int64", "float64"))
        if cast and len(node.args) == 1:
            return unit_of_expr(node.args[0])
        return None
    ident = identifier_of(node)
    if ident is None:
        return None
    return unit_of_identifier(ident)


def _contains_conversion(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.BinOp) and \
                isinstance(sub.op, (ast.Mult, ast.Div, ast.FloorDiv)):
            if _is_conversion_factor(sub.left) or \
                    _is_conversion_factor(sub.right):
                return True
    return False


class _UnitScan(ast.NodeVisitor):
    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []

    def _flag(self, node: ast.AST, a: str, b: str, what: str) -> None:
        self.findings.append(Finding(
            "unit-mix", self.src.rel_path, node.lineno, node.col_offset,
            f"{what} mixes time units: {a} vs {b} with no explicit "
            "conversion",
            hint="convert through utils.clock constants (US/MS/SEC) or "
                 "rename so the suffix matches the actual unit"))

    def _check_pair(self, node: ast.AST, left: ast.AST, right: ast.AST,
                    what: str) -> None:
        ua, ub = unit_of_expr(left), unit_of_expr(right)
        if ua is not None and ub is not None and ua != ub:
            if not (_contains_conversion(left) or _contains_conversion(right)):
                self._flag(node, ua, ub, what)

    def visit_BinOp(self, node: ast.BinOp) -> None:
        if isinstance(node.op, (ast.Add, ast.Sub)):
            self._check_pair(node, node.left, node.right, "arithmetic")
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        prev = node.left
        for cmp in node.comparators:
            self._check_pair(node, prev, cmp, "comparison")
            prev = cmp
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # min()/max() compare their arguments.
        if isinstance(node.func, ast.Name) and node.func.id in ("min", "max") \
                and len(node.args) >= 2:
            for other in node.args[1:]:
                self._check_pair(node, node.args[0], other,
                                 f"{node.func.id}() argument")
        # Keyword arguments: f(period_ns=x_us) is an interface-crossing
        # unit bug the callee can never catch.
        for kw in node.keywords:
            if kw.arg is None:
                continue
            want = unit_of_identifier(kw.arg)
            got = unit_of_expr(kw.value)
            if want is not None and got is not None and want != got \
                    and not _contains_conversion(kw.value):
                self._flag(kw.value, got, f"{kw.arg}= ({want})",
                           "keyword argument")
        self.generic_visit(node)

    def _check_assign(self, node: ast.AST, target: ast.AST,
                      value: ast.AST) -> None:
        ident = identifier_of(target)
        if ident is None:
            return
        want = unit_of_identifier(ident)
        got = unit_of_expr(value)
        if want is not None and got is not None and want != got \
                and not _contains_conversion(value):
            self._flag(node, got, f"{ident} ({want})", "assignment")

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_assign(node, t, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_assign(node, node.target, node.value)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if isinstance(node.op, (ast.Add, ast.Sub)):
            self._check_assign(node, node.target, node.value)
        self.generic_visit(node)


class TimeUnitPass(Pass):
    id = "time-units"
    rules = ("unit-mix",)
    description = ("_NS/_US/_MS suffix consistency: arithmetic, "
                   "comparisons, assignments, and keyword args mixing "
                   "units without an explicit conversion")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None:
            return []
        scan = _UnitScan(src)
        scan.visit(src.tree)
        return scan.findings
