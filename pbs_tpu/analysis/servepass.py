"""Serve-discipline pass: the partition rule table stays honest and
mesh-axis names stay in one place.

The serving tier (docs/SERVING.md) partitions parameters by an ordered
regex rule table (pbs_tpu/serve/partition.py): first match wins, an
unmatched leaf is a hard error at construction. Two rot modes are
invisible at runtime and need a checker:

- ``serve-unmatched-rule``: a rule in a ``*_RULES`` table that is DEAD
  (matches none of the module's ``TEMPLATE_PATHS`` flagship paths) or
  SHADOWED (every path it matches was already claimed by an earlier
  rule), or a template path no rule covers. A dead rule is usually a
  typo'd regex that silently stopped placing a weight family; a
  shadowed rule means the table's ORDER no longer does what its
  author believed; an uncovered path is a construction-time crash
  waiting for the next model. The table and paths are extracted as
  AST literals, so the check runs with no jax anywhere in sight.
- ``serve-raw-mesh-axis``: a mesh-axis name string literal inside a
  ``PartitionSpec`` / ``P`` / ``NamedSharding`` / ``Mesh`` /
  ``make_mesh`` call outside ``pbs_tpu/parallel/`` and
  ``pbs_tpu/serve/partition.py``. Axis names are topology facts with
  exactly two homes: the parallel layer that defines layouts and the
  serve partition table that maps rules onto them POSITIONALLY. A
  raw ``"tp"`` anywhere else couples that module to one mesh shape
  and rots the moment the mesh is renamed or reshaped — route it
  through a ``parallel/sharding.py`` helper (the serving KV cache's
  ``slot_cache_kv_sharding`` is the template).
"""

from __future__ import annotations

import ast
import re

from pbs_tpu.analysis.core import (
    CheckContext,
    Finding,
    Pass,
    SourceFile,
    qualified_name,
)

#: Call surfaces whose positional string literals are mesh-axis names.
_AXIS_CALLS = ("PartitionSpec", "P", "NamedSharding", "Mesh", "make_mesh")


def _anchored(rel_path: str) -> str:
    parts = rel_path.replace("\\", "/").split("/")
    if "pbs_tpu" in parts:
        parts = parts[parts.index("pbs_tpu") + 1:]
    return "/".join(parts)


def _is_test_path(rel_path: str) -> bool:
    norm = rel_path.replace("\\", "/")
    return "tests/" in norm or \
        norm.rsplit("/", 1)[-1].startswith("test_")


def _axis_exempt(anchored: str) -> bool:
    """The two legitimate axis-name homes (module docstring)."""
    return anchored.startswith("parallel/") or \
        anchored == "serve/partition.py"


def _literal(node: ast.AST):
    """ast.literal_eval that swallows non-literals (dynamic tables are
    out of scope for a static table audit)."""
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


class _AxisScan(ast.NodeVisitor):
    """Flags string literals in positional args of the axis-call
    surfaces — recursing through tuple/list/dict containers (dict keys
    are ``make_mesh``'s axis names) but NOT into keyword arguments
    (``memory_kind=...`` and friends are not axis names)."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []

    def _scan(self, node: ast.AST, call: str) -> None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            self.findings.append(Finding(
                check="serve-raw-mesh-axis",
                path=self.src.rel_path,
                line=node.lineno, col=node.col_offset,
                message=f"raw mesh-axis name {node.value!r} in a "
                        f"{call}(...) call outside the parallel layer",
                hint="axis names live in pbs_tpu/parallel/ (layout "
                     "helpers like slot_cache_kv_sharding) or the "
                     "positional rule table in serve/partition.py; "
                     "a literal here couples this module to one mesh "
                     "shape (docs/SERVING.md)",
            ))
            return
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for e in node.elts:
                self._scan(e, call)
        elif isinstance(node, ast.Dict):
            for k in node.keys:
                if k is not None:
                    self._scan(k, call)

    def visit_Call(self, node: ast.Call) -> None:
        qual = qualified_name(node.func)
        if qual is not None:
            leaf = qual.rsplit(".", 1)[-1]
            if leaf in _AXIS_CALLS:
                for arg in node.args:
                    self._scan(arg, leaf)
        self.generic_visit(node)


def _audit_table(rules, paths, line_of_rule, table_line,
                 rel_path: str) -> list[Finding]:
    """First-match-wins claim tracking, the static twin of
    ``pbs_tpu.serve.partition.audit_rules`` (kept jax-free here on
    purpose — the runtime auditor imports the partition module, which
    imports jax)."""
    findings: list[Finding] = []
    compiled: list[tuple[int, "re.Pattern | None"]] = []
    for i, entry in enumerate(rules):
        pat = entry[0]
        try:
            compiled.append((i, re.compile(pat)))
        except re.error as e:
            findings.append(Finding(
                check="serve-unmatched-rule", path=rel_path,
                line=line_of_rule(i), col=0,
                message=f"partition rule {pat!r} does not compile: {e}",
                hint="every rule regex must compile; a broken rule "
                     "silently stops placing its weight family"))
            compiled.append((i, None))
    claimed: dict[str, int] = {}
    matched_any = [False] * len(rules)
    matched_fresh = [False] * len(rules)
    for path in paths:
        for i, rx in compiled:
            if rx is None or rx.search(path) is None:
                continue
            matched_any[i] = True
            if path not in claimed:
                claimed[path] = i
                matched_fresh[i] = True
    for i, entry in enumerate(rules):
        if compiled[i][1] is None:
            continue
        if not matched_any[i]:
            findings.append(Finding(
                check="serve-unmatched-rule", path=rel_path,
                line=line_of_rule(i), col=0,
                message=f"dead partition rule {entry[0]!r}: matches no "
                        "template path",
                hint="delete it or fix the regex — a dead rule is "
                     "usually a typo that stopped placing a weight "
                     "family (TEMPLATE_PATHS is the coverage "
                     "universe)"))
        elif not matched_fresh[i]:
            findings.append(Finding(
                check="serve-unmatched-rule", path=rel_path,
                line=line_of_rule(i), col=0,
                message=f"shadowed partition rule {entry[0]!r}: every "
                        "path it matches is claimed by an earlier rule",
                hint="first match wins — reorder the table or delete "
                     "the rule; a shadowed rule means the order no "
                     "longer does what it reads as doing"))
    uncovered = [p for p in paths if p not in claimed]
    if uncovered:
        findings.append(Finding(
            check="serve-unmatched-rule", path=rel_path,
            line=table_line, col=0,
            message="template path(s) no rule covers: "
                    + ", ".join(repr(p) for p in uncovered),
            hint="an uncovered non-scalar leaf is a hard error at "
                 "backend construction (match_partition_rules); add "
                 "a rule or drop the path"))
    return findings


class ServeDisciplinePass(Pass):
    id = "serve-discipline"
    rules = ("serve-unmatched-rule", "serve-raw-mesh-axis")
    description = ("the serving tier's partition rule table stays "
                   "honest (no dead/shadowed rules, no uncovered "
                   "template path) and mesh-axis name literals stay "
                   "inside parallel/ + serve/partition.py")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None or _is_test_path(src.rel_path):
            return []
        anchored = _anchored(src.rel_path)
        findings: list[Finding] = []
        if not _axis_exempt(anchored):
            scan = _AxisScan(src)
            scan.visit(src.tree)
            findings.extend(scan.findings)
        # Rule-table audit: any module declaring both a *_RULES literal
        # and a TEMPLATE_PATHS literal at top level opts in (the serve
        # partition module is the flagship; fixture twins mirror it).
        tables: list[tuple[ast.AST, object]] = []
        paths = None
        paths_line = 1
        for node in src.tree.body:
            targets = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                if t.id.endswith("_RULES"):
                    tables.append((value, _literal(value)))
                elif t.id == "TEMPLATE_PATHS":
                    paths = _literal(value)
                    paths_line = node.lineno
        if paths is None:
            return findings
        for value_node, rules in tables:
            if not isinstance(rules, (tuple, list)) or not all(
                    isinstance(e, (tuple, list)) and len(e) >= 1
                    and isinstance(e[0], str) for e in rules):
                continue
            elt_lines = [e.lineno for e in value_node.elts] \
                if isinstance(value_node, (ast.Tuple, ast.List)) else []

            def line_of_rule(i: int, _lines=elt_lines,
                             _fallback=value_node.lineno) -> int:
                return _lines[i] if i < len(_lines) else _fallback

            findings.extend(_audit_table(
                rules, tuple(paths), line_of_rule,
                paths_line, src.rel_path))
        return findings
