"""Rollout-discipline pass: production knob writes go through the
guarded rollout path.

The knob registry made every tunable declared and every push validated
(docs/KNOBS.md); the autopilot made production pushes GUARDED — a
candidate reaches the fleet only through the canary controller's
scoped-push → SLO-burn guard → promote/rollback protocol
(docs/AUTOPILOT.md). Both guarantees evaporate if any other module
writes knobs directly: a raw ``channel.push`` skips the canary scoping
and the guard window entirely, and a ``set_local`` silently forks a
process's view away from the channel every consumer watches. Two
rules:

- ``rollout-push``: a ``.push(...)`` call on a knob channel (an object
  constructed from ``KnobChannel.create``/``KnobChannel.attach`` in
  the same module, including ``self.x = KnobChannel...`` attributes
  and direct ``KnobChannel.create(p).push(...)`` chains) outside the
  sanctioned writers.
- ``rollout-set-local``: a call to the registry's ``set_local``
  (however imported: ``knobs.set_local``, ``registry.set_local``, or
  the bare name from either module) outside the sanctioned writers.

Sanctioned writers: ``knobs/`` (the machinery), ``autopilot/canary.py``
(THE production rollout path), ``cli/`` (the operator's explicit
hands, ``pbst knobs set``), ``analysis/`` (this checker's own
fixtures/tooling), and tests. The chaos harness's mid-run knob plan
keeps a justified line suppression — it is the adversary, not a
production writer (docs/ANALYSIS.md).
"""

from __future__ import annotations

import ast

from pbs_tpu.analysis.core import (
    CheckContext,
    Finding,
    Pass,
    SourceFile,
    qualified_name,
)

#: Channel constructor classmethods whose result is a knob channel.
CHANNEL_CTORS = {"KnobChannel.create", "KnobChannel.attach"}

#: Modules of the registry whose ``set_local`` is the guarded surface.
SET_LOCAL_MODULES = ("pbs_tpu.knobs", "pbs_tpu.knobs.registry")


def _anchored(rel_path: str) -> str:
    parts = rel_path.replace("\\", "/").split("/")
    if "pbs_tpu" in parts:
        parts = parts[parts.index("pbs_tpu") + 1:]
    return "/".join(parts)


def _exempt(rel_path: str) -> bool:
    anchored = _anchored(rel_path)
    if anchored.startswith(("knobs/", "cli/", "analysis/")) \
            or anchored == "autopilot/canary.py":
        return True
    norm = rel_path.replace("\\", "/")
    return "tests/" in norm or norm.rsplit("/", 1)[-1].startswith("test_")


def _is_channel_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    qual = qualified_name(node.func)
    if qual is None:
        return False
    # Match on the trailing "KnobChannel.create" segments so aliased
    # module prefixes (pbs_tpu.knobs.channel.KnobChannel.create, a
    # bare KnobChannel import, ...) all resolve.
    parts = qual.split(".")
    return len(parts) >= 2 and ".".join(parts[-2:]) in CHANNEL_CTORS


class _Taint(ast.NodeVisitor):
    """First sweep: names/attributes bound to knob-channel
    constructions, plus the module's set_local aliases."""

    def __init__(self) -> None:
        self.channels: set[str] = set()
        self.set_local_names: set[str] = set()
        self.knobs_modules: set[str] = set()

    def _record(self, value: ast.AST, targets: list[ast.AST]) -> None:
        if not _is_channel_ctor(value):
            return
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                self.channels.add(tgt.id)
            elif isinstance(tgt, ast.Attribute):
                self.channels.add(tgt.attr)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._record(node.value, node.targets)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record(node.value, [node.target])
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module in SET_LOCAL_MODULES:
            for alias in node.names:
                if alias.name == "set_local":
                    self.set_local_names.add(alias.asname or alias.name)
        if node.module == "pbs_tpu":
            for alias in node.names:
                if alias.name == "knobs":
                    self.knobs_modules.add(alias.asname or "knobs")
        if node.module == "pbs_tpu.knobs":
            for alias in node.names:
                if alias.name == "registry":
                    self.knobs_modules.add(alias.asname or "registry")
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name in SET_LOCAL_MODULES:
                self.knobs_modules.add(alias.asname or alias.name)
        self.generic_visit(node)


class _RolloutScan(ast.NodeVisitor):
    def __init__(self, src: SourceFile, taint: _Taint):
        self.src = src
        self.taint = taint
        self.findings: list[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "push":
            base = fn.value
            base_name = None
            if isinstance(base, ast.Name):
                base_name = base.id
            elif isinstance(base, ast.Attribute):
                base_name = base.attr
            tainted = (base_name in self.taint.channels
                       or _is_channel_ctor(base))
            if tainted:
                self.findings.append(Finding(
                    check="rollout-push",
                    path=self.src.rel_path,
                    line=node.lineno, col=node.col_offset,
                    message="knob channel push outside the guarded "
                            "rollout path",
                    hint="production knob writes go through the "
                         "canary controller (pbs_tpu/autopilot/"
                         "canary.py) or the operator CLI — a raw "
                         "push skips canary scoping and the "
                         "SLO-burn guard (docs/AUTOPILOT.md)",
                ))
        qual = qualified_name(fn)
        if qual is not None:
            parts = qual.split(".")
            is_set_local = (
                qual in self.taint.set_local_names
                or (len(parts) >= 2 and parts[-1] == "set_local"
                    and (parts[-2] in ("knobs", "registry")
                         or ".".join(parts[:-1])
                         in self.taint.knobs_modules)))
            if is_set_local:
                self.findings.append(Finding(
                    check="rollout-set-local",
                    path=self.src.rel_path,
                    line=node.lineno, col=node.col_offset,
                    message="process-local knob override outside the "
                            "guarded rollout path",
                    hint="set_local forks this process's knob view "
                         "away from the channel every consumer "
                         "watches; push through the canary "
                         "controller or `pbst knobs set` instead "
                         "(docs/KNOBS.md, docs/AUTOPILOT.md)",
                ))
        self.generic_visit(node)


class RolloutDisciplinePass(Pass):
    id = "rollout-discipline"
    rules = ("rollout-push", "rollout-set-local")
    description = ("production knob writes go through the guarded "
                   "rollout path: channel.push / set_local calls "
                   "outside knobs/, autopilot/canary.py, the CLI, "
                   "and tests are findings — a raw push skips canary "
                   "scoping and the SLO-burn guard")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None or _exempt(src.rel_path):
            return []
        taint = _Taint()
        taint.visit(src.tree)
        scan = _RolloutScan(src, taint)
        scan.visit(src.tree)
        return scan.findings
