"""Scenario-discipline pass: the promoted corpus stays replayable.

The scenario subsystem's whole value is that a discovered pathology is
a PERMANENT regression test (docs/SCENARIOS.md): a corpus entry
replays because it records its genome, seed, harness config, and
golden digests, and a genome reproduces because it only ever comes
from the seeded factories. Both properties rot silently without a
checker. Two rules:

- ``scenario-corpus-golden``: a corpus entry
  (``pbs_tpu/scenarios/corpus/*.json``) that is unparseable or
  missing its replay provenance — ``genome``, ``seed``, ``config``,
  or either golden digest. Such an entry LOOKS like a regression gate
  but ``pbst scenarios replay --check`` cannot hold it to anything.
  The corpus directory is checked whenever the scenarios package is
  in the scanned set (so the tier-1 tree selfcheck always covers the
  shipped corpus).
- ``scenario-raw-genome``: a direct ``Genome(...)`` construction
  outside the genome module itself. Hand-built genomes bypass the
  gene-table validation and the sha256-derived provenance the corpus
  and archive rely on; use ``Genome.from_seed`` / ``from_dict`` /
  ``mutate`` / ``crossover`` (the factories the determinism contract
  covers).
"""

from __future__ import annotations

import ast
import json
import os

from pbs_tpu.analysis.core import (
    CheckContext,
    Finding,
    Pass,
    SourceFile,
    qualified_name,
)

#: Keys a corpus entry must carry to be replayable, plus the golden
#: digests checked separately (non-empty strings).
_CORPUS_KEYS = ("name", "genome", "seed", "config", "golden")


def _anchored(rel_path: str) -> str:
    parts = rel_path.replace("\\", "/").split("/")
    if "pbs_tpu" in parts:
        parts = parts[parts.index("pbs_tpu") + 1:]
    return "/".join(parts)


def _is_test_path(rel_path: str) -> bool:
    norm = rel_path.replace("\\", "/")
    return "tests/" in norm or \
        norm.rsplit("/", 1)[-1].startswith("test_")


class _GenomeScan(ast.NodeVisitor):
    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        qual = qualified_name(node.func)
        if qual is not None and \
                (qual == "Genome" or qual.endswith(".Genome")):
            self.findings.append(Finding(
                check="scenario-raw-genome",
                path=self.src.rel_path,
                line=node.lineno, col=node.col_offset,
                message="scenario genome constructed outside the "
                        "seeded factories",
                hint="build genomes with Genome.from_seed / "
                     "from_dict / mutate / crossover — a hand-built "
                     "Genome(...) skips gene-table validation and "
                     "breaks the archive/corpus reproducibility "
                     "contract (docs/SCENARIOS.md)",
            ))
        self.generic_visit(node)


class ScenarioDisciplinePass(Pass):
    id = "scenario-discipline"
    rules = ("scenario-corpus-golden", "scenario-raw-genome")
    description = ("the promoted scenario corpus stays replayable: "
                   "corpus entries missing golden digests or replay "
                   "provenance, and Genome(...) constructions outside "
                   "the seeded factories, are findings")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None or _is_test_path(src.rel_path):
            return []
        anchored = _anchored(src.rel_path)
        if anchored.startswith("scenarios/"):
            # Remember scanned scenario packages; their corpus dirs
            # are validated once, in finalize.
            dirs = ctx.state.setdefault("scenario_corpus_dirs", {})
            pkg_dir = os.path.dirname(os.path.abspath(src.path))
            rel_dir = os.path.dirname(src.rel_path)
            if os.path.basename(pkg_dir) == "corpus":
                pkg_dir = os.path.dirname(pkg_dir)
                rel_dir = os.path.dirname(rel_dir)
            dirs.setdefault(os.path.join(pkg_dir, "corpus"),
                            (rel_dir + "/corpus") if rel_dir
                            else "corpus")
        if anchored == "scenarios/genome.py":
            return []
        scan = _GenomeScan(src)
        scan.visit(src.tree)
        return scan.findings

    def finalize(self, ctx: CheckContext) -> list[Finding]:
        findings: list[Finding] = []
        dirs = ctx.state.get("scenario_corpus_dirs", {})
        for corpus_dir in sorted(dirs):
            rel_dir = dirs[corpus_dir]
            if not os.path.isdir(corpus_dir):
                continue
            for fname in sorted(os.listdir(corpus_dir)):
                if not fname.endswith(".json"):
                    continue
                rel = f"{rel_dir}/{fname}"
                try:
                    with open(os.path.join(corpus_dir, fname)) as f:
                        entry = json.load(f)
                except (OSError, json.JSONDecodeError) as e:
                    findings.append(Finding(
                        check="scenario-corpus-golden", path=rel,
                        line=1, col=0,
                        message=f"corpus entry unreadable: {e}",
                        hint="regenerate with `pbst scenarios "
                             "promote` (docs/SCENARIOS.md)"))
                    continue
                if not isinstance(entry, dict):
                    findings.append(Finding(
                        check="scenario-corpus-golden", path=rel,
                        line=1, col=0,
                        message="corpus entry is not a JSON object",
                        hint="regenerate with `pbst scenarios "
                             "promote` (docs/SCENARIOS.md)"))
                    continue
                missing = [k for k in _CORPUS_KEYS
                           if k not in entry]
                golden = entry.get("golden")
                if isinstance(golden, dict):
                    for k in ("trace_digest", "report_digest"):
                        if not golden.get(k):
                            missing.append(f"golden.{k}")
                elif "golden" in entry:
                    # Present but not an object: replay_corpus would
                    # refuse it, so it is not a regression gate either.
                    missing.append("golden (not an object)")
                if missing:
                    findings.append(Finding(
                        check="scenario-corpus-golden", path=rel,
                        line=1, col=0,
                        message="corpus entry missing replay "
                                f"provenance: {', '.join(missing)}",
                        hint="a promoted scenario must carry genome "
                             "+ seed + config + golden trace/report "
                             "digests so `pbst scenarios replay "
                             "--check` can hold it; re-promote with "
                             "`pbst scenarios promote`"))
        return findings
