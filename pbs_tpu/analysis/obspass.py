"""Obs-discipline pass.

The span layer (``pbs_tpu.obs.spans``; docs/TRACING.md) makes three
promises the rest of the tree can quietly break:

- **every span closes** — a begin-style span emit (``span.begin()`` /
  ``spans.start()``) that can exit the function on a control-flow path
  with no terminal emit leaves an unclosed span: the chain validator
  reports a gap at chaos time, but the bug belongs at review time.
  Scoped to gateway/federation code, where request custody moves.
  Rule ``obs-unclosed-span``.
- **span emits stay batched** — a scalar ring ``.emit(...)`` of a
  ``SPAN_*`` event inside a loop pays the per-event ring cost the
  :class:`~pbs_tpu.obs.spans.SpanRecorder` exists to amortize (its
  methods stage through an EmitBatch). Rule ``obs-span-emit-in-loop``
  (the span twin of perf-discipline's ``perf-emit-in-loop``).
- **no histogram-bucket scans in hot paths** — quantiles over the
  log2 histograms are one ``cumsum`` + ``searchsorted``
  (:func:`~pbs_tpu.obs.spans.hist_quantile`); a ``for`` loop striding
  ``HIST_BUCKETS`` in producer code re-introduces the per-element
  Python cost the vectorized helper removed. Rule ``obs-hist-scan``.
"""

from __future__ import annotations

import ast

from pbs_tpu.analysis.core import CheckContext, Finding, Pass, SourceFile

#: Modules that IMPLEMENT the span/histogram layout — the scans and
#: scalar emits live there by design.
OBS_MACHINERY = ("obs/spans.py", "obs/trace.py", "perf/")

#: Where the unclosed-span rule applies: the code that moves request
#: custody around (and therefore opens/closes spans on branchy paths).
SPAN_SCOPE = ("gateway/",)

#: Begin-style / terminal-style method names on a span-ish receiver.
SPAN_BEGIN = ("begin", "start", "open")
SPAN_END = ("end", "close", "finish", "complete", "shed")


def _anchored(rel_path: str) -> str:
    parts = rel_path.replace("\\", "/").split("/")
    if "pbs_tpu" in parts:
        parts = parts[parts.index("pbs_tpu") + 1:]
    return "/".join(parts)


def _is_test(rel_path: str) -> bool:
    norm = rel_path.replace("\\", "/")
    return "tests/" in norm or norm.rsplit("/", 1)[-1].startswith("test_")


def _receiver_ident(func: ast.Attribute) -> str:
    base = func.value
    if isinstance(base, ast.Attribute):
        return base.attr
    if isinstance(base, ast.Name):
        return base.id
    return ""


def _span_call(node: ast.Call, names: tuple[str, ...]) -> bool:
    func = node.func
    return (isinstance(func, ast.Attribute) and func.attr in names
            and "span" in _receiver_ident(func).lower())


def _mentions_span_event(node: ast.Call) -> bool:
    for arg in node.args:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Attribute) and \
                    sub.attr.startswith("SPAN_"):
                return True
            if isinstance(sub, ast.Name) and sub.id.startswith("SPAN_"):
                return True
    return False


def _mentions_hist_buckets(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == "HIST_BUCKETS":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "HIST_BUCKETS":
            return True
    return False


class _ObsScan(ast.NodeVisitor):
    def __init__(self, src: SourceFile, span_scope: bool,
                 emit_scope: bool):
        self.src = src
        self.span_scope = span_scope
        self.emit_scope = emit_scope
        self.findings: list[Finding] = []
        self._loop_depth = 0

    # -- unclosed spans (per function, control-flow aware) ---------------

    def _visit_func(self, node) -> None:
        if self.span_scope:
            begins: list[ast.Call] = []
            ends: list[ast.Call] = []
            returns: list[ast.stmt] = []
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    if _span_call(sub, SPAN_BEGIN):
                        begins.append(sub)
                    elif _span_call(sub, SPAN_END):
                        ends.append(sub)
                elif isinstance(sub, (ast.Return, ast.Raise)):
                    returns.append(sub)
                elif sub is not node and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    pass  # nested defs still walked; good enough
            if begins and not ends:
                b = begins[0]
                self.findings.append(Finding(
                    "obs-unclosed-span", self.src.rel_path, b.lineno,
                    b.col_offset,
                    "span begun here but no terminal span emit exists "
                    "in this function — every control-flow path must "
                    "close the span or the chain validator reports a "
                    "gap at chaos time",
                    hint="emit the terminal (complete/shed/end) on "
                         "every exit path, or route the lifecycle "
                         "through SpanRecorder's paired emit points "
                         "(obs/spans.py, docs/TRACING.md)"))
            elif begins and ends:
                first_begin = min(b.lineno for b in begins)
                for r in returns:
                    if r.lineno > first_begin and not any(
                            e.lineno <= r.lineno for e in ends):
                        self.findings.append(Finding(
                            "obs-unclosed-span", self.src.rel_path,
                            r.lineno, r.col_offset,
                            "early exit between span begin and its "
                            "terminal emit — this path leaves the "
                            "span unclosed",
                            hint="close the span before returning/"
                                 "raising, or restructure so the "
                                 "terminal emit dominates every exit "
                                 "(docs/TRACING.md)"))
        self.generic_visit(node)

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- scalar SPAN_* emits in loops ------------------------------------

    def _visit_loop(self, node) -> None:
        if self.emit_scope and isinstance(node, ast.For) and \
                _mentions_hist_buckets(node.iter):
            self.findings.append(Finding(
                "obs-hist-scan", self.src.rel_path, node.lineno,
                node.col_offset,
                "per-bucket Python scan over HIST_BUCKETS in a hot "
                "path — quantiles over the log2 histograms are one "
                "vectorized pass",
                hint="use hist_quantile / LatencyHistograms."
                     "class_quantile (obs/spans.py): cumsum + "
                     "searchsorted, no Python loop"))
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _visit_loop
    visit_While = _visit_loop

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if (self.emit_scope and self._loop_depth > 0
                and isinstance(func, ast.Attribute)
                and func.attr in ("emit", "trace_emit")
                and "batch" not in _receiver_ident(func).lower()
                and _mentions_span_event(node)):
            self.findings.append(Finding(
                "obs-span-emit-in-loop", self.src.rel_path, node.lineno,
                node.col_offset,
                "scalar ring emit of a SPAN_* event inside a loop — "
                "span producers must stage through the recorder's "
                "EmitBatch (one vectorized ring write per watermark)",
                hint="emit through SpanRecorder (its methods stage "
                     "via EmitBatch), or build records and call "
                     "emit_many once (obs/spans.py)"))
        self.generic_visit(node)


class ObsDisciplinePass(Pass):
    id = "obs-discipline"
    rules = ("obs-unclosed-span", "obs-span-emit-in-loop",
             "obs-hist-scan")
    description = ("span/histogram discipline (docs/TRACING.md): spans "
                   "close on every control-flow path in gateway code, "
                   "SPAN_* emits stay on the EmitBatch staging path, "
                   "and no per-bucket HIST_BUCKETS scans outside the "
                   "vectorized helpers")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None or _is_test(src.rel_path):
            return []
        anchored = _anchored(src.rel_path)
        if any(anchored == m or anchored.startswith(m)
               for m in OBS_MACHINERY):
            return []
        span_scope = any(anchored.startswith(p) for p in SPAN_SCOPE)
        scan = _ObsScan(src, span_scope, True)
        scan.visit(src.tree)
        return scan.findings
