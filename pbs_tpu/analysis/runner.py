"""``pbst check`` driver: walk, parse, run passes, filter, format.

The runner owns everything pass-agnostic: file discovery, suppression
filtering (passes emit every hit; the escape hatch is applied in ONE
place so no pass can forget it), deterministic ordering, and the two
output formats. Exit-code contract (CI gates on it):

- 0: clean tree (possibly via justified suppressions)
- 1: findings
- 2: usage error (no files, unknown pass, unreadable graph)
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Iterable

from pbs_tpu.analysis.core import (
    CheckContext,
    CSourceFile,
    Finding,
    Pass,
    SourceFile,
)
from pbs_tpu.analysis.counterapi import CounterApiPass
from pbs_tpu.analysis.durabilitypass import DurabilityPass
from pbs_tpu.analysis.gatewaypass import GatewayDisciplinePass
from pbs_tpu.analysis.hwpass import HwDisciplinePass
from pbs_tpu.analysis.knobspass import KnobDisciplinePass
from pbs_tpu.analysis.locks import LockDisciplinePass
from pbs_tpu.analysis.memmodel import (
    AbiLayoutDriftPass,
    DeterminismDisciplinePass,
    SeqlockDisciplinePass,
)
from pbs_tpu.analysis.netdiscipline import NetDisciplinePass
from pbs_tpu.analysis.obspass import ObsDisciplinePass
from pbs_tpu.analysis.perfpass import PerfDisciplinePass
from pbs_tpu.analysis.procpass import ProcessDisciplinePass
from pbs_tpu.analysis.rolloutpass import RolloutDisciplinePass
from pbs_tpu.analysis.scenariopass import ScenarioDisciplinePass
from pbs_tpu.analysis.schedops import SchedOpsPass
from pbs_tpu.analysis.servepass import ServeDisciplinePass
from pbs_tpu.analysis.units import TimeUnitPass

#: The suite, in report order. Adding a pass = append here + docs.
ALL_PASSES: tuple[type[Pass], ...] = (
    LockDisciplinePass,
    TimeUnitPass,
    SchedOpsPass,
    CounterApiPass,
    NetDisciplinePass,
    GatewayDisciplinePass,
    PerfDisciplinePass,
    ObsDisciplinePass,
    KnobDisciplinePass,
    RolloutDisciplinePass,
    ScenarioDisciplinePass,
    DurabilityPass,
    ProcessDisciplinePass,
    ServeDisciplinePass,
    SeqlockDisciplinePass,
    AbiLayoutDriftPass,
    HwDisciplinePass,
    DeterminismDisciplinePass,
)


def pass_ids() -> list[str]:
    return [p.id for p in ALL_PASSES]


@dataclasses.dataclass
class CheckResult:
    findings: list[Finding]
    suppressed: list[tuple[Finding, str]]  # (finding, justification)
    files_scanned: int
    passes_run: list[str]

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def as_dict(self) -> dict[str, Any]:
        return {
            "version": 1,
            "passes": self.passes_run,
            "files_scanned": self.files_scanned,
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": [
                {**f.as_dict(), "justification": j}
                for f, j in self.suppressed
            ],
            "counts": self.counts(),
        }

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.check] = out.get(f.check, 0) + 1
        return out


#: Extensions the checker scans. .py files get the AST pass suite;
#: .cc files get the cross-language memmodel passes (run_c hook).
CHECK_EXTS = (".py", ".cc")


def iter_check_files(paths: Iterable[str],
                     exts: tuple[str, ...] = CHECK_EXTS) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(exts):
                out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith((".", "__pycache__")))
                for f in sorted(files):
                    if f.endswith(exts):
                        out.append(os.path.join(root, f))
    return sorted(dict.fromkeys(out))


def iter_py_files(paths: Iterable[str]) -> list[str]:
    return iter_check_files(paths, exts=(".py",))


def load_dynamic_graph(path: str) -> set[tuple[str, str]]:
    """Edges from a ``pbst lockdep --dump-graph`` artifact. Accepts the
    stable export shape ({"edges": [["a","b"], ...]}), the raw snapshot
    shape ({"edges": {"a": ["b", ...]}}), a whole obs dump (descends
    into its "lockdep" section), and a bare pair list. Anything else
    is a ValueError — fabricating edges from an unrelated dict would
    silently disable the cross-check."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and isinstance(data.get("lockdep"), dict):
        data = data["lockdep"]  # obs dump artifact: use its section
    if isinstance(data, dict):
        if "edges" not in data:
            raise ValueError("dict artifact has no 'edges' key — not a "
                             "lock-order graph")
        edges = data["edges"]
    else:
        edges = data
    out: set[tuple[str, str]] = set()
    if isinstance(edges, dict):
        for a, bs in edges.items():
            if not isinstance(bs, list) or \
                    not all(isinstance(b, str) for b in bs):
                raise ValueError(f"edges[{a!r}] is not a list of class "
                                 "names")
            for b in bs:
                out.add((str(a), b))
    elif isinstance(edges, list):
        for pair in edges:
            if not isinstance(pair, (list, tuple)) or len(pair) != 2:
                raise ValueError(f"edge {pair!r} is not a [holder, taken] "
                                 "pair")
            out.add((str(pair[0]), str(pair[1])))
    else:
        raise ValueError("graph holds no edges dict or pair list")
    return out


def changed_check_files(base_ref: str, paths: Iterable[str],
                        root: str | None = None) -> list[str]:
    """The ``--changed`` fast path: checkable files (.py and .cc)
    under ``paths`` that differ from ``base_ref`` in git (working tree
    vs ref, deletions excluded) plus untracked files. Raises
    ValueError when git cannot answer (not a repo, unknown ref) — the
    CLI maps that to a usage error, never to a silently-empty "clean"
    run.

    A changed ``.cc`` file arms the cross-language memmodel passes,
    which diff the C layout against its Python mirrors — so the
    changed set is EXPANDED with every sibling .cc under ``paths``
    (pbst_fastcall.cc #includes pbst_runtime.cc: constants flow across
    files) and the declared Python ABI anchor modules (resolved
    against the git toplevel; silently absent in trees that don't
    have them). A .py-only change set is returned as-is.

    Caveat (documented in docs/ANALYSIS.md): cross-file analyses
    (static lock-order graph, knob-native-drift, knob constant
    resolution across modules) see only the changed subset in this
    mode — it is the pre-commit fast path; CI runs the full tree."""
    import subprocess

    root = os.path.abspath(root or os.getcwd())
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            cwd=root, capture_output=True, text=True, timeout=60)
        diff = subprocess.run(
            ["git", "diff", "--name-only", "--diff-filter=d", base_ref],
            cwd=root, capture_output=True, text=True, timeout=60)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=60)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise ValueError(f"git unavailable for --changed: {e}") from None
    if top.returncode != 0 or diff.returncode != 0:
        raise ValueError(
            f"git diff {base_ref!r} failed: "
            f"{(diff.stderr or top.stderr).strip() or 'unknown error'}")
    # `git diff --name-only` paths are TOPLEVEL-relative; `ls-files
    # --others` paths are cwd-relative. Anchor each against the right
    # base or a subdirectory invocation silently reports clean.
    toplevel = top.stdout.strip()
    changed = {os.path.abspath(os.path.join(toplevel, n))
               for n in diff.stdout.splitlines() if n.endswith(CHECK_EXTS)}
    if untracked.returncode == 0:
        changed |= {os.path.abspath(os.path.join(root, n))
                    for n in untracked.stdout.splitlines()
                    if n.endswith(CHECK_EXTS)}
    wanted = set()
    for p in iter_check_files(paths):
        ap = os.path.abspath(p)
        if ap in changed and os.path.isfile(ap):
            wanted.add(p)
    if any(p.endswith(".cc") for p in wanted):
        # Cross-language context for the memmodel passes: every .cc
        # under paths (constants span #include'd siblings) + the
        # Python mirror modules the ABI contract names.
        from pbs_tpu.analysis.memmodel import CROSS_LANG_PY_ANCHORS

        wanted |= {p for p in iter_check_files(paths)
                   if p.endswith(".cc")}
        for rel in CROSS_LANG_PY_ANCHORS:
            ap = os.path.join(toplevel, rel)
            if os.path.isfile(ap):
                wanted.add(ap)
    return sorted(wanted)


def changed_py_files(base_ref: str, paths: Iterable[str],
                     root: str | None = None) -> list[str]:
    """Back-compat shim: the .py subset of :func:`changed_check_files`
    (no cross-language expansion)."""
    return [p for p in changed_check_files(base_ref, paths, root)
            if p.endswith(".py")]


def list_suppressions(paths: Iterable[str],
                      root: str | None = None) -> list[dict]:
    """Every suppression comment under ``paths`` with file:line,
    rules, scope, and justification — the ``pbst check
    --list-suppressions`` audit surface. Unparseable/justification-
    less comments are listed too (rule ``bad-suppression``), so the
    audit can't under-report the escape hatch."""
    root = root or os.getcwd()
    out: list[dict] = []
    for path in iter_check_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except (OSError, UnicodeDecodeError):
            continue
        rel = os.path.relpath(os.path.abspath(path), root)
        cls = CSourceFile if path.endswith(".cc") else SourceFile
        src = cls(path, text, rel_path=rel.replace(os.sep, "/"))
        for s in src.suppressions:
            out.append({
                "path": src.rel_path, "line": s.line,
                "rules": list(s.rules),
                "scope": "file" if s.file_wide else "line",
                "justification": s.justification,
            })
        for f_ in src.bad_suppressions:
            out.append({
                "path": src.rel_path, "line": f_.line,
                "rules": ["bad-suppression"], "scope": "line",
                "justification": "",
            })
    out.sort(key=lambda d: (d["path"], d["line"]))
    return out


def check_paths(paths: Iterable[str],
                passes: Iterable[str] | None = None,
                dynamic_graph: set[tuple[str, str]] | None = None,
                root: str | None = None) -> CheckResult:
    """Run the suite over ``paths``. ``root`` (default cwd) anchors the
    relative paths findings report, so golden outputs are stable."""
    root = root or os.getcwd()
    selected = list(ALL_PASSES)
    if passes is not None:
        wanted = set(passes)
        unknown = wanted - set(pass_ids())
        if unknown:
            raise KeyError(
                f"unknown pass(es) {sorted(unknown)}; "
                f"available: {pass_ids()}")
        selected = [p for p in ALL_PASSES if p.id in wanted]

    files: list[SourceFile] = []
    c_files: list[CSourceFile] = []
    for path in iter_check_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except (OSError, UnicodeDecodeError):
            continue
        rel = os.path.relpath(os.path.abspath(path), root)
        rel = rel.replace(os.sep, "/")
        if path.endswith(".cc"):
            c_files.append(CSourceFile(path, text, rel_path=rel))
        else:
            files.append(SourceFile(path, text, rel_path=rel))

    ctx = CheckContext(files, dynamic_lock_edges=dynamic_graph,
                       c_files=c_files)
    instances = [cls() for cls in selected]
    raw: list[Finding] = []
    for src in files:
        if src.parse_error is not None:
            raw.append(src.parse_error)
        raw.extend(src.bad_suppressions)
        if src.tree is None:
            continue
        for inst in instances:
            raw.extend(inst.run(src, ctx))
    for csrc in c_files:
        raw.extend(csrc.bad_suppressions)
        for inst in instances:
            raw.extend(inst.run_c(csrc, ctx))
    for inst in instances:
        raw.extend(inst.finalize(ctx))

    by_rel = {src.rel_path: src for src in [*files, *c_files]}
    findings: list[Finding] = []
    suppressed: list[tuple[Finding, str]] = []
    for f in sorted(raw, key=Finding.sort_key):
        src = by_rel.get(f.path)
        if src is not None and src.suppressed(f.check, f.line):
            just = next((s.justification for s in src.suppressions
                         if s.matches(f.check, f.line)), "")
            suppressed.append((f, just))
        else:
            findings.append(f)
    return CheckResult(findings=findings, suppressed=suppressed,
                       files_scanned=len(files) + len(c_files),
                       passes_run=[p.id for p in instances])


def format_human(result: CheckResult) -> str:
    lines = []
    for f in result.findings:
        lines.append(f.format())
    counts = result.counts()
    summary = (
        f"pbst check: {len(result.findings)} finding(s) in "
        f"{result.files_scanned} file(s)"
        + (f" [{', '.join(f'{k}={v}' for k, v in sorted(counts.items()))}]"
           if counts else "")
        + (f"; {len(result.suppressed)} suppressed"
           if result.suppressed else "")
        + f" (passes: {', '.join(result.passes_run)})"
    )
    lines.append(summary)
    return "\n".join(lines)
