"""Perf-discipline pass.

PR 5 vectorized the trace/telemetry hot path (``docs/PERF.md``):
records move through ``TraceBuffer.emit_many``/``consume`` in bulk
slice copies, and bursty producers stage events through
``obs.trace.EmitBatch``. What regresses is code quietly reintroducing
the per-record idioms the rewrite removed — a Python loop striding a
``TRACE_REC_WORDS``-word buffer one record at a time, or a hot loop
paying a scalar ring emit per event. Two rules:

- ``perf-rec-loop``: a ``for`` loop whose body does
  ``TRACE_REC_WORDS``-strided record arithmetic — the
  one-record-per-iteration copy the vectorized ring APIs replaced.
  Scoped to the whole tree minus the machinery that *implements* the
  record layout (``obs/trace.py``) and the harness that measures it
  (``perf/``).
- ``perf-emit-in-loop``: a scalar ``.emit(...)``/``.trace_emit(...)``
  call inside a ``for``/``while`` body in the heavy-producer packages
  (``sim/``, ``gateway/``, ``telemetry/``). Staged emits are
  sanctioned and recognized by naming convention: a receiver whose
  trailing identifier contains ``batch`` (``self._trace_batch.emit``,
  ``ring_batch.emit``) is an ``EmitBatch``, which exists precisely to
  be called per event.
- ``perf-dispatch-alloc``: per-dispatch Python-object allocation in a
  simulator dispatch edge — a ``.append(...)`` call or a
  dict/list/set display (or comprehension) inside a ``do_schedule`` /
  ``wake`` / ``sleep`` / ``descheduled`` body under ``sim/``. The
  probe rewrite (``sim/engine.py``) moved accumulation onto
  preallocated grow-by-doubling numpy arrays precisely because a list
  append per dispatched quantum was the sweep bottleneck; this rule
  keeps it out. The list-based reference probe carries justified
  line suppressions — it exists to witness equivalence, not to sweep.
- ``perf-native-unchecked``: a call site consuming a
  ``native_mod.load()`` / ``native_mod.fastcall()`` result without
  handling the None branch. The native runtime is OPTIONAL by
  contract (no toolchain → pure-Python fallback); code that does
  ``native_mod.load().pbst_x(...)``, or stashes the result and never
  None-checks it, crashes exactly on the hosts the fallback exists
  for. Guards are recognized as: the result name (or ``self``
  attribute) appearing in an ``if``/``while``/ternary/``assert``
  test, or in an ``is None`` / ``is not None`` compare — in the
  enclosing function for locals, anywhere in the class for
  attributes. Scoped to the whole tree minus ``runtime/native.py``
  (the loader itself).
- ``perf-native-sim-unguarded``: sim/sweep code invoking the native
  sim dispatch core (``run_native``/``sim_run``) without a
  degradation branch. The C core is optional exactly like the rest of
  the native runtime, AND configuration-gated (policies, executors,
  probes it doesn't model): every consumer must route through
  ``native_core.unsupported_reason`` / ``available_tier`` and keep
  the pure-Python witness engine as the fallback, or a toolchain-less
  host (or an unsupported sweep cell) crashes instead of degrading.
  Recognized guards: a name assigned from one of the guard calls
  appearing in a conditional/None compare, or a guard call directly
  inside an ``if``/``while``/ternary/``assert`` test — in the
  enclosing function (module scope for top-level code). Scoped to
  ``sim/`` minus ``sim/native_core.py`` (the marshaller owns its own
  availability checks).
"""

from __future__ import annotations

import ast

from pbs_tpu.analysis.core import CheckContext, Finding, Pass, SourceFile

#: Modules that implement the record layout / measure it — the strided
#: arithmetic lives there by design.
REC_MACHINERY = ("obs/trace.py", "perf/")

#: Packages whose event producers are hot enough to batch.
HOT_PACKAGES = ("sim/", "gateway/", "telemetry/")

#: Scalar per-event emitters the batching APIs replace in hot loops.
EMITTERS = ("emit", "trace_emit")

#: The optional-runtime loaders whose results can be None.
NATIVE_LOADERS = ("load", "fastcall")

#: Scheduler-probe dispatch edges: the per-quantum hot methods the
#: numpy-accumulator rewrite de-allocated (sim/engine.py).
DISPATCH_EDGES = ("do_schedule", "wake", "sleep", "descheduled")

#: Packages whose dispatch edges the allocation rule covers.
DISPATCH_PACKAGES = ("sim/",)

#: The loader implementation itself (its internal load() calls are the
#: machinery the rule protects callers of).
NATIVE_MACHINERY = ("runtime/native.py",)

#: Native sim-core consumers that must sit behind a degradation branch.
NATIVE_SIM_CONSUMERS = ("run_native", "sim_run")

#: The calls whose (None-checked) result constitutes that branch.
NATIVE_SIM_GUARDS = ("unsupported_reason", "supported", "available_tier")

#: Packages the sim-core rule covers...
NATIVE_SIM_PACKAGES = ("sim/",)

#: ...minus the marshaller that implements the core's entry points.
NATIVE_SIM_MACHINERY = ("sim/native_core.py",)


def _anchored(rel_path: str) -> str:
    parts = rel_path.replace("\\", "/").split("/")
    if "pbs_tpu" in parts:
        parts = parts[parts.index("pbs_tpu") + 1:]
    return "/".join(parts)


def _is_test(rel_path: str) -> bool:
    norm = rel_path.replace("\\", "/")
    return "tests/" in norm or norm.rsplit("/", 1)[-1].startswith("test_")


def _receiver_ident(func: ast.Attribute) -> str:
    """Trailing identifier of the emit receiver: ``self._trace_batch``
    -> "_trace_batch", ``ring`` -> "ring"."""
    base = func.value
    if isinstance(base, ast.Attribute):
        return base.attr
    if isinstance(base, ast.Name):
        return base.id
    return ""


def _mentions_rec_words(node: ast.AST) -> bool:
    return any(isinstance(sub, ast.Name) and sub.id == "TRACE_REC_WORDS"
               for sub in ast.walk(node))


class _DispatchAllocScan(ast.NodeVisitor):
    """perf-dispatch-alloc: allocation idioms inside a sim dispatch
    edge's body (nested defs get their own scan, so a helper defined
    inside an edge is not double-counted)."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node.name in DISPATCH_EDGES:
            for stmt in node.body:
                self._scan_body(stmt)
        # Recurse either way: a nested def (edge or not) gets its own
        # visit — _scan_body below excludes nested-def subtrees from
        # the ENCLOSING edge's scan.
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            "perf-dispatch-alloc", self.src.rel_path, node.lineno,
            node.col_offset,
            f"{what} inside a scheduler dispatch edge — one Python "
            "object allocation per dispatched quantum is the "
            "accumulation pattern the numpy probe rewrite removed",
            hint="accumulate on preallocated grow-by-doubling arrays "
                 "(index store + count bump; see sim/engine.py "
                 "SchedulerProbe/_TenantAcc) and defer container "
                 "building to the metrics accessors"))

    def _scan_body(self, stmt: ast.stmt) -> None:
        # Manual stack walk: ast.walk would descend INTO nested defs,
        # attributing a helper's one-time allocations to the edge —
        # here a nested def's whole subtree is pruned (it gets its own
        # visit_FunctionDef pass instead).
        stack = [stmt]
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "append":
                self._flag(sub, "list .append() per dispatch")
            elif isinstance(sub, (ast.Dict, ast.DictComp)):
                self._flag(sub, "dict literal/comprehension")
            elif isinstance(sub, (ast.List, ast.ListComp,
                                  ast.Set, ast.SetComp)):
                self._flag(sub, "list/set literal/comprehension")
            stack.extend(ast.iter_child_nodes(sub))


class _PerfScan(ast.NodeVisitor):
    def __init__(self, src: SourceFile, rec_scope: bool, emit_scope: bool):
        self.src = src
        self.rec_scope = rec_scope
        self.emit_scope = emit_scope
        self.findings: list[Finding] = []
        self._loop_depth = 0

    def _visit_loop(self, node: ast.For | ast.While) -> None:
        if self.rec_scope and isinstance(node, ast.For) and any(
                _mentions_rec_words(stmt) for stmt in node.body):
            self.findings.append(Finding(
                "perf-rec-loop", self.src.rel_path, node.lineno,
                node.col_offset,
                "per-record loop over a TRACE_REC_WORDS-strided buffer — "
                "one slice copy per record is the scalar path the "
                "vectorized ring APIs replaced",
                hint="move records in bulk: TraceBuffer.emit_many / "
                     "consume / peek copy the wrapped span in at most "
                     "two contiguous slices (obs/trace.py)"))
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _visit_loop
    visit_While = _visit_loop

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if (self.emit_scope and self._loop_depth > 0
                and isinstance(func, ast.Attribute)
                and func.attr in EMITTERS
                and "batch" not in _receiver_ident(func).lower()):
            self.findings.append(Finding(
                "perf-emit-in-loop", self.src.rel_path, node.lineno,
                node.col_offset,
                f"scalar .{func.attr}() inside a loop in a hot producer "
                "package — every event pays the full ring-emit cost",
                hint="stage through an EmitBatch (one vectorized "
                     "emit_many per watermark) or build the records and "
                     "call emit_many once (obs/trace.py)"))
        self.generic_visit(node)


def _is_native_loader(node: ast.Call) -> bool:
    func = node.func
    return (isinstance(func, ast.Attribute)
            and func.attr in NATIVE_LOADERS
            and "native" in _receiver_ident(func).lower())


def _none_guard_idents(scope: ast.AST) -> set[str]:
    """Identifiers (plain names and attribute names) that appear in a
    conditional test or an ``is [not] None`` compare inside ``scope``
    — the shapes a None-branch handler takes."""
    guarded: set[str] = set()

    def _collect(expr: ast.AST) -> None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name):
                guarded.add(sub.id)
            elif isinstance(sub, ast.Attribute):
                guarded.add(sub.attr)

    for sub in ast.walk(scope):
        if isinstance(sub, (ast.If, ast.While, ast.IfExp, ast.Assert)):
            _collect(sub.test)
        elif isinstance(sub, ast.Compare):
            if any(isinstance(c, ast.Constant) and c.value is None
                   for c in [sub.left, *sub.comparators]):
                _collect(sub)
    return guarded


class _NativeScan:
    """perf-native-unchecked: loader results consumed without a None
    branch. Locals are checked against their enclosing function,
    ``self.X`` stashes against their whole class (the stash-in-init,
    branch-at-use idiom of TraceBuffer/Ledger)."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            "perf-native-unchecked", self.src.rel_path, node.lineno,
            node.col_offset,
            f"{what} — native_mod.load()/fastcall() return None when "
            "the runtime is unavailable (no toolchain, failed build), "
            "and this site would crash exactly on the hosts the "
            "pure-Python fallback exists for",
            hint="branch on the result (`if lib is not None: ...`) "
                 "and keep the Python path as the fallback "
                 "(runtime/native.py, docs/PERF.md)"))

    def scan(self, tree: ast.AST) -> None:
        # Direct uses: an attribute ridden straight off the call.
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Call) and \
                    _is_native_loader(node.value):
                self._flag(node, "attribute access directly on a "
                                 "native loader result")
        # Stashed results: name assigns per function, self-attribute
        # assigns per class.
        for scope in ast.walk(tree):
            if isinstance(scope, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                self._scan_function(scope)
            elif isinstance(scope, ast.ClassDef):
                self._scan_class(scope)

    def _loader_assigns(self, scope: ast.AST):
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Assign) and \
                    isinstance(sub.value, ast.Call) and \
                    _is_native_loader(sub.value) and \
                    len(sub.targets) == 1:
                yield sub, sub.targets[0]

    def _scan_function(self, fn) -> None:
        guarded = None  # computed lazily: most functions have none
        for assign, target in self._loader_assigns(fn):
            if not isinstance(target, ast.Name):
                continue  # self.X handled at class level
            if guarded is None:
                guarded = _none_guard_idents(fn)
            if target.id not in guarded:
                self._flag(assign, f"native loader result bound to "
                                   f"{target.id!r} with no None "
                                   "branch in this function")

    def _scan_class(self, cls) -> None:
        guarded = None
        for assign, target in self._loader_assigns(cls):
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            if guarded is None:
                guarded = _none_guard_idents(cls)
            if target.attr not in guarded:
                self._flag(assign, f"native loader result stashed on "
                                   f"self.{target.attr} with no None "
                                   "branch anywhere in this class")


def _call_name(func: ast.AST) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


class _NativeSimScan:
    """perf-native-sim-unguarded: native sim-core invocations whose
    enclosing scope has no degradation branch (see module docstring)."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []

    def _flag(self, node: ast.AST, what: str) -> None:
        self.findings.append(Finding(
            "perf-native-sim-unguarded", self.src.rel_path, node.lineno,
            node.col_offset,
            f"{what} with no degradation branch in scope — the native "
            "sim core is optional (toolchain) AND configuration-gated "
            "(policies/executors/probes it doesn't model); this site "
            "crashes exactly where the Python witness engine should "
            "take over",
            hint="gate on native_core.unsupported_reason(...) (None = "
                 "supported) or available_tier() and fall back to the "
                 "pure-Python engine path (sim/engine.py _run_native, "
                 "docs/SIM.md 'Native dispatch core')"))

    @staticmethod
    def _scope_nodes(scope: ast.AST):
        """The scope's own statements, nested def subtrees pruned
        (each def is judged against its own body)."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield sub
            stack.extend(ast.iter_child_nodes(sub))

    def _scope_guarded(self, scope: ast.AST) -> bool:
        guard_names: set[str] = set()
        for sub in self._scope_nodes(scope):
            if isinstance(sub, ast.Assign) and \
                    isinstance(sub.value, ast.Call) and \
                    _call_name(sub.value.func) in NATIVE_SIM_GUARDS and \
                    len(sub.targets) == 1 and \
                    isinstance(sub.targets[0], ast.Name):
                guard_names.add(sub.targets[0].id)
            elif isinstance(sub, (ast.If, ast.While, ast.IfExp,
                                  ast.Assert)):
                # A guard call used directly in the test counts too.
                for c in ast.walk(sub.test):
                    if isinstance(c, ast.Call) and \
                            _call_name(c.func) in NATIVE_SIM_GUARDS:
                        return True
        if not guard_names:
            return False
        return bool(guard_names & _none_guard_idents(scope))

    def _scan_scope(self, scope: ast.AST) -> None:
        guarded = None  # lazy: most scopes consume nothing
        for sub in self._scope_nodes(scope):
            if isinstance(sub, ast.Call) and \
                    _call_name(sub.func) in NATIVE_SIM_CONSUMERS:
                if guarded is None:
                    guarded = self._scope_guarded(scope)
                if not guarded:
                    self._flag(sub, f"native sim-core call "
                                    f".{_call_name(sub.func)}(...)")

    def scan(self, tree: ast.AST) -> None:
        # Module top-level is a scope of its own; each def is scanned
        # against its own body (a guard in the caller doesn't sanction
        # an unguarded helper).
        self._scan_scope(tree)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_scope(node)


class PerfDisciplinePass(Pass):
    id = "perf-discipline"
    rules = ("perf-rec-loop", "perf-emit-in-loop",
             "perf-dispatch-alloc", "perf-native-unchecked",
             "perf-native-sim-unguarded")
    description = ("trace/telemetry hot paths stay vectorized and "
                   "native-optional: no per-record TRACE_REC_WORDS "
                   "loops, no scalar ring emits inside loops in "
                   "sim/gateway/telemetry (EmitBatch/emit_many are "
                   "the sanctioned forms), no per-dispatch container "
                   "allocation in sim dispatch edges (numpy "
                   "accumulators are the sanctioned form), and every "
                   "native loader result handles the None/unavailable "
                   "branch")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None or _is_test(src.rel_path):
            return []
        anchored = _anchored(src.rel_path)
        rec_scope = not any(
            anchored == m or anchored.startswith(m) for m in REC_MACHINERY)
        emit_scope = any(anchored.startswith(p) for p in HOT_PACKAGES)
        native_scope = anchored not in NATIVE_MACHINERY
        findings: list[Finding] = []
        if rec_scope or emit_scope:
            scan = _PerfScan(src, rec_scope, emit_scope)
            scan.visit(src.tree)
            findings.extend(scan.findings)
        if any(anchored.startswith(p) for p in DISPATCH_PACKAGES):
            dscan = _DispatchAllocScan(src)
            dscan.visit(src.tree)
            findings.extend(dscan.findings)
        if native_scope:
            nat = _NativeScan(src)
            nat.scan(src.tree)
            findings.extend(nat.findings)
        if any(anchored.startswith(p) for p in NATIVE_SIM_PACKAGES) \
                and anchored not in NATIVE_SIM_MACHINERY:
            sim = _NativeSimScan(src)
            sim.scan(src.tree)
            findings.extend(sim.findings)
        return findings
