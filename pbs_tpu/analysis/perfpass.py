"""Perf-discipline pass.

PR 5 vectorized the trace/telemetry hot path (``docs/PERF.md``):
records move through ``TraceBuffer.emit_many``/``consume`` in bulk
slice copies, and bursty producers stage events through
``obs.trace.EmitBatch``. What regresses is code quietly reintroducing
the per-record idioms the rewrite removed — a Python loop striding a
``TRACE_REC_WORDS``-word buffer one record at a time, or a hot loop
paying a scalar ring emit per event. Two rules:

- ``perf-rec-loop``: a ``for`` loop whose body does
  ``TRACE_REC_WORDS``-strided record arithmetic — the
  one-record-per-iteration copy the vectorized ring APIs replaced.
  Scoped to the whole tree minus the machinery that *implements* the
  record layout (``obs/trace.py``) and the harness that measures it
  (``perf/``).
- ``perf-emit-in-loop``: a scalar ``.emit(...)``/``.trace_emit(...)``
  call inside a ``for``/``while`` body in the heavy-producer packages
  (``sim/``, ``gateway/``, ``telemetry/``). Staged emits are
  sanctioned and recognized by naming convention: a receiver whose
  trailing identifier contains ``batch`` (``self._trace_batch.emit``,
  ``ring_batch.emit``) is an ``EmitBatch``, which exists precisely to
  be called per event.
"""

from __future__ import annotations

import ast

from pbs_tpu.analysis.core import CheckContext, Finding, Pass, SourceFile

#: Modules that implement the record layout / measure it — the strided
#: arithmetic lives there by design.
REC_MACHINERY = ("obs/trace.py", "perf/")

#: Packages whose event producers are hot enough to batch.
HOT_PACKAGES = ("sim/", "gateway/", "telemetry/")

#: Scalar per-event emitters the batching APIs replace in hot loops.
EMITTERS = ("emit", "trace_emit")


def _anchored(rel_path: str) -> str:
    parts = rel_path.replace("\\", "/").split("/")
    if "pbs_tpu" in parts:
        parts = parts[parts.index("pbs_tpu") + 1:]
    return "/".join(parts)


def _is_test(rel_path: str) -> bool:
    norm = rel_path.replace("\\", "/")
    return "tests/" in norm or norm.rsplit("/", 1)[-1].startswith("test_")


def _receiver_ident(func: ast.Attribute) -> str:
    """Trailing identifier of the emit receiver: ``self._trace_batch``
    -> "_trace_batch", ``ring`` -> "ring"."""
    base = func.value
    if isinstance(base, ast.Attribute):
        return base.attr
    if isinstance(base, ast.Name):
        return base.id
    return ""


def _mentions_rec_words(node: ast.AST) -> bool:
    return any(isinstance(sub, ast.Name) and sub.id == "TRACE_REC_WORDS"
               for sub in ast.walk(node))


class _PerfScan(ast.NodeVisitor):
    def __init__(self, src: SourceFile, rec_scope: bool, emit_scope: bool):
        self.src = src
        self.rec_scope = rec_scope
        self.emit_scope = emit_scope
        self.findings: list[Finding] = []
        self._loop_depth = 0

    def _visit_loop(self, node: ast.For | ast.While) -> None:
        if self.rec_scope and isinstance(node, ast.For) and any(
                _mentions_rec_words(stmt) for stmt in node.body):
            self.findings.append(Finding(
                "perf-rec-loop", self.src.rel_path, node.lineno,
                node.col_offset,
                "per-record loop over a TRACE_REC_WORDS-strided buffer — "
                "one slice copy per record is the scalar path the "
                "vectorized ring APIs replaced",
                hint="move records in bulk: TraceBuffer.emit_many / "
                     "consume / peek copy the wrapped span in at most "
                     "two contiguous slices (obs/trace.py)"))
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _visit_loop
    visit_While = _visit_loop

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if (self.emit_scope and self._loop_depth > 0
                and isinstance(func, ast.Attribute)
                and func.attr in EMITTERS
                and "batch" not in _receiver_ident(func).lower()):
            self.findings.append(Finding(
                "perf-emit-in-loop", self.src.rel_path, node.lineno,
                node.col_offset,
                f"scalar .{func.attr}() inside a loop in a hot producer "
                "package — every event pays the full ring-emit cost",
                hint="stage through an EmitBatch (one vectorized "
                     "emit_many per watermark) or build the records and "
                     "call emit_many once (obs/trace.py)"))
        self.generic_visit(node)


class PerfDisciplinePass(Pass):
    id = "perf-discipline"
    rules = ("perf-rec-loop", "perf-emit-in-loop")
    description = ("trace/telemetry hot paths stay vectorized: no "
                   "per-record TRACE_REC_WORDS loops, no scalar ring "
                   "emits inside loops in sim/gateway/telemetry "
                   "(EmitBatch/emit_many are the sanctioned forms)")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None or _is_test(src.rel_path):
            return []
        anchored = _anchored(src.rel_path)
        rec_scope = not any(
            anchored == m or anchored.startswith(m) for m in REC_MACHINERY)
        emit_scope = any(anchored.startswith(p) for p in HOT_PACKAGES)
        if not (rec_scope or emit_scope):
            return []
        scan = _PerfScan(src, rec_scope, emit_scope)
        scan.visit(src.tree)
        return scan.findings
