"""Scheduler-ops conformance pass.

The sched registry (``sched/__init__.py`` / ``@register_scheduler``) is
the ops-table: every registered policy must present the interface
``sched/base.py`` declares — the shape Xen enforces at compile time
through ``struct scheduler`` and C type checking, which Python happily
skips. Three rules:

- ``sched-ops-missing``: a registered policy does not implement a
  required (abstract) hook — ``wake``, ``do_schedule``.
- ``sched-ops-signature``: an implemented hook's positional parameters
  differ from the ops-table declaration (wrong arity or names — the
  calls are positional in the dispatch hot path, so a renamed/extra
  parameter is a latent TypeError or silent misbind).
- ``sched-ops-clamp``: ``do_schedule`` returns a ``Decision`` whose
  quantum derives from ``params.tslice_us`` without clamping it into
  the dispatch-legal band — the exact bug class PR 1's feedback
  ``_shrink`` clamp fixed: an out-of-band store write (operator
  ``sched-credit -t``, restore of an old save) lands a slice outside
  [TSLICE_MIN_US, TSLICE_MAX_US] and the policy dispatches it
  verbatim. Clamp with ``sched.base.clamp_tslice_us`` (or an
  equivalent min/max) at the Decision site.

When ``sched/base.py`` is among the scanned files the ops-table spec is
parsed from it (so the checker can never drift from the code); when a
subset of files is checked, a built-in fallback spec of the required
hooks is used.
"""

from __future__ import annotations

import ast

from pbs_tpu.analysis.core import CheckContext, Finding, Pass, SourceFile

#: Fallback ops-table spec, used only when sched/base.py is not among
#: the scanned files. hook -> positional params after self.
FALLBACK_REQUIRED = {
    "wake": ["ctx"],
    "do_schedule": ["ex", "now_ns"],
}
FALLBACK_OPTIONAL = {
    "executor_added": ["ex"],
    "executor_removed": ["ex"],
    "job_added": ["job"],
    "job_removed": ["job"],
    "sleep": ["ctx"],
    "yield_": ["ctx"],
    "pick_executor": ["ctx"],
    "descheduled": ["ex", "ctx", "ran_ns", "now_ns"],
    "dump_settings": [],
    "dump_executor": ["ex"],
    "dump_admin_conf": [],
}

#: Names accepted as a clamp at the Decision site.
CLAMP_CALLS = ("clamp_tslice_us", "_clamp", "clamp")


def _params_of(fn: ast.FunctionDef) -> list[str]:
    args = [a.arg for a in fn.args.args]
    return args[1:] if args and args[0] == "self" else args


def _is_abstract(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        name = dec.attr if isinstance(dec, ast.Attribute) else \
            dec.id if isinstance(dec, ast.Name) else ""
        if name == "abstractmethod":
            return True
    return False


def _registered_classes(tree: ast.AST) -> list[ast.ClassDef]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for dec in node.decorator_list:
                name = dec.id if isinstance(dec, ast.Name) else \
                    dec.attr if isinstance(dec, ast.Attribute) else ""
                if name == "register_scheduler":
                    out.append(node)
    return out


def _mentions_tslice(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "tslice_us":
            return True
        if isinstance(sub, ast.Name) and sub.id == "tslice_us":
            return True
    return False


def _has_clamp(node: ast.AST) -> bool:
    has_min = has_max = False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = sub.func
            name = fn.id if isinstance(fn, ast.Name) else \
                fn.attr if isinstance(fn, ast.Attribute) else ""
            if name in CLAMP_CALLS:
                return True
            if name == "min":
                has_min = True
            if name == "max":
                has_max = True
    return has_min and has_max


class SchedOpsPass(Pass):
    id = "sched-ops"
    rules = ("sched-ops-missing", "sched-ops-signature", "sched-ops-clamp")
    description = ("registered policies implement the sched/base.py "
                   "ops table with matching signatures and clamp "
                   "tslice-derived quanta at the Decision site")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None:
            return []
        path = src.rel_path.replace("\\", "/")
        if path.endswith("sched/base.py"):
            required: dict[str, list[str]] = {}
            optional: dict[str, list[str]] = {}
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef) and node.name == "Scheduler":
                    for item in node.body:
                        if not isinstance(item, ast.FunctionDef) or \
                                item.name.startswith("__"):
                            continue
                        spec = required if _is_abstract(item) else optional
                        spec[item.name] = _params_of(item)
            if required:
                ctx.state["sched_ops_spec"] = (required, optional)
        regs = _registered_classes(src.tree)
        if regs:
            ctx.state.setdefault("sched_classes", []).append((src, regs))
        return []

    def finalize(self, ctx: CheckContext) -> list[Finding]:
        required, optional = ctx.state.get(
            "sched_ops_spec", (FALLBACK_REQUIRED, FALLBACK_OPTIONAL))
        findings: list[Finding] = []
        for src, classes in ctx.state.get("sched_classes", []):
            for cls in classes:
                methods = {m.name: m for m in cls.body
                           if isinstance(m, ast.FunctionDef)}
                for hook, params in sorted(required.items()):
                    if hook not in methods:
                        findings.append(Finding(
                            "sched-ops-missing", src.rel_path, cls.lineno,
                            cls.col_offset,
                            f"registered scheduler {cls.name!r} does not "
                            f"implement required ops-table hook {hook!r}",
                            hint=f"def {hook}(self, {', '.join(params)}): "
                                 "... (see sched/base.py)"))
                for hook, m in sorted(methods.items()):
                    spec = required.get(hook) or optional.get(hook)
                    if spec is None:
                        continue
                    got = _params_of(m)
                    if got != spec:
                        findings.append(Finding(
                            "sched-ops-signature", src.rel_path, m.lineno,
                            m.col_offset,
                            f"{cls.name}.{hook} signature ({', '.join(got)}) "
                            f"does not match the ops table "
                            f"({', '.join(spec)})",
                            hint="the dispatch path calls hooks "
                                 "positionally; match sched/base.py "
                                 "parameter names and order"))
                self._check_clamp(src, cls, methods, findings)
        return findings

    def _check_clamp(self, src: SourceFile, cls: ast.ClassDef,
                     methods: dict[str, ast.FunctionDef],
                     findings: list[Finding]) -> None:
        do_sched = methods.get("do_schedule")
        if do_sched is None:
            return
        for node in ast.walk(do_sched):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            callee = fn.id if isinstance(fn, ast.Name) else \
                fn.attr if isinstance(fn, ast.Attribute) else ""
            if callee != "Decision":
                continue
            quantum = node.args[1] if len(node.args) >= 2 else None
            for kw in node.keywords:
                if kw.arg == "quantum_ns":
                    quantum = kw.value
            if quantum is None:
                continue
            if _mentions_tslice(quantum) and not _has_clamp(quantum):
                findings.append(Finding(
                    "sched-ops-clamp", src.rel_path, quantum.lineno,
                    quantum.col_offset,
                    f"{cls.name}.do_schedule dispatches a tslice_us-derived "
                    "quantum without clamping to the dispatch-legal band",
                    hint="wrap with sched.base.clamp_tslice_us(...) — "
                         "out-of-band store writes can land tslice_us "
                         "outside [TSLICE_MIN_US, TSLICE_MAX_US]"))
