"""Durability-discipline pass.

The write-ahead intent journal (gateway/journal.py,
docs/DURABILITY.md) only recovers what was journaled FIRST: a queue or
lease mutation that sneaks past the journal is state a crash silently
loses, and a recovery path that consumes journal frames without the
sealed read surface silently replays torn or corrupt bytes. Two rules:

- ``dur-unjournaled-mutation`` — inside gateway-machinery modules
  (files under a ``gateway/`` directory, minus the queue/journal/
  replay internals and the chaos harness), a durable-state mutation —
  ``queue.push`` / ``queue.requeue_front`` / ``queue.restore_tenant``,
  an ``inflight[...]`` assignment, or a bucket ``.credit(...)`` lease
  top-up — with NO journal intent earlier in the same function body
  (custody-transfer verbs like ``adopt`` journal inside the adopting
  gateway, so their queue ops are covered there). The ordering
  is positional by design: the intent emit (or the ``journal``-guard
  that wraps it) must textually precede the mutation it covers.
- ``dur-unsealed-read`` — a function that consumes journal bytes
  (mentions a journal-ish name or a ``.jrnl`` path) and unpacks raw
  records (``struct.unpack``/``unpack_from``/``np.frombuffer``)
  without going through the sealed read surface (``read_journal``) or
  validating CRCs itself (``zlib.crc32``). Torn-tail and corrupt-body
  handling live in exactly one place; a second bespoke parser WILL
  forget one of them.
"""

from __future__ import annotations

import ast

from pbs_tpu.analysis.core import (
    CheckContext,
    Finding,
    Pass,
    SourceFile,
    qualified_name,
)

#: Attribute-call mutation surface: method name -> receiver rule
#: ("queue" = base name must contain "queue"; None = any receiver).
_MUTATIONS: dict[str, str | None] = {
    "push": "queue",
    "requeue_front": "queue",
    "restore_tenant": "queue",
    "credit": None,
}

#: Modules under gateway/ that ARE the machinery the rules protect
#: (the queue implementation itself, the journal/replay pair, and the
#: chaos harness that deliberately plays adversary).
_EXEMPT_FILES = ("fairqueue.py", "journal.py", "recovery.py",
                 "chaos.py")

_UNPACKERS = {"unpack", "unpack_from", "frombuffer"}
_SEALED = ("read_journal", "crc32")


def _in_gateway_machinery(rel_path: str) -> bool:
    norm = rel_path.replace("\\", "/")
    if "tests/" in norm or norm.rsplit("/", 1)[-1].startswith("test_"):
        return False
    parts = norm.split("/")
    if "gateway" not in parts[:-1]:
        return False
    return parts[-1] not in _EXEMPT_FILES


def _is_test_path(rel_path: str) -> bool:
    norm = rel_path.replace("\\", "/")
    return "tests/" in norm or norm.rsplit("/", 1)[-1].startswith("test_")


class _FnScan(ast.NodeVisitor):
    """Per-function facts: first line mentioning a journal, mutation
    calls, raw unpack calls, journal-ish references, sealed-read
    calls. Nested defs are scanned as their own scopes by the outer
    walker, not here."""

    def __init__(self) -> None:
        self.journal_mention: int | None = None
        self.mutations: list[tuple[str, ast.Call]] = []
        self.unpacks: list[ast.Call] = []
        self.journal_ish = False
        self.sealed = False

    def _note_name(self, text: str, line: int) -> None:
        low = text.lower()
        if "journal" in low or low.endswith(".jrnl"):
            self.journal_ish = True
            if self.journal_mention is None or line < self.journal_mention:
                self.journal_mention = line

    def visit_FunctionDef(self, node):  # nested scopes scan separately
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Name(self, node: ast.Name) -> None:
        self._note_name(node.id, node.lineno)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self._note_name(node.attr, node.lineno)
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant) -> None:
        if isinstance(node.value, str):
            self._note_name(node.value, node.lineno)

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._flag_inflight(tgt, node)
        self.generic_visit(node)

    def _flag_inflight(self, target: ast.AST, node: ast.AST) -> None:
        # self.inflight[rid] = req — the dispatch-side durable move.
        if (isinstance(target, ast.Subscript)
                and isinstance(target.value, ast.Attribute)
                and target.value.attr == "inflight"):
            fake = ast.Call(func=ast.Attribute(
                value=target.value.value, attr="inflight",
                ctx=ast.Load()), args=[], keywords=[])
            ast.copy_location(fake, node)
            self.mutations.append(("inflight[...] assignment", fake))

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        qual = qualified_name(func) or ""
        leaf = qual.rsplit(".", 1)[-1]
        if isinstance(func, ast.Attribute):
            rule = _MUTATIONS.get(func.attr)
            if rule is not None or func.attr in _MUTATIONS:
                base = func.value
                base_name = ""
                if isinstance(base, ast.Attribute):
                    base_name = base.attr
                elif isinstance(base, ast.Name):
                    base_name = base.id
                receiver_ok = (rule is None
                               or rule in base_name.lower())
                # The journal's own emit helpers share verb names
                # (journal.adopt / journal.adopt_tenant ARE the
                # intents, not mutations).
                if receiver_ok and "journal" not in base_name.lower() \
                        and base_name not in ("jr", "j"):
                    self.mutations.append((f".{func.attr}(...)", node))
        if leaf in _UNPACKERS:
            self.unpacks.append(node)
        if any(s in qual for s in _SEALED):
            self.sealed = True
        self.generic_visit(node)


def _walk_functions(tree: ast.AST):
    """Yield every function/method node, outermost first."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class DurabilityPass(Pass):
    id = "durability-discipline"
    rules = ("dur-unjournaled-mutation", "dur-unsealed-read")
    description = ("write-ahead ordering in gateway machinery (queue/"
                   "lease mutations need a preceding journal intent in "
                   "the same function) and sealed journal reads "
                   "(frame consumers go through read_journal or "
                   "validate CRCs; torn/corrupt handling lives in one "
                   "place)")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None or _is_test_path(src.rel_path):
            return []
        findings: list[Finding] = []
        machinery = _in_gateway_machinery(src.rel_path)
        is_journal_impl = src.rel_path.replace("\\", "/").endswith(
            "gateway/journal.py")
        for fn in _walk_functions(src.tree):
            scan = _FnScan()
            for stmt in fn.body:
                scan.visit(stmt)
            # The function's own name/args count toward "this is
            # journal-consuming code" (load_journal, path="x.jrnl") —
            # but NOT toward the write-ahead ordering line, which only
            # body statements can satisfy.
            names = [fn.name] + [a.arg for a in fn.args.args]
            if any("journal" in n.lower() or n.lower().endswith(".jrnl")
                   for n in names):
                scan.journal_ish = True
            if machinery:
                for label, node in scan.mutations:
                    if (scan.journal_mention is None
                            or node.lineno < scan.journal_mention):
                        findings.append(Finding(
                            "dur-unjournaled-mutation", src.rel_path,
                            node.lineno, node.col_offset,
                            f"durable gateway state moves ({label} in "
                            f"{fn.name}) with no preceding journal "
                            "intent in this function — a crash here "
                            "silently loses the transition",
                            hint="emit the matching GatewayJournal "
                                 "intent (admit/dispatch/complete/"
                                 "requeue/adopt/grant) BEFORE the "
                                 "mutation; see docs/DURABILITY.md"))
            if (scan.journal_ish and scan.unpacks and not scan.sealed
                    and not is_journal_impl):
                node = scan.unpacks[0]
                findings.append(Finding(
                    "dur-unsealed-read", src.rel_path,
                    node.lineno, node.col_offset,
                    f"{fn.name} parses journal bytes with a raw "
                    "unpack and never validates frames — torn tails "
                    "and CRC-corrupt bodies would replay silently",
                    hint="consume frames through gateway.journal."
                         "read_journal (the sealed read surface), or "
                         "verify zlib.crc32 per frame like it does"))
        return findings
