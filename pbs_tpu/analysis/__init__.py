"""Static invariant checker suite (``pbst check``).

See docs/ANALYSIS.md for the checker list, suppression syntax, and how
to add a pass. Import surface mirrors the other subsystems: the
framework types, the suite registry, and the entry points the CLI and
tests drive.
"""

from pbs_tpu.analysis.core import (
    CheckContext,
    CSourceFile,
    Finding,
    Pass,
    SourceFile,
)
from pbs_tpu.analysis.runner import (
    ALL_PASSES,
    CheckResult,
    changed_check_files,
    changed_py_files,
    check_paths,
    format_human,
    iter_check_files,
    iter_py_files,
    list_suppressions,
    load_dynamic_graph,
    pass_ids,
)

__all__ = [
    "ALL_PASSES",
    "CheckContext",
    "CheckResult",
    "CSourceFile",
    "Finding",
    "Pass",
    "SourceFile",
    "changed_check_files",
    "changed_py_files",
    "check_paths",
    "format_human",
    "iter_check_files",
    "iter_py_files",
    "list_suppressions",
    "load_dynamic_graph",
    "pass_ids",
]
