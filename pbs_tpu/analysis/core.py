"""Static invariant checking framework (the ``pbst check`` core).

PR 1 gated policy *behavior* offline (the sim regression harness); this
subsystem gates policy *code* the same way: a repo-aware AST analysis
pass suite that enforces the invariants the runtime/sched/telemetry
layers already rely on implicitly — lock discipline (lockdep's static
twin), time-unit suffix consistency, scheduler-ops conformance, and
counter-API usage. The framework is deliberately small: passes visit
parsed files and emit :class:`Finding` records; the runner collects,
filters suppressions, and formats.

Suppression syntax (reviewed escapes, never silent):

- line:  ``# pbst: ignore[rule-id] -- justification``
- file:  ``# pbst: ignore-file[rule-id] -- justification``

A suppression **must** carry a justification after ``--`` or it is
itself reported (rule ``bad-suppression``). Rule ``*`` matches every
rule (use sparingly).

No dependency on jax/numpy: ``pbst check`` must run anywhere the repo
checks out, including CI images with no accelerator stack.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Any

#: Time-unit suffixes the taxonomy uses (clock.py: ns is canonical).
UNIT_SUFFIXES = ("ns", "us", "ms")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One checker hit, anchored to file:line:col with a fix hint."""

    check: str  # rule id, e.g. "lock-raw"
    path: str
    line: int
    col: int
    message: str
    hint: str = ""

    def sort_key(self) -> tuple:
        return (self.path, self.line, self.col, self.check, self.message)

    def as_dict(self) -> dict[str, Any]:
        d = {
            "check": self.check,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }
        if self.hint:
            d["hint"] = self.hint
        return d

    def format(self) -> str:
        s = f"{self.path}:{self.line}:{self.col}: [{self.check}] {self.message}"
        if self.hint:
            s += f"\n    hint: {self.hint}"
        return s


_SUPPRESS_RE = re.compile(
    r"#\s*pbst:\s*(ignore|ignore-file)\[([A-Za-z0-9_*,\s-]+)\]"
    r"(?:\s*--\s*(.*))?")


@dataclasses.dataclass(frozen=True)
class Suppression:
    rules: tuple[str, ...]
    line: int  # comment line (line-level applies to this physical line)
    file_wide: bool
    justification: str

    def matches(self, rule: str, line: int) -> bool:
        if rule == "bad-suppression":
            return False  # the escape hatch cannot hide its own misuse
        if not any(r == "*" or r == rule for r in self.rules):
            return False
        return self.file_wide or line == self.line


class SourceFile:
    """One parsed source file: AST + per-line suppression table."""

    def __init__(self, path: str, text: str, rel_path: str | None = None):
        self.path = path
        #: Path as reported in findings (relative to the check root).
        self.rel_path = rel_path if rel_path is not None else path
        self.text = text
        self.tree: ast.AST | None = None
        self.parse_error: Finding | None = None
        self.suppressions: list[Suppression] = []
        self.bad_suppressions: list[Finding] = []
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as e:
            self.parse_error = Finding(
                "parse-error", self.rel_path, e.lineno or 1, e.offset or 0,
                f"cannot parse: {e.msg}")
        self._scan_comments()

    def _scan_comments(self) -> None:
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.text).readline)
            comments = [(t.start[0], t.string) for t in toks
                        if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = [
                (i + 1, ln[ln.index("#"):])
                for i, ln in enumerate(self.text.splitlines()) if "#" in ln
            ]
        for line, comment in comments:
            m = _SUPPRESS_RE.search(comment)
            if m is None:
                if "pbst:" in comment and "ignore" in comment:
                    self.bad_suppressions.append(Finding(
                        "bad-suppression", self.rel_path, line, 0,
                        f"unparseable suppression comment: {comment.strip()!r}",
                        hint="syntax: # pbst: ignore[rule-id] -- justification"))
                continue
            kind, rules_s, just = m.group(1), m.group(2), m.group(3)
            rules = tuple(r.strip() for r in rules_s.split(",") if r.strip())
            if not (just or "").strip():
                self.bad_suppressions.append(Finding(
                    "bad-suppression", self.rel_path, line, 0,
                    "suppression without a justification",
                    hint="append ' -- why this is safe' to the comment"))
                continue
            self.suppressions.append(Suppression(
                rules=rules, line=line, file_wide=(kind == "ignore-file"),
                justification=just.strip()))

    def suppressed(self, rule: str, line: int) -> bool:
        return any(s.matches(rule, line) for s in self.suppressions)


class CheckContext:
    """Shared state for one ``pbst check`` run (all files + options)."""

    def __init__(self, files: list[SourceFile],
                 dynamic_lock_edges: set[tuple[str, str]] | None = None):
        self.files = files
        #: Dynamic lock-order graph edges (from ``pbst lockdep
        #: --dump-graph``) merged into the static cross-check.
        self.dynamic_lock_edges = dynamic_lock_edges or set()
        #: Scratch space for passes that accumulate across files.
        self.state: dict[str, Any] = {}


class Pass:
    """One checker. Subclasses set ``id``/``rules`` and override
    :meth:`run` (per file) and optionally :meth:`finalize` (after every
    file was visited — cross-file analyses report here)."""

    id: str = "abstract"
    #: Rule ids this pass can emit (drives --list-passes and docs).
    rules: tuple[str, ...] = ()
    description: str = ""

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        return []

    def finalize(self, ctx: CheckContext) -> list[Finding]:
        return []


# -- shared AST helpers -----------------------------------------------------


def qualified_name(node: ast.AST) -> str | None:
    """Dotted name for Name/Attribute chains (``time.sleep`` ->
    "time.sleep"); None for anything dynamic."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = qualified_name(node.value)
        return f"{base}.{node.attr}" if base is not None else None
    return None


def identifier_of(node: ast.AST) -> str | None:
    """The trailing identifier a human would read a unit suffix off:
    ``job.params.tslice_us`` -> "tslice_us"; ``Counter.RUNQ_WAIT_NS``
    -> "RUNQ_WAIT_NS"; subscripts defer to the index when it carries a
    suffix (``snap[Counter.DEVICE_TIME_NS]``)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        idx = node.slice
        ident = identifier_of(idx)
        if ident is not None and unit_of_identifier(ident) is not None:
            return ident
        return identifier_of(node.value)
    if isinstance(node, ast.UnaryOp):
        return identifier_of(node.operand)
    return None


def unit_of_identifier(ident: str) -> str | None:
    low = ident.lower()
    for suf in UNIT_SUFFIXES:
        if low.endswith("_" + suf):
            return suf
    return None


