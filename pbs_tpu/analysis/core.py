"""Static invariant checking framework (the ``pbst check`` core).

PR 1 gated policy *behavior* offline (the sim regression harness); this
subsystem gates policy *code* the same way: a repo-aware AST analysis
pass suite that enforces the invariants the runtime/sched/telemetry
layers already rely on implicitly — lock discipline (lockdep's static
twin), time-unit suffix consistency, scheduler-ops conformance, and
counter-API usage. The framework is deliberately small: passes visit
parsed files and emit :class:`Finding` records; the runner collects,
filters suppressions, and formats.

Suppression syntax (reviewed escapes, never silent):

- line:  ``# pbst: ignore[rule-id] -- justification``
- file:  ``# pbst: ignore-file[rule-id] -- justification``

A suppression **must** carry a justification after ``--`` or it is
itself reported (rule ``bad-suppression``). Rule ``*`` matches every
rule (use sparingly).

No dependency on jax/numpy: ``pbst check`` must run anywhere the repo
checks out, including CI images with no accelerator stack.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Any

#: Time-unit suffixes the taxonomy uses (clock.py: ns is canonical).
UNIT_SUFFIXES = ("ns", "us", "ms")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One checker hit, anchored to file:line:col with a fix hint."""

    check: str  # rule id, e.g. "lock-raw"
    path: str
    line: int
    col: int
    message: str
    hint: str = ""

    def sort_key(self) -> tuple:
        return (self.path, self.line, self.col, self.check, self.message)

    def as_dict(self) -> dict[str, Any]:
        d = {
            "check": self.check,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }
        if self.hint:
            d["hint"] = self.hint
        return d

    def format(self) -> str:
        s = f"{self.path}:{self.line}:{self.col}: [{self.check}] {self.message}"
        if self.hint:
            s += f"\n    hint: {self.hint}"
        return s


_SUPPRESS_RE = re.compile(
    r"#\s*pbst:\s*(ignore|ignore-file)\[([A-Za-z0-9_*,\s-]+)\]"
    r"(?:\s*--\s*(.*))?")

#: Same grammar behind a C ``//`` comment leader (native/*.cc sources).
_C_SUPPRESS_RE = re.compile(
    r"//\s*pbst:\s*(ignore|ignore-file)\[([A-Za-z0-9_*,\s-]+)\]"
    r"(?:\s*--\s*(.*))?")


@dataclasses.dataclass(frozen=True)
class Suppression:
    rules: tuple[str, ...]
    line: int  # comment line (line-level applies to this physical line)
    file_wide: bool
    justification: str

    def matches(self, rule: str, line: int) -> bool:
        if rule == "bad-suppression":
            return False  # the escape hatch cannot hide its own misuse
        if not any(r == "*" or r == rule for r in self.rules):
            return False
        return self.file_wide or line == self.line


def _classify_comment(regex: re.Pattern, comment: str, line: int,
                      rel_path: str, leader: str):
    """One comment string -> Suppression, bad-suppression Finding, or
    None (not a suppression comment at all). Shared by the Python and
    C scanners so both languages get the same grammar and the same
    justification-or-report contract."""
    m = regex.search(comment)
    if m is None:
        if "pbst:" in comment and "ignore" in comment:
            return Finding(
                "bad-suppression", rel_path, line, 0,
                f"unparseable suppression comment: {comment.strip()!r}",
                hint=f"syntax: {leader} pbst: ignore[rule-id] -- "
                     "justification")
        return None
    kind, rules_s, just = m.group(1), m.group(2), m.group(3)
    rules = tuple(r.strip() for r in rules_s.split(",") if r.strip())
    if not (just or "").strip():
        return Finding(
            "bad-suppression", rel_path, line, 0,
            "suppression without a justification",
            hint="append ' -- why this is safe' to the comment")
    return Suppression(
        rules=rules, line=line, file_wide=(kind == "ignore-file"),
        justification=just.strip())


class SourceFile:
    """One parsed source file: AST + per-line suppression table."""

    def __init__(self, path: str, text: str, rel_path: str | None = None):
        self.path = path
        #: Path as reported in findings (relative to the check root).
        self.rel_path = rel_path if rel_path is not None else path
        self.text = text
        self.tree: ast.AST | None = None
        self.parse_error: Finding | None = None
        self.suppressions: list[Suppression] = []
        self.bad_suppressions: list[Finding] = []
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as e:
            self.parse_error = Finding(
                "parse-error", self.rel_path, e.lineno or 1, e.offset or 0,
                f"cannot parse: {e.msg}")
        self._scan_comments()

    def _scan_comments(self) -> None:
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.text).readline)
            comments = [(t.start[0], t.string) for t in toks
                        if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = [
                (i + 1, ln[ln.index("#"):])
                for i, ln in enumerate(self.text.splitlines()) if "#" in ln
            ]
        for line, comment in comments:
            got = _classify_comment(_SUPPRESS_RE, comment, line,
                                    self.rel_path, "#")
            if isinstance(got, Suppression):
                self.suppressions.append(got)
            elif isinstance(got, Finding):
                self.bad_suppressions.append(got)

    def suppressed(self, rule: str, line: int) -> bool:
        return any(s.matches(rule, line) for s in self.suppressions)


class CSourceFile:
    """One C/C++ source file (native/*.cc): raw text + the same
    per-line suppression table as :class:`SourceFile`, behind ``//``
    comment leaders. No AST — the memmodel passes run their own
    tokenizing scans over :attr:`code` (text with comments and string
    literals blanked, so protocol patterns never match prose).

    Duck-compatible with SourceFile where the runner cares:
    ``rel_path``/``suppressions``/``bad_suppressions``/``suppressed``.
    """

    is_c = True

    def __init__(self, path: str, text: str, rel_path: str | None = None):
        self.path = path
        self.rel_path = rel_path if rel_path is not None else path
        self.text = text
        self.suppressions: list[Suppression] = []
        self.bad_suppressions: list[Finding] = []
        self.code = self._blank_noncode(text)
        for i, ln in enumerate(self.code.splitlines()):
            # Comment start = the first // that survives string
            # blanking (a // inside a string literal is code).
            col = ln.find("//")
            if col < 0:
                continue
            got = _classify_comment(_C_SUPPRESS_RE, ln[col:], i + 1,
                                    self.rel_path, "//")
            if isinstance(got, Suppression):
                self.suppressions.append(got)
            elif isinstance(got, Finding):
                self.bad_suppressions.append(got)

    @staticmethod
    def _blank_noncode(text: str) -> str:
        """``text`` with double-quoted string literals and /* */
        comment bodies replaced by spaces (newlines kept, so offsets
        and line numbers survive). // comments are KEPT verbatim — the
        suppression scanner needs them — and stripped later by
        :meth:`code_lines`. Single quotes are left alone: this tree
        uses them as C++14 digit separators (0x70627374'6462ULL), not
        char literals, and a naive quote-matcher would blank real code
        between two separators."""
        out = []
        i, n = 0, len(text)
        while i < n:
            c = text[i]
            if c == '"':
                out.append(c)
                i += 1
                while i < n and text[i] != '"':
                    if text[i] == "\\" and i + 1 < n:
                        out.append("  ")
                        i += 2
                        continue
                    out.append("\n" if text[i] == "\n" else " ")
                    i += 1
                if i < n:
                    out.append('"')
                    i += 1
            elif c == "/" and i + 1 < n and text[i + 1] == "*":
                out.append("  ")
                i += 2
                while i + 1 < n and not (text[i] == "*"
                                         and text[i + 1] == "/"):
                    out.append("\n" if text[i] == "\n" else " ")
                    i += 1
                if i + 1 < n:
                    out.append("  ")
                    i += 2
            elif c == "/" and i + 1 < n and text[i + 1] == "/":
                while i < n and text[i] != "\n":
                    out.append(text[i])
                    i += 1
            else:
                out.append(c)
                i += 1
        return "".join(out)

    def code_lines(self) -> list[str]:
        """Per-line code with // comments stripped too (1-based via
        index+1). The surface the memmodel token scans run over."""
        out = []
        for ln in self.code.splitlines():
            cut = ln.find("//")
            out.append(ln if cut < 0 else ln[:cut])
        return out

    def suppressed(self, rule: str, line: int) -> bool:
        return any(s.matches(rule, line) for s in self.suppressions)


class CheckContext:
    """Shared state for one ``pbst check`` run (all files + options)."""

    def __init__(self, files: list[SourceFile],
                 dynamic_lock_edges: set[tuple[str, str]] | None = None,
                 c_files: list[CSourceFile] | None = None):
        self.files = files
        #: C/C++ sources (native/*.cc) in the scan set — visited by
        #: passes that override :meth:`Pass.run_c` (the cross-language
        #: memmodel passes). Empty for pure-Python runs.
        self.c_files = c_files or []
        #: Dynamic lock-order graph edges (from ``pbst lockdep
        #: --dump-graph``) merged into the static cross-check.
        self.dynamic_lock_edges = dynamic_lock_edges or set()
        #: Scratch space for passes that accumulate across files.
        self.state: dict[str, Any] = {}


class Pass:
    """One checker. Subclasses set ``id``/``rules`` and override
    :meth:`run` (per file) and optionally :meth:`finalize` (after every
    file was visited — cross-file analyses report here)."""

    id: str = "abstract"
    #: Rule ids this pass can emit (drives --list-passes and docs).
    rules: tuple[str, ...] = ()
    description: str = ""

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        return []

    def run_c(self, csrc: CSourceFile, ctx: CheckContext) -> list[Finding]:
        """Per C source file (native/*.cc). Only the cross-language
        passes override this; pure-Python passes never see C files."""
        return []

    def finalize(self, ctx: CheckContext) -> list[Finding]:
        return []


# -- shared AST helpers -----------------------------------------------------


def qualified_name(node: ast.AST) -> str | None:
    """Dotted name for Name/Attribute chains (``time.sleep`` ->
    "time.sleep"); None for anything dynamic."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = qualified_name(node.value)
        return f"{base}.{node.attr}" if base is not None else None
    return None


def identifier_of(node: ast.AST) -> str | None:
    """The trailing identifier a human would read a unit suffix off:
    ``job.params.tslice_us`` -> "tslice_us"; ``Counter.RUNQ_WAIT_NS``
    -> "RUNQ_WAIT_NS"; subscripts defer to the index when it carries a
    suffix (``snap[Counter.DEVICE_TIME_NS]``)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        idx = node.slice
        ident = identifier_of(idx)
        if ident is not None and unit_of_identifier(ident) is not None:
            return ident
        return identifier_of(node.value)
    if isinstance(node, ast.UnaryOp):
        return identifier_of(node.operand)
    return None


def unit_of_identifier(ident: str) -> str | None:
    low = ident.lower()
    for suf in UNIT_SUFFIXES:
        if low.endswith("_" + suf):
            return suf
    return None


