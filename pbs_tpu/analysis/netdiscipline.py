"""Network-discipline pass.

Control-plane bytes ride exactly one transport: :class:`dist.rpc
.RpcClient`, whose ``call`` owns bounded retries with capped backoff,
the per-op deadline, and the idempotency token the server deduplicates
(docs/FAULTS.md). What breaks is a caller in the distributed layers
opening its own socket or reaching into the client's private transport
helpers — that traffic silently loses every one of those guarantees: a
dropped frame hangs or desyncs instead of retrying, a retried mutation
re-executes instead of deduplicating, and no deadline bounds the call.
Two rules, scoped to ``dist/`` and ``ckpt/`` (the layers that talk to
peers); ``dist/rpc.py`` is exempt — it *implements* the transport:

- ``net-raw-socket``: a direct ``socket.socket`` /
  ``socket.create_connection`` / ``socket.socketpair`` construction —
  a private wire the retry/deadline/idempotency machinery never sees.
- ``net-raw-transport``: a call to the client's private helpers
  (``._roundtrip(...)`` / ``._call_raw(...)``) — ``_roundtrip``
  bypasses retries AND the idempotency token; ``_call_raw`` bypasses
  the token, so a retried mutation may execute twice.
"""

from __future__ import annotations

import ast

from pbs_tpu.analysis.core import (
    CheckContext,
    Finding,
    Pass,
    SourceFile,
    qualified_name,
)

#: Packages whose modules must speak RpcClient.call, never raw sockets.
NET_PACKAGES = ("dist", "ckpt")

#: The transport implementation itself (relative to the package root).
MACHINERY = ("dist/rpc.py",)

#: Socket constructors that open a private wire.
RAW_SOCKET_CALLS = {
    "socket.socket": "socket construction",
    "socket.create_connection": "socket connect",
    "socket.socketpair": "socket pair",
}

#: RpcClient private transport helpers and what skipping them loses.
PRIVATE_HELPERS = {
    "_roundtrip": "retries, the deadline, and the idempotency token",
    "_call_raw": "the idempotency token (a retried mutation may "
                 "execute twice)",
}


def _anchored(rel_path: str) -> list[str]:
    parts = rel_path.replace("\\", "/").split("/")
    if "pbs_tpu" in parts:
        parts = parts[parts.index("pbs_tpu") + 1:]
    return parts


def _net_module(rel_path: str) -> bool:
    parts = _anchored(rel_path)
    return bool(parts) and parts[0] in NET_PACKAGES


def _is_machinery(rel_path: str) -> bool:
    return "/".join(_anchored(rel_path)) in MACHINERY


class _NetScan(ast.NodeVisitor):
    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        qual = qualified_name(node.func)
        if qual in RAW_SOCKET_CALLS:
            self.findings.append(Finding(
                "net-raw-socket", self.src.rel_path, node.lineno,
                node.col_offset,
                f"raw {RAW_SOCKET_CALLS[qual]} ({qual}) in the control "
                "plane — this wire has no retries, no deadline, no "
                "idempotency",
                hint="speak RpcClient.call (dist/rpc.py); it owns "
                     "bounded retries, the per-op deadline, and the "
                     "idempotency token"))
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in PRIVATE_HELPERS:
            self.findings.append(Finding(
                "net-raw-transport", self.src.rel_path, node.lineno,
                node.col_offset,
                f"private transport helper .{node.func.attr}() called "
                f"outside dist/rpc.py — bypasses "
                f"{PRIVATE_HELPERS[node.func.attr]}",
                hint="use RpcClient.call / multicall; pass _deadline= "
                     "to bound the whole retry loop"))
        self.generic_visit(node)


class NetDisciplinePass(Pass):
    id = "net-discipline"
    rules = ("net-raw-socket", "net-raw-transport")
    description = ("control-plane traffic in dist//ckpt/ rides "
                   "RpcClient.call (retries, deadline, idempotency); "
                   "raw sockets and private transport helpers are "
                   "flagged")

    def run(self, src: SourceFile, ctx: CheckContext) -> list[Finding]:
        if src.tree is None or not _net_module(src.rel_path) \
                or _is_machinery(src.rel_path):
            return []
        scan = _NetScan(src)
        scan.visit(src.tree)
        return scan.findings
