from pbs_tpu.runtime.events import EventBus, EventChannel, Virq
from pbs_tpu.runtime.executor import Executor, quantum_to_steps
from pbs_tpu.runtime.job import ContextState, ExecutionContext, Job, SchedParams
from pbs_tpu.runtime.partition import Partition
from pbs_tpu.runtime.timer import Timer, TimerWheel

__all__ = [
    "ContextState",
    "EventBus",
    "EventChannel",
    "ExecutionContext",
    "Executor",
    "Virq",
    "Job",
    "Partition",
    "SchedParams",
    "Timer",
    "TimerWheel",
    "quantum_to_steps",
]
