from pbs_tpu.runtime.compile_gate import (
    CompileAdmission,
    CompileBudget,
    CompileBudgetExceeded,
)
from pbs_tpu.runtime.doorbell import Doorbell, bridge_events
from pbs_tpu.runtime.events import EventBus, EventChannel, Virq
from pbs_tpu.runtime.executor import Executor, quantum_to_steps
from pbs_tpu.runtime.hooks import HookError, HookRegistry
from pbs_tpu.runtime.image import boot_job, image_workload, save_image
from pbs_tpu.runtime.paging import (
    PagingError,
    page_in_job,
    page_out_job,
    register_paging_reclaim,
)
from pbs_tpu.runtime.sharing import SharedWeights, WeightsRegistry
from pbs_tpu.runtime.memory import (
    MemoryAccount,
    MemoryManager,
    OutOfDeviceMemory,
    device_memory_stats,
    nbytes_of,
)
from pbs_tpu.runtime.grants import (
    GrantBusy,
    GrantDenied,
    GrantError,
    GrantMapping,
    GrantTable,
    SharedRegion,
    map_grant,
)
from pbs_tpu.runtime.xsm import (
    DummyPolicy,
    LabelPolicy,
    XsmDenied,
    set_policy,
    xsm_check,
)
from pbs_tpu.runtime.job import ContextState, ExecutionContext, Job, SchedParams
from pbs_tpu.runtime.partition import Partition
from pbs_tpu.runtime.timer import Timer, TimerWheel
from pbs_tpu.runtime.watchdog import (
    WallWatchdog,
    Watchdog,
    install_crash_handler,
    write_crash_dump,
)

__all__ = [
    "CompileAdmission",
    "CompileBudget",
    "CompileBudgetExceeded",
    "ContextState",
    "Doorbell",
    "bridge_events",
    "DummyPolicy",
    "EventBus",
    "EventChannel",
    "ExecutionContext",
    "Executor",
    "GrantBusy",
    "GrantDenied",
    "GrantError",
    "GrantMapping",
    "GrantTable",
    "HookError",
    "HookRegistry",
    "LabelPolicy",
    "MemoryAccount",
    "MemoryManager",
    "OutOfDeviceMemory",
    "PagingError",
    "SharedRegion",
    "SharedWeights",
    "WeightsRegistry",
    "Virq",
    "Job",
    "Partition",
    "SchedParams",
    "Timer",
    "TimerWheel",
    "WallWatchdog",
    "Watchdog",
    "XsmDenied",
    "boot_job",
    "device_memory_stats",
    "image_workload",
    "install_crash_handler",
    "save_image",
    "map_grant",
    "nbytes_of",
    "page_in_job",
    "page_out_job",
    "quantum_to_steps",
    "register_paging_reclaim",
    "set_policy",
    "write_crash_dump",
    "xsm_check",
]
