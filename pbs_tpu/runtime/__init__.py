from pbs_tpu.runtime.events import EventBus, EventChannel, Virq
from pbs_tpu.runtime.executor import Executor, quantum_to_steps
from pbs_tpu.runtime.job import ContextState, ExecutionContext, Job, SchedParams
from pbs_tpu.runtime.partition import Partition
from pbs_tpu.runtime.timer import Timer, TimerWheel
from pbs_tpu.runtime.watchdog import (
    WallWatchdog,
    Watchdog,
    install_crash_handler,
    write_crash_dump,
)

__all__ = [
    "ContextState",
    "EventBus",
    "EventChannel",
    "ExecutionContext",
    "Executor",
    "Virq",
    "Job",
    "Partition",
    "SchedParams",
    "Timer",
    "TimerWheel",
    "WallWatchdog",
    "Watchdog",
    "install_crash_handler",
    "quantum_to_steps",
    "write_crash_dump",
]
