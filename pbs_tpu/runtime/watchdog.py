"""Watchdogs and crash dumps: failure detection for a partition.

Reference mapping (SURVEY.md §5 "failure detection"):

- The hypervisor's NMI watchdog drives a PMU counter so it can fire even
  when a CPU is wedged with interrupts off (``xen/arch/x86/nmi.c:38,
  249-302``). The TPU analog of "wedged with interrupts off" is a step
  that never returns (hung collective, tunnel loss): the cooperative run
  loop cannot observe it, so :class:`WallWatchdog` watches progress from
  its own thread — out-of-band by construction, like the NMI.
- Per-domain watchdogs (``tools/misc/xenwatchdogd.c``) require the guest
  to pet a timer or the domain is acted upon; :class:`Watchdog` is the
  in-loop equivalent, sampling executor/context progress from the timer
  wheel and flagging logical stalls (runnable work, no dispatch).
- On a fatal error Xen kexecs into a crash kernel and dumps state
  (``xen/common/kexec.c``); :func:`write_crash_dump` captures the
  postmortem (scheduler dump, per-context counters, trace tail,
  exception) as JSON next to the workload.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from typing import TYPE_CHECKING, Any, Callable

import itertools

from pbs_tpu.obs.trace import format_records
from pbs_tpu.runtime.events import Virq
from pbs_tpu.telemetry.counters import counters_dict
from pbs_tpu.utils.clock import MS

if TYPE_CHECKING:
    from pbs_tpu.runtime.job import Job
    from pbs_tpu.runtime.partition import Partition


class WatchdogStallError(RuntimeError):
    """A partition with runnable work dispatched nothing — raised out of
    the run loop when no ``on_stall`` policy is installed (the NMI
    watchdog's default action is likewise panic, ``xen/arch/x86/nmi.c``),
    which also keeps the stalled loop from spinning on the watchdog's
    own periodic timer forever."""


class Watchdog:
    """In-loop logical-stall detector (xenwatchdogd analog).

    Every ``period_ns`` of partition time, compare the partition's total
    dispatch count against the last sample. Runnable work with *nothing
    dispatched anywhere* for ``threshold`` consecutive periods is a
    stall — a scheduler/policy bug (e.g. everything parked with no
    unpark timer armed). The check is deliberately partition-global:
    with work stealing, any single busy executor proves the scheduler
    is alive, while a per-executor check would flag lanes that simply
    have fewer contexts than executors. Fires ``Virq.WATCHDOG``, then
    either invokes ``on_stall`` or raises :class:`WatchdogStallError`.
    """

    def __init__(
        self,
        partition: "Partition",
        period_ns: int = 100 * MS,
        threshold: int = 2,
        on_stall: Callable[["Partition"], None] | None = None,
    ):
        self.partition = partition
        self.threshold = threshold
        self.on_stall = on_stall
        self.stalls: list[int] = []  # now_ns of each flagged stall
        self._last: int | None = None
        self._quiet = 0
        now = partition.clock.now_ns()
        self.timer = partition.timers.arm(
            now + period_ns, self._tick, period_ns=period_ns, name="watchdog"
        )

    def cancel(self) -> None:
        """Disarm: a watchdog left ticking after its run can panic an
        unrelated later run of the same partition."""
        self.timer.stop()

    def _tick(self, now_ns: int) -> None:
        part = self.partition
        if not part.pending_work():
            self._quiet = 0
            self._last = None
            return
        cur = sum(ex.dispatch_count for ex in part.executors)
        if cur != self._last:
            self._last = cur
            self._quiet = 0
            return
        self._quiet += 1
        if self._quiet == self.threshold:
            self.stalls.append(now_ns)
            part.events.send_virq(Virq.WATCHDOG)
            if self.on_stall is not None:
                self.on_stall(part)
            else:
                raise WatchdogStallError(
                    f"partition {part.name!r}: runnable work but no "
                    f"dispatch for {self.threshold} watchdog periods")


class WallWatchdog:
    """Out-of-band hung-step detector (the NMI watchdog analog).

    Runs in its own thread on wall time, so it fires even when the run
    loop is blocked inside a step that never completes. Progress is the
    partition's quantum epoch; ``on_bark(partition, idle_s)`` is invoked
    once per continuous hang (re-armed by new progress).
    """

    def __init__(
        self,
        partition: "Partition",
        timeout_s: float = 30.0,
        poll_s: float | None = None,
        on_bark: Callable[["Partition", float], None] | None = None,
    ):
        self.partition = partition
        self.timeout_s = timeout_s
        self.poll_s = poll_s if poll_s is not None else max(timeout_s / 4, 0.01)
        self.on_bark = on_bark
        self.barks = 0
        self._armed = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "WallWatchdog":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="pbst-wall-watchdog")
        self._thread.start()
        return self

    def _loop(self) -> None:
        part = self.partition
        last_epoch = part.progress_epoch
        last_change = time.monotonic()
        barked = False
        while not self._stop.wait(self.poll_s):
            if not self._armed:
                last_epoch = part.progress_epoch
                last_change = time.monotonic()
                continue
            epoch = part.progress_epoch
            if epoch != last_epoch:
                last_epoch = epoch
                last_change = time.monotonic()
                barked = False
                continue
            idle = time.monotonic() - last_change
            if idle >= self.timeout_s and not barked:
                barked = True
                self.barks += 1
                if self.on_bark is not None:
                    self.on_bark(part, idle)

    def arm(self) -> None:
        """Watch only while armed (i.e. while a run loop is active);
        an idle partition is not a hang."""
        self._armed = True

    def disarm(self) -> None:
        self._armed = False

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def __enter__(self) -> "WallWatchdog":
        if self._thread is None or not self._thread.is_alive():
            # Re-entry after a previous stop(): restart the monitor
            # thread, otherwise this context would silently watch nothing.
            self._stop = threading.Event()
            self.start()
        self.arm()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.disarm()
        self.stop()  # idempotent; context-manager use must not leak the thread


#: Distinguishes dumps sharing a (virtual) timestamp — two jobs can
#: fault in the same scheduler round before the clock advances.
_dump_seq = itertools.count()


def write_crash_dump(
    crash_dir: str,
    partition: "Partition",
    reason: str,
    job: "Job | None" = None,
    exc: BaseException | None = None,
    max_trace: int = 256,
) -> str:
    """Capture a postmortem (kexec crash-kernel analog). Returns path."""
    os.makedirs(crash_dir, exist_ok=True)
    doc: dict[str, Any] = {
        "reason": reason,
        "time_ns": partition.clock.now_ns(),
        "partition": partition.dump(),
        "jobs": [
            {
                "job": j.name,
                "error": getattr(j, "error", None),
                "contexts": [
                    {
                        "ctx": c.name,
                        "state": c.state.value,
                        "sched_count": c.sched_count,
                        "counters": counters_dict(c.counters),
                    }
                    for c in j.contexts
                ],
            }
            for j in partition.jobs
        ],
        # peek, not drain: a second dump in the same run must still see
        # the tail, and a live xentrace-style consumer must not lose
        # records to a postmortem snapshot.
        "trace_tail": format_records(partition.peek_traces(max_trace)),
    }
    if job is not None:
        doc["failed_job"] = job.name
    if exc is not None:
        doc["exception"] = {
            "type": type(exc).__name__,
            "message": str(exc),
            "traceback": traceback.format_exception(exc),
        }
    fname = (f"crash-{partition.name}-{partition.clock.now_ns()}"
             f"-{next(_dump_seq)}.json")
    path = os.path.join(crash_dir, fname)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, default=str)
    os.replace(tmp, path)
    return path


def install_crash_handler(partition: "Partition", crash_dir: str) -> None:
    """Wire job-failure containment to crash dumps: every contained
    failure leaves a postmortem file."""

    def _handler(job: "Job", exc: BaseException) -> None:
        write_crash_dump(crash_dir, partition,
                         reason=f"job {job.name} failed", job=job, exc=exc)

    partition.on_job_failure = _handler
