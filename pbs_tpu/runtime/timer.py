"""Deadline timers fired from the executor loop.

Analog of Xen's timer substrate (``xen/common/timer.c``) as used by the
credit scheduler, which arms four per-pCPU tickers in
``csched_alloc_pdata`` (``sched_credit.c:646-692``): master_ticker
(accounting), slice_ticker (slice re-application), ticker (per-domain
tick) and metric_ticker (1 ms PMC sampling). Timers here are fired
synchronously from the executor loop against the injected clock, which
keeps every policy test deterministic under ``VirtualClock``.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Callable


class Timer:
    __slots__ = ("when_ns", "period_ns", "fn", "name", "dead")

    def __init__(self, when_ns: int, fn: Callable[[int], None], period_ns: int = 0,
                 name: str = ""):
        self.when_ns = when_ns
        self.period_ns = period_ns  # 0 = one-shot
        self.fn = fn
        self.name = name
        self.dead = False

    def stop(self) -> None:
        self.dead = True


class TimerWheel:
    """Min-heap of timers, popped by the executor before each schedule."""

    def __init__(self):
        self._heap: list[tuple[int, int, Timer]] = []
        self._seq = itertools.count()

    def arm(self, when_ns: int, fn: Callable[[int], None], period_ns: int = 0,
            name: str = "") -> Timer:
        t = Timer(when_ns, fn, period_ns, name)
        heapq.heappush(self._heap, (when_ns, next(self._seq), t))
        return t

    def next_deadline(self) -> int | None:
        while self._heap and self._heap[0][2].dead:
            heapq.heappop(self._heap)
        return self._heap[0][0] if self._heap else None

    def fire_due(self, now_ns: int, limit: int = 10_000) -> int:
        """Fire all timers due at or before ``now_ns``. Returns count."""
        h = self._heap
        # Nothing due: the per-quantum common case (the executor calls
        # this twice per dispatch) exits on one peek. A dead timer at
        # the head parked before its deadline falls through unharvested
        # until it comes due — same observable behavior.
        if not h or h[0][0] > now_ns:
            return 0
        fired = 0
        while self._heap and fired < limit:
            when, _, t = self._heap[0]
            if t.dead:
                heapq.heappop(self._heap)
                continue
            if when > now_ns:
                break
            heapq.heappop(self._heap)
            if t.period_ns > 0:
                # Re-arm before firing so handlers may stop() it.
                t.when_ns = when + t.period_ns
                heapq.heappush(self._heap, (t.when_ns, next(self._seq), t))
            t.fn(now_ns)
            fired += 1
        return fired
