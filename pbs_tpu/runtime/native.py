"""ctypes bindings for the native runtime (native/pbst_runtime.cc).

The reference's hot paths are C compiled into the hypervisor/guest
kernel; ours is a small C++ shared library over flat u64 buffers —
seqlock ledger writes/snapshots and the lockless trace ring — bound via
ctypes (no pybind11 in this image; the ABI is flat by design). The
library is built on demand with the in-tree Makefile and cached;
everything degrades to the pure-Python implementations when a toolchain
is unavailable, so nothing upstack depends on native availability.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

from pbs_tpu.obs.lockprof import ProfiledLock

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
#: PBST_NATIVE_LIB points the loader at an alternate build of the same
#: ABI — the sanitizer tier (libpbst_runtime_{asan,ubsan}.so) runs the
#: whole ctypes surface under ASan/UBSan in a subprocess with nothing
#: but this env var changed. An override path is used as-is: no
#: mtime-vs-source rebuild (the override names a specific artifact,
#: and `make asan` owns its freshness).
_LIB_OVERRIDE = os.environ.get("PBST_NATIVE_LIB") or None
_LIB_PATH = os.path.abspath(
    _LIB_OVERRIDE if _LIB_OVERRIDE
    else os.path.join(_NATIVE_DIR, "libpbst_runtime.so"))

_lock = ProfiledLock("native_load")
_lib: ctypes.CDLL | None = None
_tried = False
#: Why the native runtime is unavailable (build/load failure), cached
#: for diagnosability: `pbst perf` prints it, and the system console
#: ring records it once — "why is everything slow" must not require a
#: debugger (the failure used to be swallowed silently).
_fail_reason: str | None = None

_U64P = ctypes.POINTER(ctypes.c_uint64)
_I64P = ctypes.POINTER(ctypes.c_int64)


def _note_failure(reason: str) -> None:
    global _fail_reason
    _fail_reason = reason
    from pbs_tpu.obs import console

    console.log(f"native: runtime unavailable, pure-Python fallback "
                f"paths in use ({reason})")


def _build() -> bool:
    try:
        proc = subprocess.run(
            ["make", "-C", os.path.abspath(_NATIVE_DIR)],
            capture_output=True, text=True, timeout=120,
        )
    except Exception as e:  # no make, sandboxed exec, timeout, ...
        _note_failure(f"build not attempted: {type(e).__name__}: {e}")
        return False
    if proc.returncode == 0:
        return True
    # The actionable part of a failed make is the stderr tail (the
    # compiler error), not the whole transcript.
    tail = " | ".join(
        (proc.stderr or proc.stdout or "").strip().splitlines()[-4:])
    _note_failure(f"make exited {proc.returncode}: {tail[:400]}")
    return False


def _declare(lib: ctypes.CDLL) -> None:
    lib.pbst_ledger_slot_words.restype = ctypes.c_int
    lib.pbst_ledger_reset.argtypes = [_U64P, ctypes.c_int64]
    lib.pbst_ledger_resume.argtypes = [
        _U64P, ctypes.c_int64, ctypes.c_uint64, _U64P]
    lib.pbst_ledger_suspend.argtypes = [_U64P, ctypes.c_int64, _U64P]
    lib.pbst_ledger_add.argtypes = [
        _U64P, ctypes.c_int64, ctypes.c_int, ctypes.c_uint64]
    lib.pbst_ledger_add_many.argtypes = [_U64P, ctypes.c_int64, _U64P]
    lib.pbst_ledger_snapshot.argtypes = [
        _U64P, ctypes.c_int64, _U64P, ctypes.c_int]
    lib.pbst_ledger_snapshot.restype = ctypes.c_int
    lib.pbst_ledger_tsc_start.argtypes = [_U64P, ctypes.c_int64]
    lib.pbst_ledger_tsc_start.restype = ctypes.c_uint64
    lib.pbst_ledger_snapshot_many.argtypes = [
        _U64P, ctypes.c_int64, _I64P, ctypes.c_int, _U64P, ctypes.c_int]
    lib.pbst_ledger_snapshot_many.restype = ctypes.c_int
    lib.pbst_hist_record.argtypes = [
        _U64P, ctypes.c_int64, ctypes.c_uint64, ctypes.c_int]
    lib.pbst_hist_record_many.argtypes = [
        _U64P, ctypes.c_int64, _I64P, _U64P, ctypes.c_int, ctypes.c_int]
    lib.pbst_hist_record_many.restype = ctypes.c_int
    lib.pbst_trace_init.argtypes = [_U64P, ctypes.c_uint64]
    lib.pbst_trace_emit.argtypes = [_U64P] + [ctypes.c_uint64] * 8
    lib.pbst_trace_emit.restype = ctypes.c_int
    lib.pbst_trace_emit_many.argtypes = [_U64P, _U64P, ctypes.c_int]
    lib.pbst_trace_emit_many.restype = ctypes.c_int
    lib.pbst_trace_consume.argtypes = [_U64P, _U64P, ctypes.c_int]
    lib.pbst_trace_consume.restype = ctypes.c_int
    lib.pbst_trace_lost.argtypes = [_U64P]
    lib.pbst_trace_lost.restype = ctypes.c_uint64
    # Trace-layout getters, same stale-binary story as the sim ABI
    # getters below: obs/trace.py can assert the ring geometry this
    # .so was compiled with matches its own TRACE_*_WORDS mirrors.
    lib.pbst_trace_rec_words.restype = ctypes.c_int
    lib.pbst_trace_header_words.restype = ctypes.c_int
    _U8P = ctypes.POINTER(ctypes.c_uint8)
    lib.pbst_gather_rows.argtypes = [
        _U8P, ctypes.c_uint64, _U64P, ctypes.c_int, ctypes.c_uint64, _U8P]
    lib.pbst_gather_rows.restype = ctypes.c_int
    lib.pbst_db_header_words.restype = ctypes.c_int
    lib.pbst_db_init.argtypes = [_U64P, ctypes.c_uint64]
    lib.pbst_db_valid.argtypes = [_U64P]
    lib.pbst_db_valid.restype = ctypes.c_int
    lib.pbst_db_send.argtypes = [_U64P, ctypes.c_uint64]
    lib.pbst_db_send.restype = ctypes.c_uint64
    lib.pbst_db_pending.argtypes = [_U64P, ctypes.c_uint64]
    lib.pbst_db_pending.restype = ctypes.c_uint64
    lib.pbst_db_take.argtypes = [_U64P, ctypes.c_uint64]
    lib.pbst_db_take.restype = ctypes.c_uint64
    lib.pbst_db_seq.argtypes = [_U64P]
    lib.pbst_db_seq.restype = ctypes.c_uint64
    lib.pbst_db_wait.argtypes = [_U64P, ctypes.c_uint64, ctypes.c_uint64]
    lib.pbst_db_wait.restype = ctypes.c_uint64
    # Sweep-mode sim dispatch core (pbst_sim_run family). The ABI/word
    # getters let the marshaller (sim/native_core.py) assert that the
    # layout it builds is the layout this .so was compiled with — a
    # stale binary degrades to the Python engine instead of reading a
    # shifted state block.
    _F64P = ctypes.POINTER(ctypes.c_double)
    for fn in ("pbst_sim_abi", "pbst_sim_gs_words", "pbst_sim_js_words",
               "pbst_sim_jf_words", "pbst_sim_ev_words"):
        getattr(lib, fn).restype = ctypes.c_int64
    lib.pbst_sim_run.restype = ctypes.c_int64
    lib.pbst_sim_run.argtypes = [
        _I64P, _F64P, _I64P, _F64P, _U64P, _U64P,  # gs gf js jf ctr prev
        _I64P, _F64P,                               # ph_i ph_f
        _I64P, _I64P, _F64P, _I64P,                 # heap runq window hist
        _U64P, _U64P, _U64P, _U64P, _U64P,          # rng/wt/ww/qt/qq tabs
        _I64P,                                      # ev
    ]


def load() -> ctypes.CDLL | None:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH):
            if _LIB_OVERRIDE:
                # make only knows how to produce the default artifact;
                # an override names exactly one file, so a missing one
                # is the caller's bug, not a build trigger.
                _note_failure(
                    f"PBST_NATIVE_LIB={_LIB_PATH} does not exist")
                return None
            if not _build():
                return None
        for attempt in (0, 1):
            try:
                lib = ctypes.CDLL(_LIB_PATH)
                _declare(lib)
                _lib = lib
                break
            except (OSError, AttributeError) as e:
                # AttributeError = stale .so missing a newer symbol;
                # rebuild once, then degrade to the Python paths.
                _lib = None
                if attempt == 1 or _LIB_OVERRIDE:
                    _note_failure(
                        f"load failed: {type(e).__name__}: {e}")
                    break
                if not _build():
                    break
        return _lib


def available() -> bool:
    return load() is not None


_FC_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "pbst_fastcall.so"))
_fc = None
_fc_tried = False


def _fresh(artifact: str, sources: tuple[str, ...]) -> bool:
    """True when ``artifact`` exists and is no older than any source —
    the cheap stand-in for a make invocation."""
    try:
        amt = os.path.getmtime(artifact)
        return all(
            amt >= os.path.getmtime(
                os.path.join(os.path.abspath(_NATIVE_DIR), s))
            for s in sources)
    except OSError:
        return False


def fastcall():
    """The METH_FASTCALL binding module (native/pbst_fastcall.cc), or
    None. A tier ABOVE the ctypes bindings, not a replacement: it
    wraps the same C entry points with ~100 ns call overhead instead
    of ctypes' ~700 ns, and needs Python.h to build — hosts without
    the headers (or any import problem) stay on ctypes, with the
    reason cached for :func:`last_failure` consumers (the `pbst perf`
    report stamp) and logged to the console ring."""
    global _fc, _fc_tried
    if load() is None:
        return None  # no base library — reason already cached
        # (outside _lock: load() takes the same non-reentrant lock)
    with _lock:
        if _fc is not None or _fc_tried:
            return _fc
    # Build OUTSIDE the lock: a 120 s make held under it would convoy
    # every ring/ledger constructor. make is idempotent, so a racing
    # duplicate build is wasteful but harmless; the import below is
    # serialized again. The mtime pre-check keeps the common case
    # (fresh committed .so) free of a per-process subprocess spawn
    # while still rebuilding when a source outlives the artifact (the
    # conftest _build_native contract).
    if not _fresh(_FC_PATH, ("pbst_fastcall.cc", "pbst_runtime.cc")):
        try:
            subprocess.run(
                ["make", "-C", os.path.abspath(_NATIVE_DIR),
                 "fastcall"],
                capture_output=True, text=True, timeout=120)
        except Exception:
            pass  # missing make: the exists() check below decides
    with _lock:
        if _fc is not None or _fc_tried:
            return _fc
        _fc_tried = True
        if not os.path.exists(_FC_PATH):
            _note_failure("fastcall tier unavailable (Python.h or "
                          "toolchain missing); ctypes tier in use")
            return None
        try:
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "pbst_fastcall", _FC_PATH)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            for sym in ("trace_emit", "trace_emit_many",
                        "trace_consume", "hist_record",
                        "hist_record_many", "ledger_snapshot_many",
                        "sim_run"):
                if not hasattr(mod, sym):
                    raise AttributeError(f"stale fastcall .so: {sym}")
            _fc = mod
        except Exception as e:  # stale ABI, wrong interpreter, ...
            _fc = None
            _note_failure(f"fastcall import failed "
                          f"({type(e).__name__}: {e}); ctypes tier "
                          "in use")
        return _fc


def unavailable_reason() -> str | None:
    """Why :func:`load` returned None (build/load failure), or None
    when the library is loadable or no attempt failed yet. Cached so
    ``pbst perf`` and test skip messages can say WHY the fast paths
    are off instead of reporting a silent slowdown."""
    load()
    return None if _lib is not None else (
        _fail_reason or "never attempted")


def last_failure() -> str | None:
    """The most recent cached failure from ANY tier — including a
    fastcall build/import failure on a host whose base library loads
    fine (where :func:`unavailable_reason` correctly reports None).
    ``pbst perf``'s report stamp carries this so "why am I on the
    ctypes tier" has an answer."""
    return _fail_reason


def as_u64p(arr: np.ndarray):
    """uint64 pointer into a (C-contiguous) numpy array's buffer."""
    assert arr.dtype == np.uint64 and arr.flags["C_CONTIGUOUS"]
    return arr.ctypes.data_as(_U64P)


def as_i64p(arr: np.ndarray):
    """int64 pointer into a (C-contiguous) numpy array's buffer (slot
    index vectors for the *_many entry points)."""
    assert arr.dtype == np.int64 and arr.flags["C_CONTIGUOUS"]
    return arr.ctypes.data_as(_I64P)


def as_f64p(arr: np.ndarray):
    """float64 pointer into a (C-contiguous) numpy array's buffer (the
    sim core's float state blocks and pre-drawn jitter streams)."""
    assert arr.dtype == np.float64 and arr.flags["C_CONTIGUOUS"]
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
