"""ctypes bindings for the native runtime (native/pbst_runtime.cc).

The reference's hot paths are C compiled into the hypervisor/guest
kernel; ours is a small C++ shared library over flat u64 buffers —
seqlock ledger writes/snapshots and the lockless trace ring — bound via
ctypes (no pybind11 in this image; the ABI is flat by design). The
library is built on demand with the in-tree Makefile and cached;
everything degrades to the pure-Python implementations when a toolchain
is unavailable, so nothing upstack depends on native availability.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

from pbs_tpu.obs.lockprof import ProfiledLock

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libpbst_runtime.so"))

_lock = ProfiledLock("native_load")
_lib: ctypes.CDLL | None = None
_tried = False

_U64P = ctypes.POINTER(ctypes.c_uint64)


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", os.path.abspath(_NATIVE_DIR)],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except Exception:
        return False


def _declare(lib: ctypes.CDLL) -> None:
    lib.pbst_ledger_slot_words.restype = ctypes.c_int
    lib.pbst_ledger_reset.argtypes = [_U64P, ctypes.c_int64]
    lib.pbst_ledger_resume.argtypes = [
        _U64P, ctypes.c_int64, ctypes.c_uint64, _U64P]
    lib.pbst_ledger_suspend.argtypes = [_U64P, ctypes.c_int64, _U64P]
    lib.pbst_ledger_add.argtypes = [
        _U64P, ctypes.c_int64, ctypes.c_int, ctypes.c_uint64]
    lib.pbst_ledger_add_many.argtypes = [_U64P, ctypes.c_int64, _U64P]
    lib.pbst_ledger_snapshot.argtypes = [
        _U64P, ctypes.c_int64, _U64P, ctypes.c_int]
    lib.pbst_ledger_snapshot.restype = ctypes.c_int
    lib.pbst_ledger_tsc_start.argtypes = [_U64P, ctypes.c_int64]
    lib.pbst_ledger_tsc_start.restype = ctypes.c_uint64
    lib.pbst_trace_init.argtypes = [_U64P, ctypes.c_uint64]
    lib.pbst_trace_emit.argtypes = [_U64P] + [ctypes.c_uint64] * 8
    lib.pbst_trace_emit.restype = ctypes.c_int
    lib.pbst_trace_consume.argtypes = [_U64P, _U64P, ctypes.c_int]
    lib.pbst_trace_consume.restype = ctypes.c_int
    lib.pbst_trace_lost.argtypes = [_U64P]
    lib.pbst_trace_lost.restype = ctypes.c_uint64
    _U8P = ctypes.POINTER(ctypes.c_uint8)
    lib.pbst_gather_rows.argtypes = [
        _U8P, ctypes.c_uint64, _U64P, ctypes.c_int, ctypes.c_uint64, _U8P]
    lib.pbst_gather_rows.restype = ctypes.c_int
    lib.pbst_db_header_words.restype = ctypes.c_int
    lib.pbst_db_init.argtypes = [_U64P, ctypes.c_uint64]
    lib.pbst_db_valid.argtypes = [_U64P]
    lib.pbst_db_valid.restype = ctypes.c_int
    lib.pbst_db_send.argtypes = [_U64P, ctypes.c_uint64]
    lib.pbst_db_send.restype = ctypes.c_uint64
    lib.pbst_db_pending.argtypes = [_U64P, ctypes.c_uint64]
    lib.pbst_db_pending.restype = ctypes.c_uint64
    lib.pbst_db_take.argtypes = [_U64P, ctypes.c_uint64]
    lib.pbst_db_take.restype = ctypes.c_uint64
    lib.pbst_db_seq.argtypes = [_U64P]
    lib.pbst_db_seq.restype = ctypes.c_uint64
    lib.pbst_db_wait.argtypes = [_U64P, ctypes.c_uint64, ctypes.c_uint64]
    lib.pbst_db_wait.restype = ctypes.c_uint64


def load() -> ctypes.CDLL | None:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        for attempt in (0, 1):
            try:
                lib = ctypes.CDLL(_LIB_PATH)
                _declare(lib)
                _lib = lib
                break
            except (OSError, AttributeError):
                # AttributeError = stale .so missing a newer symbol;
                # rebuild once, then degrade to the Python paths.
                _lib = None
                if attempt == 0 and not _build():
                    break
        return _lib


def available() -> bool:
    return load() is not None


def as_u64p(arr: np.ndarray):
    """uint64 pointer into a (C-contiguous) numpy array's buffer."""
    assert arr.dtype == np.uint64 and arr.flags["C_CONTIGUOUS"]
    return arr.ctypes.data_as(_U64P)
