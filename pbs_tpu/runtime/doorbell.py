"""Cross-process doorbells: event channels over shared memory.

Reference: Xen event channels notify across domains through pending
bits in the shared_info page plus an upcall
(``xen/common/event_channel.c``; the perfctr overflow virq rides this,
``pmustate.c:66-80``). Inside one process the :class:`EventBus` plays
that role; ACROSS processes round 1 only had the control-plane RPC —
a monitor had to poll over TCP to learn "telemetry event fired".

This module is the missing shared-page notify path: per-channel
pending counts and a global notify sequence over a file-backed mmap
(the same byte-compatible native/Python split as the ledger). A
monitor process maps the file, then ``wait()``s on the sequence —
microsecond wakeups, zero RPCs. ``bridge_events`` forwards a
partition's Virq traffic into doorbell channels, so external observers
get the same interrupts in-process subscribers do.

Writer-concurrency contract (same as the ledger): the native path uses
real atomics and is safe for many senders in any process; the pure
Python fallback is in-process safe (GIL) — cross-process SENDERS
require the native library. Waiters are always safe (reads tolerate
races by re-checking).
"""

from __future__ import annotations

import time

import numpy as np

from pbs_tpu import knobs
from pbs_tpu.utils.clock import SEC, US

HEADER_WORDS = 4
_MAGIC = 0x70627374_6462  # "pbstdb"

# Pure-Python wait() poll period. The native path blocks in the
# library; the fallback polls the notify sequence at this cadence — a
# registry-declared knob (runtime.doorbell.poll_ns) so the unit
# checker and `pbst knobs` both see it instead of a bare sleep literal.
DOORBELL_POLL_NS = knobs.default("runtime.doorbell.poll_ns")


class Doorbell:
    """A channel block over caller-provided or file-backed memory."""

    @classmethod
    def file_backed(cls, path: str, n_channels: int | None = None,
                    attach: bool = False) -> "Doorbell":
        import mmap
        import os

        if attach:
            fd = os.open(path, os.O_RDWR)
            try:
                size = os.fstat(fd).st_size
                mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
            db = cls(n_channels=(size // 8) - HEADER_WORDS, buf=mm,
                     _attach=True)
            db._mmap = mm
            if int(db._arr[0]) != _MAGIC:
                raise ValueError(f"{path!r} is not an initialized "
                                 "doorbell block")
            claimed = int(db._arr[1])
            if claimed > (size // 8) - HEADER_WORDS:
                # A truncated file with an intact header would let the
                # native sender write past the end of the mapping.
                raise ValueError(
                    f"{path!r} claims {claimed} channels but holds "
                    f"only {(size // 8) - HEADER_WORDS}")
            db.n_channels = claimed
            return db
        if n_channels is None:
            raise ValueError("n_channels required when creating")
        nbytes = (HEADER_WORDS + n_channels) * 8
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            if os.fstat(fd).st_size < nbytes:
                os.ftruncate(fd, nbytes)
            mm = mmap.mmap(fd, nbytes)
        finally:
            os.close(fd)
        db = cls(n_channels, buf=mm)
        db._mmap = mm
        return db

    def __init__(self, n_channels: int, buf=None, native: bool | None = None,
                 _attach: bool = False):
        self.n_channels = int(n_channels)
        nbytes = (HEADER_WORDS + self.n_channels) * 8
        if buf is None:
            buf = bytearray(nbytes)
        mv = memoryview(buf)
        if mv.nbytes < nbytes:
            raise ValueError(f"buffer too small: {mv.nbytes} < {nbytes}")
        self._arr = np.frombuffer(
            mv, dtype="<u8", count=HEADER_WORDS + self.n_channels)
        self._nat = None
        self._ptr = None
        if native is not False:
            from pbs_tpu.runtime import native as native_mod

            lib = native_mod.load()
            if lib is not None:
                self._nat = lib
                self._ptr = native_mod.as_u64p(self._arr)
            elif native is True:
                raise RuntimeError("native runtime requested but unavailable")
        if _attach:
            return  # joiner: creator owns the header
        if self._nat is not None:
            self._nat.pbst_db_init(self._ptr, self.n_channels)
        else:
            self._arr[1] = self.n_channels
            self._arr[2] = 0
            self._arr[3] = 0
            self._arr[HEADER_WORDS:] = 0
            self._arr[0] = _MAGIC

    # -- sender side ------------------------------------------------------

    def send(self, chan: int) -> int:
        """Ring ``chan``; returns its new pending count."""
        self._check_chan(chan)
        if self._nat is not None:
            return int(self._nat.pbst_db_send(self._ptr, chan))
        self._arr[HEADER_WORDS + chan] += 1
        self._arr[2] += 1
        return int(self._arr[HEADER_WORDS + chan])

    # -- consumer side ----------------------------------------------------

    def _check_chan(self, chan: int) -> None:
        # Uniform across paths: a negative index in the Python
        # fallback would read/zero HEADER words (including the magic).
        if not 0 <= chan < self.n_channels:
            raise IndexError(f"channel {chan} out of range")

    def pending(self, chan: int) -> int:
        self._check_chan(chan)
        if self._nat is not None:
            return int(self._nat.pbst_db_pending(self._ptr, chan))
        return int(self._arr[HEADER_WORDS + chan])

    def take(self, chan: int) -> int:
        """Consume (and zero) a channel's pending count."""
        self._check_chan(chan)
        if self._nat is not None:
            return int(self._nat.pbst_db_take(self._ptr, chan))
        n = int(self._arr[HEADER_WORDS + chan])
        self._arr[HEADER_WORDS + chan] = 0
        return n

    def seq(self) -> int:
        if self._nat is not None:
            return int(self._nat.pbst_db_seq(self._ptr))
        return int(self._arr[2])

    def wait(self, last_seq: int, timeout_s: float = 1.0) -> int:
        """Block until the notify sequence moves past ``last_seq`` (any
        channel rang) or timeout. Returns the current sequence."""
        if self._nat is not None:
            return int(self._nat.pbst_db_wait(
                self._ptr, last_seq, int(timeout_s * 1e6)))
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            s = int(self._arr[2])
            if s != last_seq:
                return s
            time.sleep(DOORBELL_POLL_NS / SEC)
        return int(self._arr[2])


def bridge_events(bus, db: Doorbell, virqs=None):
    """Forward a bus's signal traffic into doorbell channels (channel
    index = port number) via a send-time tap — no port is occupied, so
    in-process subscribers may bind before OR after bridging, and the
    doorbell rings even for ports nobody bound locally (an external
    monitor may be the only consumer). ``virqs`` restricts forwarding
    to those ports; default: every port that fits the block. Returns
    the tap (pass to ``bus.remove_tap`` to unbridge)."""
    allowed = (None if virqs is None
               else {int(v) for v in virqs})

    def _tap(port: int, _db=db) -> None:
        if port < _db.n_channels and (allowed is None or port in allowed):
            _db.send(port)

    bus.add_tap(_tap)
    return _tap
