"""HBM paging: evict parked tenants' device state to host memory.

Reference: xenpaging (``tools/xenpaging``) pages guest memory out to a
dom0 file under pressure and faults it back transparently on access —
the mechanism that lets more guests exist than RAM strictly allows.
The TPU analog is stronger, not weaker: a job's state is only touched
at step boundaries and a BLOCKED job cannot be dispatched, so paging a
sleeping tenant is exact by construction — no dirty tracking, no fault
path, just whole-state eviction and restore. A parked tenant's
params/optimizer slabs are pure HBM cost; paging them means the chip
multiplexes more tenants than fit in HBM simultaneously.

Two entry points:

- explicit: ``page_out_job``/``page_in_job`` (``pbst``-driveable policy
  decisions, like ``xenpaging``'s target file size);
- automatic: ``register_paging_reclaim`` hooks a job into the
  MemoryManager's balloon path, so ``claim_or_balloon`` for a NEW
  tenant transparently pages out sleeping neighbors, biggest first —
  admission pressure is what xenpaging exists for.

Shardings are captured per leaf at page-out and reapplied at page-in,
so multi-device states restore onto the same mesh layout.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any

import numpy as np

from pbs_tpu.obs.perfc import perfc

if TYPE_CHECKING:
    from pbs_tpu.runtime.job import Job
    from pbs_tpu.runtime.partition import Partition


class PagingError(RuntimeError):
    pass


def _is_device_array(leaf: Any) -> bool:
    import jax

    return isinstance(leaf, jax.Array)


def _evict_state(state: Any) -> tuple[Any, list, int]:
    """(host_state_placeholder, paged_leaves, bytes_freed): device
    leaves become index markers; the paged list holds (np array,
    sharding) pairs for restore. Leaves belonging to a PUBLISHED
    shared weight set (runtime.sharing) are left in place: evicting a
    refcounted set through one tenant and restoring it as a private
    copy would silently break the dedup (and the tenant's account
    never paid for those bytes)."""
    import jax

    from pbs_tpu.runtime.sharing import is_shared_leaf

    leaves, treedef = jax.tree_util.tree_flatten(state)
    paged: list[tuple[np.ndarray, Any]] = []
    out_leaves = []
    freed = 0
    for leaf in leaves:
        if _is_device_array(leaf) and not is_shared_leaf(leaf):
            sharding = leaf.sharding
            host = np.asarray(jax.device_get(leaf))
            freed += int(leaf.nbytes)
            out_leaves.append(_PagedLeaf(len(paged)))
            paged.append((host, sharding))
        else:
            out_leaves.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out_leaves), paged, freed


class _PagedLeaf:
    """Marker standing where a device array lived (never dispatched:
    the owning job is BLOCKED while paged)."""

    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index

    def __repr__(self) -> str:  # surfaces clearly if ever leaked
        return f"<paged-out leaf #{self.index}>"


def _restore_state(state: Any, paged: list) -> Any:
    import jax

    live = set(jax.devices())
    leaves, treedef = jax.tree_util.tree_flatten(
        state, is_leaf=lambda x: isinstance(x, _PagedLeaf))
    out = []
    for leaf in leaves:
        if isinstance(leaf, _PagedLeaf):
            host, sharding = paged[leaf.index]
            devs = getattr(sharding, "device_set", None)
            if devs is not None and not set(devs) <= live:
                # ONLY the devices-gone case falls back to default
                # placement (post-restart restore on a different
                # topology); any other device_put failure — real HBM
                # exhaustion especially — must propagate so the job
                # stays asleep+paged instead of waking mislaid.
                out.append(jax.device_put(host))
            else:
                out.append(jax.device_put(host, sharding))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def _sleeping(job: "Job") -> bool:
    from pbs_tpu.runtime.job import ContextState

    return {c.state for c in job.contexts} <= {
        ContextState.BLOCKED, ContextState.DONE, ContextState.FAILED}


def _do_page_out(job: "Job", pressure: bool,
                 acct_used: int | None = None) -> int:
    """Shared eviction body (explicit + balloon paths); the caller
    decides policy (raise vs skip) and accounting. ``acct_used`` (the
    job's CURRENT ledger balance, when accounting is on) bounds the
    re-claim at page-in: the account may hold less than the device
    bytes (declared mem_bytes, post-admission growth) and the round
    trip must not inflate it."""
    new_state, paged, freed = _evict_state(job.state)
    if freed == 0:
        return 0
    job.state = new_state
    job.paged = paged
    job.paged_bytes = freed
    job.paged_acct_bytes = (freed if acct_used is None
                            else min(freed, acct_used))
    perfc.incr("paging_out_bytes", freed)
    job.console.write(
        f"paged out{' under pressure' if pressure else ''}: "
        f"{freed} bytes to host")
    return freed


def page_out_job(partition: "Partition", job: "Job") -> int:
    """Evict ``job``'s device state to host memory; returns bytes
    freed. The job must be asleep (BLOCKED) — it is un-runnable until
    :func:`page_in_job` (which ``Partition.wake_job`` invokes
    automatically). Idempotent: paging a paged job frees 0."""
    if getattr(job, "paged", None) is not None:
        return 0
    if not _sleeping(job):
        raise PagingError(
            f"job {job.name!r} is runnable; sleep it before paging "
            "(a dispatched paged state would fault)")
    acct_used = None
    if partition.memory is not None:
        acct_used = partition.memory.account(job.name).used_bytes
    freed = _do_page_out(job, pressure=False, acct_used=acct_used)
    if freed and partition.memory is not None:
        partition.memory.release(job.name, freed)
    return freed


def page_in_job(partition: "Partition", job: "Job") -> int:
    """Restore a paged job's device state (claiming its HBM back,
    ballooning/paging others if needed). Raises OutOfDeviceMemory when
    the chip genuinely cannot host it — the job stays paged+asleep."""
    paged = getattr(job, "paged", None)
    if paged is None:
        return 0
    nbytes = job.paged_bytes
    # Re-claim exactly what the ACCOUNT gave up at page-out (which may
    # be less than the device bytes) — claiming the device size would
    # inflate the ledger on every round trip (review finding).
    acct_bytes = getattr(job, "paged_acct_bytes", nbytes)
    if partition.memory is not None:
        # may balloon (and thereby page out) other sleeping tenants
        partition.memory.claim_or_balloon(job.name, acct_bytes)
    try:
        job.state = _restore_state(job.state, paged)
    except BaseException:
        if partition.memory is not None:
            partition.memory.release(job.name, acct_bytes)
        raise
    job.paged = None
    job.paged_bytes = 0
    job.paged_acct_bytes = 0
    perfc.incr("paging_in_bytes", nbytes)
    job.console.write(f"paged in: {nbytes} bytes to device")
    return nbytes


def register_paging_reclaim(partition: "Partition", job: "Job") -> None:
    """Hook ``job`` into the balloon path: under admission pressure,
    ``claim_or_balloon`` pages it out IF it is asleep at that moment
    (a runnable job reports 0 and the balloon moves on). The released
    accounting is handled by the balloon itself."""
    if partition.memory is None:
        raise PagingError("partition has no MemoryManager")

    def _reclaim(need: int) -> int:
        if getattr(job, "paged", None) is not None:
            return 0
        if not _sleeping(job):
            return 0  # running tenants are never paged out from under;
            # "nothing right now" is transient — balloon() skips this
            # call only, never unregisters the hook
        acct_used = partition.memory.account(job.name).used_bytes
        return _do_page_out(job, pressure=True,
                            acct_used=acct_used)  # balloon() releases

    partition.memory.register_reclaim(job.name, _reclaim)
