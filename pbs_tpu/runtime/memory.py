"""Device-memory accounting: per-job HBM budgets (page_alloc analog).

Reference: Xen's memory management (``xen/common/page_alloc.c``,
``arch/x86/mm.c``) accounts every page to a domain: ``max_pages`` caps
a domain, ``tot_pages`` tracks usage, domain builds *claim* pages up
front so admission fails fast instead of OOMing mid-boot, and the
balloon driver (``drivers/xen/balloon.c``) reclaims guest memory
cooperatively under pressure.

TPU re-expression: HBM is the contended resource. A
:class:`MemoryManager` owns one device's capacity; jobs open accounts
with optional caps, *claim* their working-set bytes at admission
(fail-fast, the claim mechanism), and can register balloon callbacks
the manager invokes under pressure (e.g. drop optimizer-state
rematerialization caches, shrink activation checkpoints). Real usage
on hardware comes from ``jax.Device.memory_stats()``; estimates for
jitted jobs come from the pytree byte size of their state.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from pbs_tpu.obs.lockprof import ProfiledLock
from pbs_tpu.obs.perfc import perfc


class OutOfDeviceMemory(MemoryError):
    """Admission-time claim failure (the -ENOMEM a domain build gets
    when its claim exceeds free heap). ``reason`` is ``"cap"`` (per-
    account limit — ballooning others cannot help) or ``"capacity"``
    (device pressure — reclaim may free room)."""

    def __init__(self, msg: str, reason: str = "capacity"):
        super().__init__(msg)
        self.reason = reason


def nbytes_of(tree: Any) -> int:
    """Pytree device-byte estimate (arrays only; None/scalars free)."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:
        leaves = [tree] if tree is not None else []
    total = 0
    for leaf in leaves:
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


def device_memory_stats(device=None) -> dict:
    """Live HBM numbers from the runtime (bytes_in_use / bytes_limit),
    empty when the backend doesn't expose them (CPU sim)."""
    try:
        import jax

        dev = device if device is not None else jax.devices()[0]
        return dict(dev.memory_stats() or {})
    except Exception:
        return {}


@dataclasses.dataclass
class MemoryAccount:
    """Per-domain accounting record (``struct domain``'s max_pages /
    tot_pages pair, in bytes)."""

    owner: str
    max_bytes: int = 0  # 0 = uncapped (dom0-style)
    used_bytes: int = 0
    claims: int = 0  # successful claim count (perfc-style)


class MemoryManager:
    """One device's HBM ledger: capacity, accounts, claims, ballooning."""

    def __init__(self, capacity_bytes: int, reserve_bytes: int = 0):
        # reserve = the runtime's own arena (Xen keeps a hypervisor
        # heap reserve the same way).
        self.capacity = int(capacity_bytes)
        self.reserve = int(reserve_bytes)
        self._accounts: dict[str, MemoryAccount] = {}
        self._reclaim: dict[str, Callable[[int], int]] = {}
        self._lock = ProfiledLock("memory_manager")

    @classmethod
    def for_device(cls, device=None,
                   default_capacity: int = 16 << 30) -> "MemoryManager":
        stats = device_memory_stats(device)
        cap = int(stats.get("bytes_limit", default_capacity))
        used = int(stats.get("bytes_in_use", 0))
        return cls(cap, reserve_bytes=used)

    # -- accounts --------------------------------------------------------

    def open_account(self, owner: str, max_bytes: int = 0) -> MemoryAccount:
        with self._lock:
            if owner in self._accounts:
                raise ValueError(f"account {owner!r} exists")
            acct = MemoryAccount(owner, max_bytes=int(max_bytes))
            self._accounts[owner] = acct
            return acct

    def close_account(self, owner: str) -> int:
        """Returns the bytes freed (domain destruction releases all)."""
        with self._lock:
            acct = self._accounts.pop(owner, None)
            self._reclaim.pop(owner, None)
            return acct.used_bytes if acct else 0

    def account(self, owner: str) -> MemoryAccount:
        with self._lock:
            return self._accounts[owner]

    # -- claims (fail-fast admission) ------------------------------------

    def free_bytes(self) -> int:
        with self._lock:
            return self._free_locked()

    def _free_locked(self) -> int:
        used = sum(a.used_bytes for a in self._accounts.values())
        return self.capacity - self.reserve - used

    def claim(self, owner: str, nbytes: int) -> None:
        """XENMEM_claim_pages: reserve before allocating. Raises
        :class:`OutOfDeviceMemory` on cap or capacity violation —
        admission fails fast rather than OOMing mid-step."""
        nbytes = int(nbytes)
        if nbytes < 0:
            raise ValueError("negative claim")
        with self._lock:
            acct = self._accounts[owner]
            if acct.max_bytes and acct.used_bytes + nbytes > acct.max_bytes:
                perfc.incr("mem_claim_cap_denied")
                raise OutOfDeviceMemory(
                    f"{owner}: claim {nbytes} exceeds cap "
                    f"{acct.max_bytes} (used {acct.used_bytes})",
                    reason="cap")
            if nbytes > self._free_locked():
                perfc.incr("mem_claim_capacity_denied")
                raise OutOfDeviceMemory(
                    f"{owner}: claim {nbytes} exceeds free "
                    f"{self._free_locked()} of {self.capacity}")
            acct.used_bytes += nbytes
            acct.claims += 1
            perfc.incr("mem_claims")

    def release(self, owner: str, nbytes: int) -> int:
        """Returns the bytes actually deducted (the account clamps at
        zero — callers re-claiming later must re-claim THIS amount,
        not their request, or the ledger inflates)."""
        with self._lock:
            acct = self._accounts[owner]
            deducted = min(acct.used_bytes, max(0, int(nbytes)))
            acct.used_bytes -= deducted
            return deducted

    # -- ballooning (cooperative reclaim) --------------------------------

    def register_reclaim(self, owner: str,
                         fn: Callable[[int], int]) -> None:
        """``fn(nbytes) -> freed`` — the balloon driver's target-set
        callback; the job frees caches and reports how much."""
        self._reclaim[owner] = fn

    def balloon(self, want_bytes: int) -> int:
        """Reclaim until ``want_bytes`` are free (or callbacks are
        exhausted). Returns bytes actually freed. Biggest consumers
        first, like the balloon targeting policy.

        A callback that frees nothing — or whose reported freeing does
        not actually grow free capacity — is skipped for the REST OF
        THIS CALL only, never unregistered ("nothing to give right
        now" is transient). A callback that DID free stays eligible,
        so chunked reclaimers (a cache evicting 100 MB per ask) are
        re-asked until the target is met or they dry up."""
        freed_total = 0
        skip: set[str] = set()
        while self.free_bytes() < want_bytes:
            with self._lock:
                candidates = sorted(
                    (a for a in self._accounts.values()
                     if a.owner in self._reclaim and a.used_bytes > 0
                     and a.owner not in skip),
                    key=lambda a: -a.used_bytes)
            if not candidates:
                break
            acct = candidates[0]
            need = want_bytes - self.free_bytes()
            fn = self._reclaim.get(acct.owner)
            if fn is None:  # concurrently unregistered
                skip.add(acct.owner)
                continue
            free_before = self.free_bytes()
            freed = int(fn(need))
            if freed > 0:
                deducted = self.release(acct.owner, freed)
                freed_total += deducted
                perfc.incr("mem_balloon_freed_bytes", deducted)
            if freed <= 0 or self.free_bytes() <= free_before:
                # dry, uncooperative, or claims bytes the ledger never
                # charged it for — either way, asking again this call
                # cannot make progress
                skip.add(acct.owner)
        return freed_total

    def claim_or_balloon(self, owner: str, nbytes: int) -> None:
        """Claim; on capacity pressure, balloon others then retry once.
        A per-account cap denial re-raises immediately — evicting other
        tenants' caches cannot make an over-cap claim succeed."""
        try:
            self.claim(owner, nbytes)
        except OutOfDeviceMemory as e:
            if e.reason == "cap":
                raise
            self.balloon(nbytes)
            self.claim(owner, nbytes)

    # -- observability ---------------------------------------------------

    def dump(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "reserve": self.reserve,
                "free": self._free_locked(),
                "accounts": {
                    a.owner: {"used": a.used_bytes, "max": a.max_bytes,
                              "claims": a.claims}
                    for a in self._accounts.values()
                },
            }
