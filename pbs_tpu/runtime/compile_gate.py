"""Compilation-aware admission control (SURVEY.md §7 "hard parts").

Analog chain: the reference's admission resource is memory —
``XENMEM_claim_pages`` fail-fast claims at domain create. The TPU-new
scarce resource is the XLA compile cache: every distinct program a
tenant brings costs a cache entry plus seconds of compile time, and
multiplexing many programs per core thrashes the cache (each eviction
converts a dispatch into a multi-second recompile stall). This gate
makes that pressure an admitted, accounted quantity, exactly like the
HBM claims in ``runtime.memory``:

- a partition gets a ``CompileBudget`` (max distinct programs = cache
  capacity; optional total compile-time budget);
- each job declares how many distinct programs it brings
  (``Job.n_programs``, default 1) and optionally an expected per-
  program compile cost; undeclared costs are projected from the
  *observed* fleet average (``CompileMeter.mean_compile_ns``);
- admission fail-fast-rejects when the projection overflows the
  budget, before any scheduler/ledger/memory state is touched.

Measured attribution (which job actually spent what) flows separately
through ``telemetry.compile.CompileMeter`` into the COMPILES /
COMPILE_TIME_NS ledger slots.
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from pbs_tpu.runtime.job import Job


class CompileBudgetExceeded(RuntimeError):
    """Admission denied: projected compile-cache pressure over budget."""


@dataclasses.dataclass
class CompileBudget:
    """Partition-level compile-capacity declaration.

    ``max_programs`` models compile-cache capacity (entries);
    ``budget_ns`` bounds cumulative compile time (spent + projected) —
    None disables that axis.
    """

    max_programs: int | None = None
    budget_ns: int | None = None


class CompileAdmission:
    """Fail-fast compile-cache admission for one partition."""

    def __init__(self, budget: CompileBudget, meter=None):
        self.budget = budget
        if meter is None:
            from pbs_tpu.telemetry.compile import CompileMeter

            meter = CompileMeter.install()
        self.meter = meter
        self.programs: dict[str, int] = {}  # job name -> claimed programs
        self.spent_ns: dict[str, int] = {}  # job name -> measured ns
        # job name -> projected ns reserved at admit time; the claim is
        # HELD until measured spend replaces it (a claim that isn't
        # held would admit unbounded projected load back-to-back).
        self.reserved_ns: dict[str, int] = {}
        self.rejections = 0

    # -- admission --------------------------------------------------------

    def projected_cost_ns(self, job: "Job") -> int:
        est = getattr(job, "est_compile_ns", None)
        per_program = (int(est) if est is not None
                       else self.meter.mean_compile_ns)
        return per_program * max(1, getattr(job, "n_programs", 1))

    def admit(self, job: "Job") -> None:
        """Raise :class:`CompileBudgetExceeded` or claim the job's
        program slots. Call before any other admission state lands (the
        claim is trivially reversible via :meth:`release`)."""
        n = max(1, int(getattr(job, "n_programs", 1)))
        b = self.budget
        if b.max_programs is not None:
            held = sum(self.programs.values())
            if held + n > b.max_programs:
                self.rejections += 1
                raise CompileBudgetExceeded(
                    f"job {job.name!r} brings {n} program(s); cache holds "
                    f"{held}/{b.max_programs} — admitting would thrash "
                    "the compile cache")
        if b.budget_ns is not None:
            projected = self.projected_cost_ns(job)
            committed = self.committed_ns()
            if committed + projected > b.budget_ns:
                self.rejections += 1
                raise CompileBudgetExceeded(
                    f"job {job.name!r} projects {projected} ns compile "
                    f"time; partition holds {committed} of "
                    f"{b.budget_ns} ns budget (measured + reserved)")
            self.reserved_ns[job.name] = projected
        self.programs[job.name] = n

    def committed_ns(self) -> int:
        """Held budget: per job, the larger of measured spend and the
        still-outstanding admission reservation."""
        names = set(self.spent_ns) | set(self.reserved_ns)
        return sum(max(self.spent_ns.get(j, 0), self.reserved_ns.get(j, 0))
                   for j in names)

    def release(self, job_name: str) -> None:
        self.programs.pop(job_name, None)
        self.spent_ns.pop(job_name, None)
        self.reserved_ns.pop(job_name, None)

    # -- measured feedback ------------------------------------------------

    def charge(self, job_name: str, compile_ns: int) -> None:
        """Measured compile time attributed to a job (fed by the
        executor after each quantum) — tightens future projections."""
        if job_name in self.programs:
            self.spent_ns[job_name] = (
                self.spent_ns.get(job_name, 0) + int(compile_ns))

    def dump(self) -> dict:
        return {
            "max_programs": self.budget.max_programs,
            "budget_ns": self.budget.budget_ns,
            "programs_held": dict(self.programs),
            "spent_ns": dict(self.spent_ns),
            "reserved_ns": dict(self.reserved_ns),
            "committed_ns": self.committed_ns(),
            "mean_compile_ns": self.meter.mean_compile_ns,
            "rejections": self.rejections,
        }
