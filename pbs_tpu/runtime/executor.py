"""Executors: the pCPU + ``schedule()`` softirq loop analog.

Each executor multiplexes execution contexts over one device lane of its
partition, mirroring Xen's per-pCPU scheduling loop
(``xen/common/schedule.c:1082-1185``): fire due timers, ask the policy
for (next, quantum), context-switch with telemetry save/restore
(``__context_switch`` at ``arch/x86/domain.c:1583-1650``:
``pmu_save_regs(prev)``; ``pmu_restore_regs(next)``; ``sched_count++``),
run, account.

TPU twist: there is no device preemption, so a quantum is realized as N
compiled steps, N derived from the policy's nanosecond slice and the
context's measured per-step time (SURVEY.md §7: "quantum = N compiled
steps"; the 100 µs slice's real analog).
"""

from __future__ import annotations

from typing import TYPE_CHECKING

import numpy as np

from pbs_tpu import knobs
from pbs_tpu.obs.trace import Ev
from pbs_tpu.runtime.job import ContextState, ExecutionContext
from pbs_tpu.telemetry.counters import NUM_COUNTERS, Counter

if TYPE_CHECKING:
    from pbs_tpu.runtime.partition import Partition

#: Upper bound on steps per quantum, so a mispredicted avg_step_ns can't
#: starve the partition (no analog needed in Xen — timers preempt).
#: Declared in the knob registry (runtime.executor.max_steps_per_quantum);
#: the native sim core restates it (sim/native_core.py) because the C
#: loop cannot read Python state.
MAX_STEPS_PER_QUANTUM = knobs.default(
    "runtime.executor.max_steps_per_quantum")

# Plain-int counter indices for the dispatch hot path (an IntEnum
# index pays an __index__ round trip per numpy access).
_I_DEVICE_TIME = int(Counter.DEVICE_TIME_NS)
_I_SCHED_COUNT = int(Counter.SCHED_COUNT)
_I_COMPILE_TIME = int(Counter.COMPILE_TIME_NS)


def quantum_to_steps(quantum_ns: int, avg_step_ns: float) -> int:
    if avg_step_ns <= 0:
        return 1
    return max(1, min(MAX_STEPS_PER_QUANTUM, round(quantum_ns / avg_step_ns)))


class Executor:
    """One schedulable device lane (pCPU analog)."""

    def __init__(self, partition: "Partition", index: int, device=None):
        self.partition = partition
        self.index = index
        self.device = device
        self.current: ExecutionContext | None = None
        self.idle_ns = 0
        self.sched_invocations = 0
        # Quanta actually dispatched (sched_invocations counts no-work
        # trips too — the watchdog must see real dispatches only).
        self.dispatch_count = 0
        # Micro-dispatch capability of the partition's source, resolved
        # once (a hasattr per quantum is measurable on the sim path).
        self._micro_ok = hasattr(partition.source, "execute_micro")

    # ------------------------------------------------------------------

    def schedule_once(self) -> bool:
        """One trip through the scheduler loop. Returns True if work ran."""
        part = self.partition
        now = part.clock.now_ns()
        part.timers.fire_due(now)
        decision = part.scheduler.do_schedule(self, now)
        self.sched_invocations += 1
        ctx = decision.ctx
        if ctx is None:
            return False
        self._run(ctx, decision.quantum_ns)
        return True

    def _run(self, ctx: ExecutionContext, quantum_ns: int) -> None:
        part = self.partition
        job = ctx.job
        now = part.clock.now_ns()

        if job.finished():
            # Admitted with max_steps already reached (e.g. 0): retire
            # without executing anything.
            for c in job.contexts:
                if c.state is not ContextState.DONE:
                    c.state = ContextState.DONE
                    part.scheduler.sleep(c)
            return

        # -- context switch in: pmu_restore_regs + sched_count++ --------
        self.current = ctx
        ctx.state = ContextState.RUNNING
        ctx.sched_count += 1
        self.dispatch_count += 1
        if ctx.ledger_slot >= 0:
            part.ledger.resume(ctx.ledger_slot, now)
        if part.trace_enabled:
            part.trace_emit(self.index, Ev.SCHED_PICK, ctx.ledger_slot,
                            quantum_ns)

        # Sub-step latency bounding: a job with micro_per_step > 1 is
        # dispatched in micro units (its step decomposed into compiled
        # chunks with host-checked exits between them), so a long step
        # no longer floors the quantum — the 100 µs slice analog
        # (sched_credit.c:52; SURVEY.md §7 "hard parts").
        K = job.micro_per_step
        micro = K > 1 and self._micro_ok
        if micro:
            n_units = quantum_to_steps(quantum_ns, ctx.avg_step_ns / K)
            if job.max_steps is not None:
                rem = ((job.max_steps - job.steps_retired()) * K
                       - ctx.micro_progress)
                n_units = max(1, min(n_units, rem))
            n_steps_equiv = n_units / K
        else:
            # quantum_to_steps, inlined (one call per dispatched
            # quantum is measurable on the sim fast path).
            avg = ctx.avg_step_ns
            if avg <= 0:
                n_units = 1
            else:
                n_units = round(quantum_ns / avg)
                if n_units < 1:
                    n_units = 1
                elif n_units > MAX_STEPS_PER_QUANTUM:
                    n_units = MAX_STEPS_PER_QUANTUM
            if job.max_steps is not None:
                remaining = job.max_steps - job.steps_retired()
                n_units = max(1, min(n_units, remaining))
            n_steps_equiv = n_units

        try:
            if micro:
                deltas = part.source.execute_micro(ctx, n_units)
            else:
                deltas = part.source.execute(ctx, n_units)
        except Exception as exc:  # noqa: BLE001 — contained below
            # Fault containment (the MCE model, tools/tests/mce-test):
            # a device/step fault poisons only the faulting job; the
            # partition and its other tenants keep running.
            if ctx.ledger_slot >= 0:
                part.ledger.suspend(
                    ctx.ledger_slot, np.zeros(NUM_COUNTERS, dtype=np.uint64))
            self.current = None
            part.fail_job(ctx.job, exc, ctx=ctx, lane=self.index)
            return

        # -- context switch out: pmu_save_regs (perfctr_cpu_vsuspend
        # publishes sums into vcpu->pmc[], perfctr.c:1547-1573) ----------
        ran_ns = int(deltas[_I_DEVICE_TIME])
        deltas[_I_SCHED_COUNT] = 1
        np.add(ctx.counters, deltas, out=ctx.counters)
        ctx.observe_step_time(ran_ns, n_steps_equiv)
        if part.compile_admission is not None:
            # Measured compile spend tightens the admission projections
            # (runtime.compile_gate) — the accounting leg of the claim.
            c_ns = int(deltas[_I_COMPILE_TIME])
            if c_ns:
                part.compile_admission.charge(job.name, c_ns)
        if ctx.ledger_slot >= 0:
            part.ledger.suspend(ctx.ledger_slot, deltas)
        self.current = None
        part.progress_epoch += 1

        end = part.clock.now_ns()
        if part.trace_enabled:
            part.trace_emit(self.index, Ev.SCHED_DESCHED, ctx.ledger_slot,
                            ran_ns)
        if part.recorder is not None:
            part.recorder.on_quantum(
                self.index, ctx, quantum_ns, n_units, deltas, now, end)
        part.timers.fire_due(end)
        part.scheduler.descheduled(self, ctx, ran_ns, end)
        # Overflow check at the quantum boundary (pmu_ihandler analog):
        # counters only advance here, so this is where i-mode thresholds
        # can cross; the virq is delivered by the run loop between quanta.
        part.sampler.check(ctx)

        if job.finished():
            for c in job.contexts:
                if c.state is not ContextState.DONE:
                    c.state = ContextState.DONE
                    part.scheduler.sleep(c)
        elif ctx.state is ContextState.RUNNING:
            ctx.state = ContextState.RUNNABLE

    def __repr__(self) -> str:
        return f"Executor({self.partition.name}#{self.index})"
