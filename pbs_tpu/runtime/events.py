"""Event channels: async doorbell signaling (event_channel.c analog).

Reference: Xen event channels (``xen/common/event_channel.c``) are the
async signaling fabric — interdomain doorbells, virtual IRQs
(``VIRQ_PERFCTR`` 13 added at ``public/xen.h:163``, delivered to the
guest's perfctr driver via ``send_guest_vcpu_virq``,
``pmustate.c:66-80``), and IPIs. Binding is by port; notification is
edge-triggered (pending bit), delivery is a callback.

Here: a per-partition EventBus with ports, VIRQ-style well-known
events, edge-triggered pending semantics (multiple sends before a
dispatch coalesce — exactly like the evtchn pending bit), masking, and
delivery either synchronous (sim determinism) or via the run loop
(``deliver_pending`` is called by the partition between quanta).
"""

from __future__ import annotations

import enum
from typing import Callable


class Virq(enum.IntEnum):
    """Well-known virtual interrupts (public/xen.h VIRQ_*)."""

    TELEMETRY = 13  # VIRQ_PERFCTR: counter overflow / telemetry event
    TRACE = 4  # VIRQ_TBUF: trace ring passed threshold
    WATCHDOG = 17  # job heartbeat missed
    CKPT_DONE = 32  # checkpoint epoch finished
    JOB_DONE = 33
    JOB_FAILED = 34  # fault contained to a job (MCE containment)


class EventChannel:
    __slots__ = ("port", "handler", "pending", "masked", "sends", "deliveries")

    def __init__(self, port: int, handler: Callable[[int], None]):
        self.port = port
        self.handler = handler
        self.pending = False
        self.masked = False
        self.sends = 0
        self.deliveries = 0


class EventBus:
    def __init__(self, synchronous: bool = False):
        """synchronous=True delivers at send time (deterministic sim);
        False coalesces until deliver_pending() (run-loop delivery)."""
        self.synchronous = synchronous
        self._channels: dict[int, EventChannel] = {}
        self._next_port = 64  # low ports reserved for VIRQs
        # Send-time taps: observe EVERY signal without occupying a port
        # (the doorbell bridge rides here — an interrupt is raised at
        # send, independent of in-process binding/masking/delivery).
        self._taps: list[Callable[[int], None]] = []

    def add_tap(self, fn: Callable[[int], None]) -> None:
        self._taps.append(fn)

    def remove_tap(self, fn: Callable[[int], None]) -> None:
        if fn in self._taps:
            self._taps.remove(fn)

    # -- binding (evtchn_bind_* analogs) ---------------------------------

    def bind(self, handler: Callable[[int], None], port: int | None = None) -> int:
        if port is None:
            while self._next_port in self._channels:
                self._next_port += 1
            port = self._next_port
            self._next_port += 1
        if port in self._channels:
            raise ValueError(f"port {port} already bound")
        self._channels[port] = EventChannel(port, handler)
        return port

    def bind_virq(self, virq: Virq, handler: Callable[[int], None]) -> int:
        return self.bind(handler, port=int(virq))

    def unbind(self, port: int) -> None:
        self._channels.pop(port, None)

    def mask(self, port: int, masked: bool = True) -> None:
        self._channels[port].masked = masked

    # -- signaling (evtchn_send / send_guest_vcpu_virq analogs) ----------

    def send(self, port: int) -> bool:
        for tap in self._taps:
            tap(port)  # fires even with no in-process subscriber
        ch = self._channels.get(port)
        if ch is None:
            return False
        ch.sends += 1
        ch.pending = True  # edge-triggered: repeat sends coalesce
        if self.synchronous and not ch.masked:
            self._deliver(ch)
        return True

    def send_virq(self, virq: Virq) -> bool:
        return self.send(int(virq))

    # -- delivery --------------------------------------------------------

    def _deliver(self, ch: EventChannel) -> None:
        ch.pending = False
        ch.deliveries += 1
        ch.handler(ch.port)

    def deliver_pending(self) -> int:
        """Dispatch all pending unmasked channels; returns count."""
        n = 0
        for ch in list(self._channels.values()):
            if ch.pending and not ch.masked:
                self._deliver(ch)
                n += 1
        return n

    def dump(self) -> list[dict]:
        return [
            {
                "port": ch.port,
                "pending": ch.pending,
                "masked": ch.masked,
                "sends": ch.sends,
                "deliveries": ch.deliveries,
            }
            for ch in sorted(self._channels.values(), key=lambda c: c.port)
        ]
