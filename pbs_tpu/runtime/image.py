"""Job images: boot a tenant from on-disk artifacts (the pygrub analog).

Reference: pygrub (``tools/pygrub``) reads a guest's disk image, parses
its bootloader config, extracts kernel+initrd, and hands them to the
domain builder — ``xl create`` boots an image with no externally
supplied kernel. The TPU-native analog makes a *job image directory*
the workload's self-describing boot medium:

    image.json  — the bootloader config: workload kind, model config,
                  training hyperparameters, sched params, data spec
    ckpt/       — optional checkpoint (the kernel/initrd: the state
                  that actually boots); absent = cold boot from init

``boot_job(path)`` parses the manifest, builds the model + compiled
train step, restores the checkpoint when present, and returns a ready
:class:`~pbs_tpu.runtime.job.Job`. ``image_workload`` exposes the same
flow as an agent workload factory, so ``pbst create -w image`` boots a
job from disk on any host — completing the xl-create-from-image story
the round-1 parity table marked "no analog".

``save_image`` is the other direction (the image builder): write the
manifest + current state so a running job can be turned back into
bootable media (and shipped, rsync'd, or placed under ``pbst migrate``).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:
    from pbs_tpu.runtime.partition import Partition

MANIFEST_NAME = "image.json"
CKPT_DIR = "ckpt"

_DTYPES = {"bfloat16": "bfloat16", "float32": "float32",
           "float16": "float16"}


def _resolve_dtype(name: str):
    import jax.numpy as jnp

    if name not in _DTYPES:
        raise ValueError(f"unsupported dtype {name!r} in image manifest")
    return getattr(jnp, name)


def _dtype_name(dtype) -> str:
    import numpy as np

    return np.dtype(dtype).name


def save_image(path: str, kind: str, config: dict, *, state=None,
               sched: dict | None = None, train: dict | None = None,
               data: dict | None = None,
               metadata: dict | None = None) -> dict:
    """Write a bootable job image. ``config`` holds the model-config
    kwargs (dtype as a string); ``state`` (optional) checkpoints the
    current (params, opt_state, step) so the boot is warm."""
    from pbs_tpu.ckpt.checkpoint import save_checkpoint

    os.makedirs(path, exist_ok=True)
    config = dict(config)
    if "dtype" in config and not isinstance(config["dtype"], str):
        # callers may pass a live dtype (e.g. jnp.bfloat16); manifests
        # store the canonical name so images stay JSON + portable
        config["dtype"] = _dtype_name(config["dtype"])
    manifest = {
        "version": 1,
        "kind": kind,
        "config": config,
        "sched": sched or {},
        "train": {"learning_rate": 3e-4, "batch": 4, "seq": 256,
                  "seed": 0, **(train or {})},
        "data": data or {"kind": "synthetic"},
        "metadata": metadata or {},
        "has_ckpt": state is not None,
    }
    # Checkpoint FIRST, manifest last: the manifest rename is the
    # commit point, so a crash mid-save can only leave an image that
    # under-promises (stale manifest), never one that promises warm
    # state it doesn't have.
    if state is not None:
        save_checkpoint(os.path.join(path, CKPT_DIR), state,
                        metadata={"image": kind})
    tmp = os.path.join(path, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, os.path.join(path, MANIFEST_NAME))
    return manifest


def read_manifest(path: str) -> dict:
    with open(os.path.join(path, MANIFEST_NAME)) as f:
        m = json.load(f)
    if m.get("version") != 1:
        raise ValueError(f"unsupported image version {m.get('version')!r}")
    if m.get("kind") not in ("transformer", "moe"):
        raise ValueError(f"unknown image kind {m.get('kind')!r}")
    return m


def _make_batch_fn(data: dict, image_path: str, batch: int, seq: int,
                   vocab: int, seed: int):
    """step -> (batch, seq) int32 host tokens, from the manifest's
    data spec. ``synthetic`` (default) needs no files; ``corpus``
    memory-maps a packed token file — a RELATIVE path resolves inside
    the image directory, so an image can carry its own data shard and
    stay a fully self-contained boot medium."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    kind = data.get("kind", "synthetic")
    if kind == "synthetic":
        def batch_fn(step: int):
            return jax.random.randint(
                jax.random.fold_in(jax.random.PRNGKey(seed + 1), step),
                (batch, seq), 0, vocab, jnp.int32)

        return batch_fn
    if kind == "corpus":
        from pbs_tpu.data.tokens import TokenDataset

        path = data.get("path")
        if not path:
            raise ValueError("corpus data spec needs 'path'")
        if not os.path.isabs(path):
            path = os.path.join(image_path, path)
        ds = TokenDataset(path)
        if seq > ds.n_tokens:
            # Loud at boot, not contained-as-job.error at step 0 (over
            # the control plane the create RPC would report success
            # while the job sat dead).
            raise ValueError(
                f"corpus {path!r} holds {ds.n_tokens} tokens — shorter "
                f"than one training sequence (seq={seq})")
        sequential = data.get("sampling", "random") == "sequential"

        def batch_fn(step: int):
            if sequential:
                return ds.window(step, batch, seq)
            # reproducible random windows: one generator per step
            rng = np.random.default_rng(seed * 1_000_003 + step)
            return ds.sample(batch, seq, rng)

        return batch_fn
    raise ValueError(f"unknown data kind {kind!r} in image manifest")


def _build(kind: str, config: dict, train: dict, data: dict,
           image_path: str):
    """(cfg, init_state_fn, step_fn) for a manifest."""
    import jax
    import jax.numpy as jnp

    cfg_kwargs = dict(config)
    if "dtype" in cfg_kwargs:
        cfg_kwargs["dtype"] = _resolve_dtype(cfg_kwargs["dtype"])
    lr = float(train.get("learning_rate", 3e-4))
    batch = int(train.get("batch", 4))
    seq = int(train.get("seq", 256))
    seed = int(train.get("seed", 0))

    if kind == "transformer":
        from pbs_tpu.models import (
            TransformerConfig,
            init_params,
            make_train_step,
        )

        cfg = TransformerConfig(**cfg_kwargs)
        init_opt, train_step = make_train_step(cfg, learning_rate=lr)

        def init_state():
            params = init_params(cfg, jax.random.PRNGKey(seed))
            return (params, jax.jit(init_opt)(params), 0)

    else:  # moe — validated by read_manifest
        from pbs_tpu.models import (
            MoEConfig,
            init_moe_params,
            make_moe_train_step,
        )

        cfg = MoEConfig(**cfg_kwargs)
        init_opt, train_step = make_moe_train_step(cfg, learning_rate=lr)

        def init_state():
            params = init_moe_params(cfg, jax.random.PRNGKey(seed))
            return (params, jax.jit(init_opt)(params), 0)

    seq = min(seq, cfg.max_seq)
    batch_fn = _make_batch_fn(data, image_path, batch, seq, cfg.vocab,
                              seed)

    def step_fn(state):
        tokens = batch_fn(int(state[2]))
        return train_step(state, tokens)

    return cfg, init_state, step_fn


def boot_job(path: str, name: str | None = None,
             max_steps: int | None = None):
    """Boot a Job from an image directory (cold from init, warm from
    the bundled checkpoint). The job is NOT yet admitted — hand it to
    ``Partition.add_job`` (or use ``image_workload`` via an agent)."""
    from pbs_tpu.ckpt.checkpoint import checkpoint_exists, restore_checkpoint
    from pbs_tpu.runtime.job import Job, SchedParams

    m = read_manifest(path)
    cfg, init_state, step_fn = _build(
        m["kind"], m["config"], m["train"],
        m.get("data") or {"kind": "synthetic"}, path)
    state = init_state()
    ckpt = os.path.join(path, CKPT_DIR)
    if m.get("has_ckpt"):
        if not checkpoint_exists(ckpt):
            # Never silently cold-boot a warm image: restarting from
            # step 0 under the same name would discard all progress
            # without a trace (truncated copy / partial rsync).
            raise FileNotFoundError(
                f"image {path!r} promises a checkpoint (has_ckpt) but "
                f"{ckpt!r} has no manifest — refusing to cold-boot")
        state, _ = restore_checkpoint(ckpt, like=state)
    return Job(
        name or m["metadata"].get("name", os.path.basename(path.rstrip("/"))),
        step_fn=step_fn,
        state=state,
        params=SchedParams(**m.get("sched", {})),
        max_steps=max_steps if max_steps is not None
        else m["train"].get("max_steps"),
        label=str(m["metadata"].get("label", "user")),
    )


def image_workload(partition: "Partition", job_name: str,
                   spec: dict) -> Any:
    """Agent workload factory: ``spec={"path": <image dir>, ...}`` —
    the ``xl create <image>`` flow over the control plane. Extra spec
    keys override the manifest (sched, max_steps)."""
    path = spec.get("path")
    if not path:
        raise ValueError("image workload needs spec['path']")
    job = boot_job(path, name=job_name, max_steps=spec.get("max_steps"))
    for k, v in (spec.get("sched") or {}).items():
        if not hasattr(job.params, k):
            # a typo'd knob silently running at defaults is worse than
            # a loud reject (the manifest path raises the same way)
            raise KeyError(f"unknown sched param {k!r} in image spec")
        setattr(job.params, k, v)
    if "label" in spec:
        job.label = str(spec["label"])
    return partition.add_job(job)
