"""Jobs and execution contexts — the domain/vCPU analogs.

Reference mapping (SURVEY.md §7):

- ``struct domain``  -> ``Job``: one tenant workload (a pjit-compiled
  train/serve loop) with scheduling parameters (weight, cap, per-job
  adaptive time slice — ``csched_dom`` fields at ``sched_credit.c:204-219``)
  and accumulated contention telemetry (``spinlock_latency`` /
  ``spinlock_count`` fed by ``do_vcrd_op``, ``sched_credit.c:249-259``).
- ``struct vcpu``    -> ``ExecutionContext``: one schedulable lane of a
  job on one executor. Multi-context jobs are the analog of multi-vCPU
  SMP guests and are gang-scheduled (lock-holder preemption reborn:
  preempting one host of a ring stalls the ring — SURVEY.md §7 risks).
  Carries the per-context counter mirror (``vcpu->pmc[18]``,
  ``xen/include/xen/sched.h:178-180``) and ``sched_count``
  (``arch/x86/domain.c:1620``).

A TPU job cannot be preempted mid-step (no device-level preemption):
the scheduling quantum is realized as a number of compiled steps, with
the per-job time slice converted through the job's measured step time.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable

import numpy as np

from pbs_tpu.telemetry.counters import NUM_COUNTERS, Counter


class ContextState(enum.Enum):
    # Mirrors RUNSTATE_* (xen/include/public/vcpu.h) in spirit.
    RUNNABLE = "runnable"
    RUNNING = "running"
    BLOCKED = "blocked"  # sleeping; waits for wake()
    PARKED = "parked"  # cap exceeded (CSCHED_FLAG_VCPU_PARKED analog)
    DONE = "done"
    FAILED = "failed"  # contained fault (MCE-containment analog)


from pbs_tpu.utils.params import integer_param

# Boot-param analog of ``sched_credit_tslice_us=`` (sched_credit.c:126-127):
# overrides the default per-job slice for jobs that don't set one.
_tslice_param = integer_param("sched_credit_tslice_us", 100)


@dataclasses.dataclass
class SchedParams:
    """Per-job scheduling knobs (the ``xl sched-credit -w/-c/-t`` surface,
    ``tools/libxl/xl_cmdimpl.c:4805-4896``)."""

    weight: int = 256  # CSCHED_DEFAULT_WEIGHT (sched_credit.c)
    cap: int = 0  # percent of one executor; 0 = uncapped
    # Per-job time slice in µs; adaptive policy mutates this.
    # CSCHED_DEFAULT_TSLICE_US = 100 (sched_credit.c:52).
    tslice_us: int = dataclasses.field(
        default_factory=lambda: _tslice_param.value)
    # Latency-sensitive jobs get BOOST priority on wake (serving).
    boost_on_wake: bool = True


class Job:
    """One tenant workload.

    ``step_fn(state) -> state`` or ``(state, metrics_dict)`` must be a
    host-callable that advances the job by exactly one step (normally a
    jit-compiled function). ``compiled`` optionally exposes the XLA
    executable for cost analysis. For SimBackend jobs, ``step_fn`` may be
    ``None`` — the backend is the device.
    """

    def __init__(
        self,
        name: str,
        step_fn: Callable[[Any], Any] | None = None,
        state: Any = None,
        params: SchedParams | None = None,
        compiled: Any = None,
        max_steps: int | None = None,
        n_contexts: int = 1,
        gang: bool = False,
        label: str = "user",
        mem_bytes: int | None = None,
        micro_per_step: int = 1,
        micro_step_fn: Callable[[Any], Any] | None = None,
        n_programs: int = 1,
        est_compile_ns: int | None = None,
    ):
        self.name = name
        # Compile-cache admission declaration (runtime.compile_gate):
        # how many distinct XLA programs this job brings (cache entries
        # it will occupy) and, optionally, the expected per-program
        # compile cost; undeclared costs are projected from the
        # observed fleet average.
        self.n_programs = max(1, int(n_programs))
        self.est_compile_ns = est_compile_ns
        # Security label for XSM checks (the FLASK domain label).
        self.label = label
        # Declared HBM working set; None = estimate from state at
        # admission (runtime.memory.nbytes_of).
        self.mem_bytes = mem_bytes
        self.step_fn = step_fn
        # Sub-step latency bounding (SURVEY.md §7 "hard parts"; the real
        # analog of the reference's 100 µs slice, sched_credit.c:52):
        # a job whose compiled step is long may decompose it into
        # ``micro_per_step`` micro-steps (e.g. gradient-accumulation
        # chunks, each an inner lax.scan), advanced by ``micro_step_fn``
        # (required when K > 1 on a real backend — step_fn advances a
        # FULL step). The executor then converts quanta to micro units
        # and can deschedule the job mid-step at a chunk boundary — a
        # host-checked early exit between compiled chunks. The mid-step
        # position lives in ctx.micro_progress and travels in
        # save/restore records (dist/agent.py) so migration can't
        # desync step retirement from the model's accumulation cursor.
        self.micro_per_step = max(1, int(micro_per_step))
        self.micro_step_fn = micro_step_fn
        self.state = state
        self.params = params or SchedParams()
        self.compiled = compiled
        self.max_steps = max_steps
        self.gang = gang and n_contexts > 1
        self.contexts: list[ExecutionContext] = [
            ExecutionContext(self, i) for i in range(n_contexts)
        ]
        # Contention channel accumulators (sdom->spinlock_latency /
        # spinlock_count, filled by do_vcrd_op sched_credit.c:249-259).
        self.contention_wait_ns: int = 0
        self.contention_events: int = 0
        # Metric outputs recomputed by the feedback policy
        # (sdom->cache_miss_rate / cpi, sched_credit.c:427-435).
        self.stall_rate: float = 0.0
        self.nspi: float = 0.0  # ns per step (CPI analog)
        # Set by Partition.fail_job when a fault is contained to this job.
        self.error: str | None = None
        # Scheduler-private per-job state hangs here (sched "domdata").
        self.sched_priv: Any = None
        # Measured-telemetry override: profile every N-th invocation of
        # THIS job regardless of the backend-wide default (None = use
        # the backend's). Foreign tenants set this so they get measured
        # phases without cooperating (the HVM vPMU analog).
        self.profile_every: int | None = None
        # (fn, args, kwargs) of a foreign callable adopted via
        # Job.foreign — lets the backend harvest XLA cost analysis
        # from the jit wrapper lazily, attributed to this job.
        self._foreign_spec: tuple | None = None
        # Per-job console ring (the xl console analog): lifecycle
        # events land here; the workload writes via Job.log.
        from pbs_tpu.obs.console import Console

        self.console = Console()
        # xenpaging analog (runtime.paging): while non-None, the
        # device leaves of ``state`` live in host memory and the job
        # must stay BLOCKED; wake_job restores transparently.
        self.paged = None
        self.paged_bytes = 0
        self.paged_acct_bytes = 0

    @classmethod
    def foreign(
        cls,
        name: str,
        fn: Callable[..., Any],
        *call_args: Any,
        params: "SchedParams | None" = None,
        max_steps: int | None = None,
        profile_every: int = 8,
        **call_kwargs: Any,
    ) -> "Job":
        """Adopt an arbitrary jitted callable as a tenant — the HVM
        vPMU analog.

        The reference fully virtualizes the PMU for guests that know
        nothing about the hypervisor: ``vpmu_core2.c`` saves/loads the
        real counter MSRs around each vcpu switch and traps the guest's
        own MSR accesses (``core2_vpmu_save``/``__core2_vpmu_load``,
        ``xen-4.2.1/xen/arch/x86/hvm/vmx/vpmu_core2.c:267-518``), so a
        non-paravirtualized HVM guest still yields measured telemetry.
        Here the same claim: ``fn`` follows no framework protocol — any
        signature, any return value, no metrics dict — yet the job gets
        *measured* stall/collective phases, because the backend samples
        the XLA profiler around its quanta (``telemetry/profiler.py``)
        and harvests cost analysis from the jit wrapper, rather than
        asking the workload to report.

        Each step invokes ``fn(*call_args, **call_kwargs)`` and syncs
        on its output; the arguments are fixed (a tenant that wants to
        thread state through steps is by definition cooperating — use
        the normal ``Job`` protocol).
        """
        job = cls(name, step_fn=None, state=None, params=params,
                  max_steps=max_steps)

        def step_fn(_state):
            # Once the backend has harvested the AOT executable
            # (telemetry.source._job_cost), dispatch through it: the
            # jit wrapper's own call cache is separate from the AOT
            # path, so calling ``fn`` again would compile a second
            # time on a real chip (~20-40 s double-charged).
            target = job.compiled if job.compiled is not None else fn
            # Pin the no-cooperation contract: wrap in (state, {}) so
            # a foreign fn returning (output, some_dict) — an ordinary
            # JAX (out, aux) shape — is never sniffed as the
            # cooperative metrics protocol by the backend.
            return target(*call_args, **call_kwargs), {}

        job.step_fn = step_fn
        job.profile_every = max(1, int(profile_every))
        job._foreign_spec = (fn, call_args, call_kwargs)
        return job

    def log(self, line: str) -> int:
        """Workload-side console write (the guest printk)."""
        return self.console.write(line)

    # -- contention hints (batched vcrd_op) ------------------------------

    def report_contention(self, wait_ns: int, events: int = 1) -> None:
        """Batched analog of the ``vcrd_op`` hypercall: the workload (or
        the collective instrumentation in pbs_tpu.parallel) reports time
        spent waiting on peers. Accumulated here, consumed and cleared by
        the feedback policy's metric tick (sched_credit.c:302-389)."""
        self.contention_wait_ns += int(wait_ns)
        self.contention_events += int(events)

    def take_contention(self) -> tuple[int, int]:
        w, e = self.contention_wait_ns, self.contention_events
        self.contention_wait_ns = 0
        self.contention_events = 0
        return w, e

    # -- progress --------------------------------------------------------

    def steps_retired(self) -> int:
        return int(
            sum(int(c.counters[Counter.STEPS_RETIRED]) for c in self.contexts)
        )

    def finished(self) -> bool:
        if self.max_steps is None:
            return False
        return self.steps_retired() >= self.max_steps

    def __repr__(self) -> str:
        return f"Job({self.name!r}, w={self.params.weight}, cap={self.params.cap})"


class ExecutionContext:
    """One schedulable lane of a job (vCPU analog)."""

    def __init__(self, job: Job, index: int):
        self.job = job
        self.index = index
        self.state = ContextState.RUNNABLE
        # Counter mirror maintained by the executor at deschedule
        # (vcpu->pmc[], published by perfctr_cpu_vsuspend,
        # xen/arch/x86/perfctr.c:1547-1573).
        self.counters = np.zeros(NUM_COUNTERS, dtype=np.uint64)
        # Feedback policy's last-seen values (csched_vcpu->prev_pmc,
        # delta'd at sched_credit.c:411-425).
        self.prev_counters = np.zeros(NUM_COUNTERS, dtype=np.uint64)
        # vcpu->sched_count analog.
        self.sched_count = 0
        # EWMA of step wall time, for quantum(ns) -> steps conversion.
        self.avg_step_ns: float = 1_000_000.0
        # Position within the current step in micro units
        # (0..job.micro_per_step-1); advanced by the telemetry source.
        self.micro_progress: int = 0
        # Assigned executor id (affinity pin; None = any).
        self.executor_hint: int | None = None
        # Ledger slot id, assigned by the partition at admission.
        self.ledger_slot: int = -1
        # Scheduler-private per-context state (sched "vdata").
        self.sched_priv: Any = None

    @property
    def name(self) -> str:
        return f"{self.job.name}/{self.index}"

    def runnable(self) -> bool:
        return self.state in (ContextState.RUNNABLE, ContextState.RUNNING)

    def observe_step_time(self, total_ns: int, n_steps: int) -> None:
        if n_steps <= 0 or total_ns <= 0:
            return
        per = total_ns / n_steps
        # EWMA alpha=0.25: smooth enough to ride compile spikes, fast
        # enough to track phase changes at the 1 ms metric cadence.
        self.avg_step_ns = 0.75 * self.avg_step_ns + 0.25 * per

    def __repr__(self) -> str:
        return f"Ctx({self.name}, {self.state.value})"
