"""Lifecycle hook scripts: the Xen hotplug-script analog.

Reference: domain lifecycle drives ``/etc/xen/scripts/*`` — vif/vbd
hotplug scripts run with a device environment on attach/detach, and a
script failure fails the attach (the domain doesn't get a half-plugged
device). The TPU analog attaches side-effectful environment setup to
job lifecycle: mounting a dataset path, registering with an external
tracker, tearing down exports — things the framework itself should not
hardcode.

Hooks may be Python callables (``fn(event, env)``) or shell commands
(run with the event environment exported as ``PBST_*`` variables, the
exact hotplug-script contract). ``required=True`` hooks propagate
failure — an admission hook that raises aborts ``add_job`` and the
partition unwinds the whole admission (the attach-fails semantics);
optional hooks are contained and counted.
"""

from __future__ import annotations

import subprocess
from typing import Callable

EVENTS = ("job-add", "job-remove", "job-fail", "job-sleep", "job-wake")


class HookError(RuntimeError):
    """A required hook failed; the triggering operation must unwind."""


class HookRegistry:
    def __init__(self):
        self._hooks: dict[str, list[tuple[object, bool]]] = {
            e: [] for e in EVENTS}
        self.failures = 0
        self.fired = 0

    def on(self, event: str, hook: "Callable | str",
           required: bool = False) -> None:
        """Register a callable ``fn(event, env)`` or a shell command
        string for ``event``."""
        if event not in self._hooks:
            raise ValueError(f"unknown hook event {event!r}; "
                             f"one of {EVENTS}")
        self._hooks[event].append((hook, required))

    def fire(self, event: str, env: dict[str, str],
             console=None) -> None:
        """Run all hooks for ``event``. Optional-hook failures are
        contained (counted, logged to ``console`` when given);
        required-hook failures raise :class:`HookError`."""
        for hook, required in self._hooks.get(event, ()):
            self.fired += 1
            try:
                if callable(hook):
                    hook(event, dict(env))
                else:
                    import os

                    proc = subprocess.run(
                        str(hook), shell=True, capture_output=True,
                        timeout=60, env={**os.environ, **env},
                    )
                    if proc.returncode != 0:
                        raise RuntimeError(
                            f"hook command rc={proc.returncode}: "
                            f"{proc.stderr.decode(errors='replace')[-200:]}")
            except Exception as e:  # noqa: BLE001 — containment decision
                self.failures += 1
                if console is not None:
                    console.write(f"[hook:{event}] FAILED: {e}")
                if required:
                    raise HookError(f"{event} hook failed: {e}") from e

    def dump(self) -> dict:
        return {
            "registered": {e: len(h) for e, h in self._hooks.items() if h},
            "fired": self.fired,
            "failures": self.failures,
        }
