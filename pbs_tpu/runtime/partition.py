"""Partitions: cpupool analogs owning devices, a scheduler, and jobs.

Xen cpupools (``xen/common/cpupool.c``) hard-partition pCPUs into pools,
each with its own scheduler instance; domains live in exactly one pool.
Here a Partition owns a set of device lanes (TPU cores/chips or sim
lanes), one scheduler instance chosen from the registry, the telemetry
ledger for its contexts (the 8-page shared_info analog,
``xen/common/domain.c:618-626``), and the timer substrate.

The cooperative ``run()`` loop drives executors round-robin on one host
thread — the simulation/CI mode. Under a ``VirtualClock`` the loop is
fully deterministic; when every executor is idle the clock jumps to the
next timer deadline (event-driven simulation).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

import os

from pbs_tpu.obs.trace import Ev, EmitBatch, TraceBuffer, merge_records
from pbs_tpu.runtime import xsm
from pbs_tpu.runtime.events import EventBus, Virq
from pbs_tpu.runtime.executor import Executor
from pbs_tpu.runtime.job import ContextState, Job, SchedParams
from pbs_tpu.runtime.timer import TimerWheel
from pbs_tpu.sched.base import Scheduler, make_scheduler
from pbs_tpu.telemetry.ledger import Ledger
from pbs_tpu.telemetry.source import TelemetrySource
from pbs_tpu.utils.clock import Clock, VirtualClock
from pbs_tpu.utils.params import string_param

DEFAULT_LEDGER_SLOTS = 128

# ``sched=`` boot param (schedule.c:65-70): the scheduler a partition
# gets when its creator doesn't pick one explicitly.
_sched_param = string_param("sched", "credit")


class Partition:
    def __init__(
        self,
        name: str,
        source: TelemetrySource,
        scheduler: str | None = None,
        n_executors: int = 1,
        devices: list[Any] | None = None,
        clock: Clock | None = None,
        ledger_slots: int = DEFAULT_LEDGER_SLOTS,
        ledger_path: str | None = None,
        trace_dir: str | None = None,
        sched_params: dict[str, Any] | None = None,
        memory: "MemoryManager | None" = None,
        compile_admission: "CompileAdmission | None" = None,
    ):
        self.name = name
        self.source = source
        self.clock = clock if clock is not None else source.clock
        self.timers = TimerWheel()
        # File-backed ledger lets external monitors (pbst top) read live
        # telemetry lock-free across processes.
        self._ledger_path = ledger_path
        if ledger_path is not None:
            self.ledger = Ledger.file_backed(ledger_path, ledger_slots)
        else:
            self.ledger = Ledger(ledger_slots)
        # Per-executor lockless trace rings (per-CPU rings, trace.c).
        self.traces: list[TraceBuffer] = []
        # Master trace switch (the tb_init_done analog): single-owner
        # drivers that consume no ring (sim sweep cells) turn it off so
        # dispatch events skip the ring entirely.
        self.trace_enabled = True
        # Optional per-ring staging batches (enable_trace_batching):
        # single-threaded drivers (the sim engine) trade immediate ring
        # visibility for one vectorized write per batch.
        self._trace_batches: list[EmitBatch] | None = None
        # Async signaling fabric (event_channel.c analog); delivered by
        # the run loop between quanta.
        self.events = EventBus()
        # i-mode counter sampling: thresholds -> Virq.TELEMETRY -> rearm
        # (the VIRQ_PERFCTR overflow path, telemetry/sampler.py).
        from pbs_tpu.telemetry.sampler import OverflowSampler

        self.sampler = OverflowSampler(self.events)
        # Optional quantum/tick recorder (pbs_tpu.sim.trace.TraceRecorder):
        # when set, every dispatched quantum and feedback tick is appended
        # as a JSONL record so the run can be replayed in the simulator.
        self.recorder = None
        # Optional HBM accounting/admission (runtime.memory).
        self.memory = memory
        # Optional compile-cache admission (runtime.compile_gate): the
        # TPU-new scarce resource SURVEY.md §7 flags — distinct programs
        # per partition and cumulative compile time.
        self.compile_admission = compile_admission
        # Lifecycle hook scripts (the /etc/xen/scripts hotplug analog,
        # runtime.hooks): required job-add hooks gate admission.
        from pbs_tpu.runtime.hooks import HookRegistry

        self.hooks = HookRegistry()
        self._free_slots = list(range(ledger_slots - 1, -1, -1))
        self.jobs: list[Job] = []
        # Monotone quantum counter; WallWatchdog reads it out-of-band.
        self.progress_epoch = 0
        # Hook invoked on contained job failures (crash-dump wiring).
        self.on_job_failure: Callable[[Job, BaseException], None] | None = None
        self.executors: list[Executor] = []
        self.scheduler: Scheduler = make_scheduler(
            scheduler if scheduler is not None else _sched_param.value,
            self, **(sched_params or {})
        )
        # File-backed rings let an external xenbaked-style monitor attach
        # live (obs.mon); otherwise rings live in process memory.
        # Absolute path: the meta sidecar publishes it for monitors that
        # run with a different working directory.
        self._trace_dir = (
            os.path.abspath(trace_dir) if trace_dir is not None else None)
        if self._trace_dir is not None:
            os.makedirs(self._trace_dir, exist_ok=True)
        devices = devices or [None] * n_executors
        for i, dev in enumerate(devices):
            ex = Executor(self, i, device=dev)
            self.executors.append(ex)
            if self._trace_dir is not None:
                self.traces.append(TraceBuffer.file_backed(
                    os.path.join(self._trace_dir, f"trace{i}.ring")))
            else:
                self.traces.append(TraceBuffer())
            self.scheduler.executor_added(ex)
        # Overflow crossings land in ring 0 as TELEM_OVERFLOW in every
        # mode (trace content must not depend on whether trace batching
        # is enabled): the sampler stages a quantum's firings and
        # flushes at the end of each check() call.
        if self.traces:
            self.sampler.bind_trace(
                EmitBatch(self.traces[0], capacity=64), self.clock)

    # -- admission (domain_create analog, xen/common/domain.c) -----------

    def add_job(self, job: Job, subject: str = xsm.SYSTEM) -> Job:
        xsm.xsm_check(subject, "job.create", job.label)
        if self.compile_admission is not None:
            # Fail-fast compile-cache claim FIRST: it touches no shared
            # state beyond its own table, so rejection leaves nothing
            # to unwind (the XENMEM_claim_pages ordering).
            self.compile_admission.admit(job)
        if self.memory is not None:
            # Fail-fast HBM admission (XENMEM_claim_pages): account +
            # claim the working set before touching scheduler state, so
            # a denied job leaves nothing behind.
            from pbs_tpu.runtime.memory import nbytes_of

            need = (job.mem_bytes if job.mem_bytes is not None
                    else nbytes_of(job.state))
            self.memory.open_account(job.name)
            try:
                self.memory.claim_or_balloon(job.name, need)
            except Exception:
                self.memory.close_account(job.name)
                if self.compile_admission is not None:
                    self.compile_admission.release(job.name)
                raise
        try:
            for ctx in job.contexts:
                if not self._free_slots:
                    raise RuntimeError("ledger slots exhausted")
                ctx.ledger_slot = self._free_slots.pop()
                self.ledger.reset(ctx.ledger_slot)
        except Exception:
            # Unwind fully — slots back on the freelist, account closed —
            # so a failed admission leaves nothing behind and the name
            # stays retryable.
            for ctx in job.contexts:
                if ctx.ledger_slot >= 0:
                    self._free_slots.append(ctx.ledger_slot)
                    ctx.ledger_slot = -1
            if self.memory is not None:
                self.memory.close_account(job.name)
            if self.compile_admission is not None:
                self.compile_admission.release(job.name)
            raise
        # Scheduler enrollment is part of the same atomic admission: a
        # job_added/wake failure must unwind jobs-list membership, the
        # ledger slots, and the memory account, or the name stops being
        # retryable and the slots leak.
        enrolled = False
        try:
            self.jobs.append(job)
            self.scheduler.job_added(job)
            enrolled = True
            for ctx in job.contexts:
                if ctx.state is ContextState.RUNNABLE:
                    self.scheduler.wake(ctx)
            self._publish_meta()
            # Hotplug: a REQUIRED job-add hook failing aborts the whole
            # admission (the vif-attach-fails semantics) via the unwind
            # below; optional failures are contained inside fire().
            self.hooks.fire("job-add", self._hook_env(job),
                            console=job.console)
            job.console.write(
                f"admitted to {self.name} "
                f"({len(job.contexts)} ctx, scheduler "
                f"{self.scheduler.name})")
        except Exception:
            if enrolled:
                try:
                    self.scheduler.job_removed(job)
                except Exception:  # noqa: BLE001 — best-effort unwind
                    pass
            if job in self.jobs:
                self.jobs.remove(job)
            for ctx in job.contexts:
                if ctx.ledger_slot >= 0:
                    self._free_slots.append(ctx.ledger_slot)
                    ctx.ledger_slot = -1
            if self.memory is not None:
                self.memory.close_account(job.name)
            if self.compile_admission is not None:
                self.compile_admission.release(job.name)
            try:
                # A required-hook failure lands AFTER the sidecar was
                # published: republish so monitors never attribute the
                # freed slots to a job that was never admitted.
                self._publish_meta()
            except Exception:  # noqa: BLE001 — unwind must complete
                pass
            raise
        return job

    def create_job(
        self,
        name: str,
        step_fn: Callable | None = None,
        state: Any = None,
        params: SchedParams | None = None,
        **kw: Any,
    ) -> Job:
        job = Job(name, step_fn=step_fn, state=state, params=params, **kw)
        return self.add_job(job)

    def _hook_env(self, job: Job, **extra: str) -> dict[str, str]:
        return {
            "PBST_JOB": job.name,
            "PBST_PARTITION": self.name,
            "PBST_LABEL": job.label,
            **extra,
        }

    def remove_job(self, job: Job, subject: str = xsm.SYSTEM) -> None:
        xsm.xsm_check(subject, "job.destroy", job.label)
        from pbs_tpu.runtime.hooks import HookError

        try:
            # Teardown hooks run while the job still exists (the detach
            # script sees the device); failure cannot block destruction.
            self.hooks.fire("job-remove", self._hook_env(job),
                            console=job.console)
        except HookError:
            pass
        job.console.write("destroyed")
        if self.memory is not None:
            self.memory.close_account(job.name)
        if self.compile_admission is not None:
            self.compile_admission.release(job.name)
        # Dead jobs must not pin their contexts via armed samples (or
        # keep getting scanned by every overflow check).
        self.sampler.disarm_job(job)
        self.scheduler.job_removed(job)
        self.jobs.remove(job)
        for ctx in job.contexts:
            if ctx.ledger_slot >= 0:
                self._free_slots.append(ctx.ledger_slot)
                ctx.ledger_slot = -1
        self._publish_meta()

    def job(self, name: str) -> Job:
        for j in self.jobs:
            if j.name == name:
                return j
        raise KeyError(name)

    # -- run-state control (vcpu_sleep/wake, schedule.c) -----------------

    def sleep_job(self, job: Job, notify: bool = True) -> None:
        """``notify=False`` is the internal-quiesce form (Remus epoch
        capture, migration save): a sub-second suspend/resume cycle is
        not a lifecycle event, and hotplug scripts must not run inside
        it (Xen likewise never runs scripts on Remus epochs)."""
        from pbs_tpu.runtime.hooks import HookError

        changed = False
        for ctx in job.contexts:
            if ctx.runnable():
                ctx.state = ContextState.BLOCKED
                self.scheduler.sleep(ctx)
                changed = True
        if changed and notify:
            try:
                self.hooks.fire("job-sleep", self._hook_env(job),
                                console=job.console)
            except HookError:
                pass  # run-state changes cannot be vetoed

    def wake_job(self, job: Job, notify: bool = True) -> None:
        from pbs_tpu.runtime.hooks import HookError

        if getattr(job, "paged", None) is not None:
            # xenpaging fault path: touching a paged tenant restores
            # its device state first (claiming HBM back; may raise
            # OutOfDeviceMemory, leaving the job asleep+paged).
            from pbs_tpu.runtime.paging import page_in_job

            page_in_job(self, job)
        changed = False
        for ctx in job.contexts:
            if ctx.state is ContextState.BLOCKED:
                ctx.state = ContextState.RUNNABLE
                self.scheduler.wake(ctx)
                changed = True
        if changed and notify:
            try:
                self.hooks.fire("job-wake", self._hook_env(job),
                                console=job.console)
            except HookError:
                pass

    def fail_job(self, job: Job, exc: BaseException,
                 ctx: "ExecutionContext | None" = None,
                 lane: int = 0) -> None:
        """Contain a fault to one job (the MCE-containment model,
        ``tools/tests/mce-test``): mark every context FAILED, notify,
        dump — the partition and its other tenants keep running.
        ``ctx``/``lane`` identify the faulting context and executor so
        the postmortem trace names the right victim."""
        job.error = f"{type(exc).__name__}: {exc}"
        job.console.write(f"FAULT contained: {job.error}")
        self.sampler.disarm_job(job)
        from pbs_tpu.runtime.hooks import HookError

        try:
            self.hooks.fire(
                "job-fail",
                self._hook_env(job, PBST_ERROR=job.error),
                console=job.console)
        except HookError:
            pass  # containment must complete regardless
        for c in job.contexts:
            if c.state is not ContextState.FAILED:
                c.state = ContextState.FAILED
                self.scheduler.sleep(c)
        if ctx is None and job.contexts:
            ctx = job.contexts[0]
        self.trace_emit(lane, Ev.JOB_FAILED,
                        ctx.ledger_slot if ctx is not None else 0)
        self.events.send_virq(Virq.JOB_FAILED)
        if self.on_job_failure is not None:
            self.on_job_failure(job, exc)

    # -- the loop --------------------------------------------------------

    def pending_work(self) -> bool:
        # PARKED counts: a timer (acct refill) will unpark it
        # (CSCHED_FLAG_VCPU_PARKED is cleared in csched_acct).
        live = (ContextState.RUNNABLE, ContextState.RUNNING,
                ContextState.PARKED)
        return any(
            ctx.state in live for j in self.jobs for ctx in j.contexts
        )

    def run(
        self,
        until_ns: int | None = None,
        max_rounds: int | None = None,
    ) -> int:
        """Drive executors until no runnable work (or bounds hit).

        Returns the number of quanta executed.
        """
        rounds = 0
        quanta = 0
        # Hot-loop hoists: bound methods + the executor list are loop
        # invariants, and a round is ~one dispatched quantum.
        now_ns = self.clock.now_ns
        deliver_pending = self.events.deliver_pending
        executors = self.executors
        while True:
            if until_ns is not None and now_ns() >= until_ns:
                break
            if max_rounds is not None and rounds >= max_rounds:
                break
            rounds += 1
            deliver_pending()
            ran_any = False
            for ex in executors:
                if until_ns is not None and now_ns() >= until_ns:
                    break
                if ex.schedule_once():
                    ran_any = True
                    quanta += 1
            if not ran_any:
                if not self.pending_work():
                    break
                # All runnable work exists but nothing was dispatched
                # (e.g. parked for cap enforcement): jump to the next
                # timer event under virtual time, else we're stuck.
                deadline = self.timers.next_deadline()
                if deadline is None:
                    break
                if isinstance(self.clock, VirtualClock):
                    if deadline > self.clock.now_ns():
                        self.clock.advance(deadline - self.clock.now_ns())
                    self.timers.fire_due(self.clock.now_ns())
                else:
                    import time as _t

                    _t.sleep(min(0.001, max(0.0, (deadline - self.clock.now_ns()) / 1e9)))
        # Refresh the monitor sidecar so adapted tslice/weights are
        # visible to pbst top after the run; staged trace batches land
        # in the rings so attached monitors see the full stream.
        self.flush_traces()
        self._publish_meta()
        return quanta

    # -- observability ---------------------------------------------------

    def _publish_meta(self) -> None:
        """Sidecar slot map so external monitors can label ledger slots
        (the xenstore-registered device metadata analog)."""
        if self._ledger_path is None:
            return
        import json

        meta = {
            "partition": self.name,
            "scheduler": self.scheduler.name,
            "trace_dir": self._trace_dir,
            "n_rings": len(self.traces),
            # Counter-source provenance (docs/HWTELEM.md): sources
            # that can say what they are (hwtelem ladder tiers) do, so
            # `pbst top` never reports sim-sourced numbers as live.
            "source": (self.source.describe()
                       if hasattr(self.source, "describe") else
                       {"tier": type(self.source).__name__}),
            "slots": {
                str(ctx.ledger_slot): {
                    "ctx": ctx.name,
                    "job": job.name,
                    "weight": job.params.weight,
                    "cap": job.params.cap,
                    "tslice_us": job.params.tslice_us,
                }
                for job in self.jobs
                for ctx in job.contexts
                if ctx.ledger_slot >= 0
            },
        }
        tmp = self._ledger_path + ".meta.json.tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=1)
        os.replace(tmp, self._ledger_path + ".meta.json")

    def enable_trace_batching(self, capacity: int = 256,
                              flush_ns: int = 1_000_000) -> None:
        """Stage trace events per ring through :class:`EmitBatch` (one
        vectorized ``emit_many`` per watermark instead of a scalar emit
        per event). Only for single-threaded drivers that own every
        producer — the sim engine — because staged records reach the
        ring at flush granularity; live multi-threaded partitions keep
        scalar emits so cross-thread ring order matches emit order."""
        self._trace_batches = [
            EmitBatch(t, capacity=capacity, flush_ns=flush_ns)
            for t in self.traces
        ]

    def flush_traces(self) -> None:
        if self._trace_batches is not None:
            for b in self._trace_batches:
                b.flush()

    def trace_emit(self, exi: int, event: int, *args: int) -> None:
        if self.trace_enabled and 0 <= exi < len(self.traces):
            if self._trace_batches is not None:
                self._trace_batches[exi].emit(
                    self.clock.now_ns(), event, *args)
            else:
                self.traces[exi].emit(self.clock.now_ns(), event, *args)

    def peek_traces(self, max_records: int = 4096):
        """Non-destructive tail of all rings, merged and time-sorted —
        for postmortems/snapshots that must not race a live consumer."""
        self.flush_traces()
        return merge_records([t.peek(max_records) for t in self.traces])

    def drain_traces(self, max_records: int = 4096):
        """xentrace analog: drain all rings, merged and time-sorted."""
        self.flush_traces()
        return merge_records([t.consume(max_records) for t in self.traces])

    def dump(self) -> dict[str, Any]:
        """The 'r'/'z' console-key dump surface
        (``keyhandler.c:543-563``, ``schedule_customized_dump``
        ``schedule.c:1442-1451``)."""
        return {
            "partition": self.name,
            "scheduler": self.scheduler.dump_settings(),
            "executors": [
                {
                    "index": ex.index,
                    "sched_invocations": ex.sched_invocations,
                    **self.scheduler.dump_executor(ex),
                }
                for ex in self.executors
            ],
            "contexts": self.scheduler.dump_admin_conf(),
        }
