"""Grant tables: controlled zero-copy shared memory (grant_table.c analog).

Reference: Xen grant tables (``xen/common/grant_table.c``, ~2.5k LoC)
let a domain *grant* specific frames of its memory to a specific peer —
the substrate for zero-copy I/O between isolation boundaries: blkfront
grants pages to blkback, netfront to netback. Key semantics preserved
here:

- a grant names (grantee, region, access) and yields a small integer
  *ref* the grantee uses to map;
- mapping is refcounted (``map_ref``/``unmap_ref``); the granter cannot
  end access while mappings exist (``gnttab_end_foreign_access`` "still
  in use" busy state);
- *transfer* moves ownership of a region outright (the page-transfer
  flavor used by early netfront);
- everything is revocable and auditable from the granter side.

TPU re-expression: the "frames" are byte ranges of named host
shared-memory segments (``multiprocessing.shared_memory``) — the same
pinned-host-buffer substrate the telemetry ledger and trace rings ride.
Data-plane tensors move over ICI inside XLA programs and never touch
this path; grants carry host-side staging buffers (checkpoint chunks,
telemetry pages, input shards) between the controller/agent processes
of one host.
"""

from __future__ import annotations

import dataclasses
from multiprocessing import shared_memory
from typing import Any

import numpy as np

from pbs_tpu.obs.lockprof import ProfiledLock

GRANT_INVALID = -1


class GrantError(Exception):
    pass


class GrantBusy(GrantError):
    """End-access/transfer attempted while mappings exist (the
    ``gnttab_end_foreign_access`` still-in-use state)."""


class GrantDenied(GrantError):
    """Mapper is not the grantee, or access mode exceeds the grant."""


class SharedRegion:
    """A named host shared-memory segment (the granter's 'frames').

    ``create=True`` allocates; otherwise attaches to an existing segment
    by name (what a peer process does after receiving a grant ref).
    """

    def __init__(self, name: str | None = None, size: int = 0,
                 create: bool = False):
        self._shm = shared_memory.SharedMemory(
            name=name, create=create, size=size if create else 0)
        self.name = self._shm.name
        self.size = self._shm.size

    def view(self, offset: int = 0, length: int | None = None,
             readonly: bool = False) -> np.ndarray:
        length = self.size - offset if length is None else length
        arr = np.frombuffer(self._shm.buf, dtype=np.uint8,
                            offset=offset, count=length)
        if readonly:
            arr = arr.view()
            arr.flags.writeable = False
        return arr

    def close(self) -> None:
        # Views into the buffer must be dropped by callers first.
        self._shm.close()

    def unlink(self) -> None:
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass


@dataclasses.dataclass
class GrantEntry:
    ref: int
    segment: str  # shared-memory segment name
    offset: int
    length: int
    grantee: str  # domain name allowed to map
    readonly: bool
    use_count: int = 0  # live mappings
    revoked: bool = False
    transferred_to: str | None = None

    def describe(self) -> dict[str, Any]:
        """Wire form a granter sends to the grantee (the grant ref plus
        enough to attach — Xen passes just the ref because the table
        itself is shared; ours rides the control plane)."""
        return {
            "ref": self.ref,
            "segment": self.segment,
            "offset": self.offset,
            "length": self.length,
            "readonly": self.readonly,
        }


class GrantTable:
    """One domain's grant table (``struct grant_table`` per domain)."""

    def __init__(self, owner: str):
        self.owner = owner
        self._entries: dict[int, GrantEntry] = {}
        self._next_ref = 0
        self._lock = ProfiledLock("grant_table")

    # -- granter side ----------------------------------------------------

    def grant_access(self, grantee: str, region: SharedRegion,
                     offset: int = 0, length: int | None = None,
                     readonly: bool = False) -> int:
        """gnttab_grant_foreign_access: allow ``grantee`` to map a byte
        range of ``region``. Returns the grant ref."""
        length = region.size - offset if length is None else length
        if offset < 0 or length <= 0 or offset + length > region.size:
            raise GrantError(
                f"range [{offset}, {offset + length}) outside segment "
                f"of {region.size} bytes")
        with self._lock:
            ref = self._next_ref
            self._next_ref += 1
            self._entries[ref] = GrantEntry(
                ref=ref, segment=region.name, offset=offset, length=length,
                grantee=grantee, readonly=readonly)
            return ref

    def end_access(self, ref: int, force: bool = False) -> None:
        """gnttab_end_foreign_access: revoke. Raises :class:`GrantBusy`
        while mapped unless forced (force mirrors the page-orphaning
        fallback — the mapping stays valid but the grant is dead)."""
        with self._lock:
            e = self._need(ref)
            if e.use_count > 0 and not force:
                raise GrantBusy(
                    f"grant {ref} has {e.use_count} live mappings")
            e.revoked = True

    def transfer(self, ref: int, new_owner: str) -> GrantEntry:
        """gnttab_transfer: move ownership outright. The entry is
        removed from this table; the region now belongs to
        ``new_owner`` (who should re-grant as needed)."""
        with self._lock:
            e = self._need(ref)
            if e.use_count > 0:
                raise GrantBusy(
                    f"grant {ref} has {e.use_count} live mappings")
            e.revoked = True
            e.transferred_to = new_owner
            del self._entries[ref]
            return e

    def entry(self, ref: int) -> GrantEntry:
        with self._lock:
            return self._need(ref)

    def active(self) -> list[GrantEntry]:
        with self._lock:
            return [e for e in self._entries.values() if not e.revoked]

    def _need(self, ref: int) -> GrantEntry:
        e = self._entries.get(ref)
        if e is None:
            raise GrantError(f"bad grant ref {ref}")
        return e

    # -- grantee side ----------------------------------------------------

    def map_ref(self, ref: int, as_domain: str,
                write: bool = False) -> "GrantMapping":
        """gnttab_map_grant_ref: validate and produce a mapping handle.
        The returned mapping attaches the shared segment (possibly in a
        different process via ``GrantEntry.describe()`` + ``map_grant``)."""
        with self._lock:
            e = self._need(ref)
            if e.revoked:
                raise GrantError(f"grant {ref} revoked")
            if e.grantee != as_domain:
                raise GrantDenied(
                    f"grant {ref} is for {e.grantee!r}, not {as_domain!r}")
            if write and e.readonly:
                raise GrantDenied(f"grant {ref} is read-only")
            e.use_count += 1
        try:
            return GrantMapping(self, e, write=write)
        except BaseException:
            # Attach failed (e.g. segment unlinked): no mapping exists
            # to unmap, so the refcount must not stay pinned or the
            # grant reads busy forever.
            self._unmap(ref)
            raise

    def _unmap(self, ref: int) -> None:
        with self._lock:
            e = self._entries.get(ref)
            if e is not None and e.use_count > 0:
                e.use_count -= 1


class GrantMapping:
    """A live mapping of a granted range (the map_track entry)."""

    def __init__(self, table: GrantTable, entry: GrantEntry, write: bool):
        self._table = table
        self._entry = entry
        self._write = write
        self._region = SharedRegion(name=entry.segment)
        self.data = self._region.view(
            entry.offset, entry.length, readonly=not write)

    def unmap(self) -> None:
        if self._table is not None:
            del self.data
            self._region.close()
            self._table._unmap(self._entry.ref)
            self._table = None

    def __enter__(self) -> "GrantMapping":
        return self

    def __exit__(self, *exc) -> None:
        self.unmap()


def map_grant(desc: dict, write: bool = False) -> tuple[SharedRegion, np.ndarray]:
    """Foreign-process attach from a wire-form grant description
    (``GrantEntry.describe()``): returns (region, view). The caller must
    ``region.close()`` when done. Refcounts live in the granter's table,
    so cross-process mappers report unmap over the control plane."""
    if write and desc.get("readonly"):
        raise GrantDenied("grant is read-only")
    region = SharedRegion(name=desc["segment"])
    view = region.view(desc["offset"], desc["length"],
                       readonly=not write)
    return region, view
