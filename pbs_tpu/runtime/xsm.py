"""Mandatory access control on control-plane ops (XSM/Flask analog).

Reference: Xen's XSM (``xen/xsm/``, ~13k LoC) interposes a pluggable
security module on every sensitive hypercall: the default ``dummy``
module allows everything (classic dom0-is-root), while FLASK enforces
label-based policy (subject label × operation class × target label →
allow/deny) compiled from policy rules. Hooks sit at the hypercall
dispatch layer (``do_domctl``/``do_sysctl`` entry), not inside the
subsystems.

Same shape here: :func:`xsm_check` is called at the control-plane
surfaces (agent ops, partition admission, store writes) with a subject
label, an operation name, and a target label. :class:`DummyPolicy`
allows all; :class:`LabelPolicy` evaluates explicit rules with a
configurable default. Labels live on jobs (``Job(label=...)``) and on
RPC peers (agents attach a subject to incoming ops).
"""

from __future__ import annotations

import dataclasses
import fnmatch

from pbs_tpu.obs.lockprof import ProfiledLock

#: The all-powerful subject (dom0 / system_u in FLASK terms).
SYSTEM = "system"


class XsmDenied(PermissionError):
    def __init__(self, subject: str, op: str, target: str | None):
        tgt = f" target={target!r}" if target is not None else ""
        super().__init__(f"xsm: {subject!r} denied {op!r}{tgt}")
        self.subject = subject
        self.op = op
        self.target = target


class DummyPolicy:
    """Allow-everything (the XSM dummy module)."""

    name = "dummy"

    def check(self, subject: str, op: str, target: str | None) -> bool:
        return True


@dataclasses.dataclass(frozen=True)
class Rule:
    """allow/deny (subject-glob, op-glob, target-glob). First match
    wins, like an access-vector lookup."""

    subject: str
    op: str
    target: str  # a None target matches as "" (so "*" covers it)
    allow: bool

    def matches(self, subject: str, op: str, target: str | None) -> bool:
        return (fnmatch.fnmatchcase(subject, self.subject)
                and fnmatch.fnmatchcase(op, self.op)
                and fnmatch.fnmatchcase(target or "", self.target))


class LabelPolicy:
    """FLASK-style explicit rules over labels.

    ``default_allow=False`` is enforcing mode (deny anything unmatched);
    True is permissive-with-denials (useful for staged rollout). The
    ``system`` subject always passes — Xen likewise never locks out the
    hypervisor's own internal ops.
    """

    name = "label"

    def __init__(self, rules: list[Rule] | None = None,
                 default_allow: bool = False):
        self.rules = list(rules or [])
        self.default_allow = default_allow
        self.denials: list[tuple[str, str, str | None]] = []  # AVC log

    def allow(self, subject: str, op: str = "*", target: str = "*") -> "LabelPolicy":
        self.rules.append(Rule(subject, op, target, True))
        return self

    def deny(self, subject: str, op: str = "*", target: str = "*") -> "LabelPolicy":
        self.rules.append(Rule(subject, op, target, False))
        return self

    def check(self, subject: str, op: str, target: str | None) -> bool:
        if subject == SYSTEM:
            return True
        for r in self.rules:
            if r.matches(subject, op, target):
                if not r.allow:
                    self.denials.append((subject, op, target))
                return r.allow
        if not self.default_allow:
            self.denials.append((subject, op, target))
        return self.default_allow


_lock = ProfiledLock("xsm_policy")
_policy = DummyPolicy()


def set_policy(policy) -> None:
    """Install the active security module (boot-time XSM selection)."""
    global _policy
    with _lock:
        _policy = policy


def get_policy():
    return _policy


def xsm_check(subject: str, op: str, target: str | None = None) -> None:
    """Hook: raise :class:`XsmDenied` unless policy allows. Call sites
    mirror Xen's — at the operation dispatch surface, before any state
    changes."""
    if not _policy.check(subject, op, target):
        raise XsmDenied(subject, op, target)
