"""Shared immutable weights across tenants (the mem-sharing analog).

Reference: Xen memory sharing (``tools/memshr``,
``xen/arch/x86/mm/mem_sharing.c``) deduplicates identical pages across
domains down to one physical page, copy-on-write on modification —
density for fleets of near-identical guests. The TPU fleet equivalent
is sharper: serving tenants of the SAME model each carry gigabytes of
identical weights, and ``jax.Array`` is immutable, so N jobs can
reference ONE device copy with no CoW machinery at all — a write is
impossible by construction. (Training jobs produce new arrays every
step; they are exactly the pages mem-sharing would break anyway, and
simply don't share.)

The registry refcounts named weight sets and charges their HBM ONCE
against a dedicated account, so the MemoryManager's admission math
prices a second same-model tenant at its PRIVATE state only (KV
cache, cursors) — the density win is visible to the claim system, not
just physically true.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from pbs_tpu.obs.lockprof import ProfiledLock
from pbs_tpu.obs.perfc import perfc
from pbs_tpu.runtime.memory import nbytes_of

#: Account-name prefix for shared sets (one account per set).
SHARED_PREFIX = "shared:"

# Every leaf of every PUBLISHED set, process-wide, keyed by id — the
# pager consults this to skip shared leaves (evicting a refcounted set
# through one tenant and restoring a private copy would break the
# dedup). The map holds STRONG references with a per-leaf count, so a
# registered id can never be recycled onto an unrelated object while
# it is in the map (id() reuse after gc was a real bug here).
_shared_leaves: dict[int, tuple[Any, int]] = {}
_shared_ids_lock = ProfiledLock("shared_leaves")


def is_shared_leaf(leaf: Any) -> bool:
    """True when ``leaf`` belongs to a currently-published shared
    weight set (any registry in this process)."""
    ent = _shared_leaves.get(id(leaf))
    return ent is not None and ent[0] is leaf


def _register_leaves(params: Any) -> None:
    import jax

    with _shared_ids_lock:
        for leaf in jax.tree_util.tree_leaves(params):
            ent = _shared_leaves.get(id(leaf))
            _shared_leaves[id(leaf)] = (
                leaf, (ent[1] + 1) if ent is not None else 1)


def _unregister_leaves(params: Any) -> None:
    import jax

    with _shared_ids_lock:
        for leaf in jax.tree_util.tree_leaves(params):
            ent = _shared_leaves.get(id(leaf))
            if ent is None:
                continue
            if ent[1] <= 1:
                del _shared_leaves[id(leaf)]
            else:
                _shared_leaves[id(leaf)] = (leaf, ent[1] - 1)


@dataclasses.dataclass
class SharedWeights:
    """Handle to one published weight set."""

    name: str
    params: Any  # immutable pytree of jax arrays
    nbytes: int
    refs: int = 0


class WeightsRegistry:
    """Refcounted publication of immutable weight sets.

    With a :class:`MemoryManager`, the set's bytes are claimed once
    under ``shared:<name>`` at publish and released when the last
    reference drops — N sharers never multiply the bill.
    """

    def __init__(self, memory=None):
        self.memory = memory
        self._sets: dict[str, SharedWeights] = {}
        self._lock = ProfiledLock("weights_registry")

    def publish(self, name: str, params: Any) -> SharedWeights:
        """Register a weight set (claims its HBM once). Publishing an
        existing name is an error — immutability is the whole safety
        story, so sets are never silently replaced under readers."""
        with self._lock:
            if name in self._sets:
                raise ValueError(f"weight set {name!r} already published")
            nbytes = nbytes_of(params)
            if self.memory is not None:
                self.memory.open_account(SHARED_PREFIX + name)
                try:
                    self.memory.claim_or_balloon(SHARED_PREFIX + name,
                                                 nbytes)
                except BaseException:
                    self.memory.close_account(SHARED_PREFIX + name)
                    raise
            sw = SharedWeights(name, params, nbytes)
            self._sets[name] = sw
            _register_leaves(params)
            perfc.incr("weights_published")
            return sw

    def acquire(self, name: str) -> Any:
        """Take a reference; returns the params pytree. Tenants hold
        the SAME arrays — zero copies, zero extra HBM."""
        with self._lock:
            sw = self._sets[name]
            sw.refs += 1
            perfc.incr("weights_acquired")
            return sw.params

    def release(self, name: str) -> int:
        """Drop a reference; at zero the set unpublishes and its HBM
        account closes. Returns remaining refs. Releasing a set with
        no outstanding references raises — an underflow means some
        tenant double-released while another may still hold the
        arrays, and silently closing the account would free HBM the
        ledger still needs to model (review finding)."""
        with self._lock:
            sw = self._sets[name]
            if sw.refs <= 0:
                raise ValueError(
                    f"release of {name!r} with no outstanding "
                    "references (double-release?)")
            sw.refs -= 1
            if sw.refs == 0:
                del self._sets[name]
                _unregister_leaves(sw.params)
                if self.memory is not None:
                    self.memory.close_account(SHARED_PREFIX + name)
                perfc.incr("weights_unpublished")
            return sw.refs

    def unpublish(self, name: str) -> None:
        """Publisher-side teardown of a set nobody acquired (refs must
        be zero — live sharers pin the set)."""
        with self._lock:
            sw = self._sets[name]
            if sw.refs > 0:
                raise ValueError(
                    f"cannot unpublish {name!r}: {sw.refs} live "
                    "reference(s)")
            del self._sets[name]
            _unregister_leaves(sw.params)
            if self.memory is not None:
                self.memory.close_account(SHARED_PREFIX + name)
            perfc.incr("weights_unpublished")

    def refs(self, name: str) -> int:
        with self._lock:
            sw = self._sets.get(name)
            return sw.refs if sw else 0

    def saved_bytes(self) -> int:
        """The mem-sharing headline: bytes deduplicated = what the
        CURRENT sharers would have cost privately, minus the one copy."""
        with self._lock:
            return sum(max(0, sw.refs - 1) * sw.nbytes
                       for sw in self._sets.values())

    def dump(self) -> dict:
        with self._lock:
            return {
                "sets": {
                    n: {"nbytes": sw.nbytes, "refs": sw.refs}
                    for n, sw in self._sets.items()
                },
                "saved_bytes": sum(
                    max(0, sw.refs - 1) * sw.nbytes
                    for sw in self._sets.values()),
            }
