"""Init-time perf self-test of the telemetry hot paths (x86_tests.c).

The reference ships microbenchmarks of its own hot path wired to boot:
``drivers/perfctr/x86_tests.c:1-333`` times rdpmc/rdmsr/cli-sti cycles
at module init and prints the costs, so a driver regression that makes
counter reads expensive is caught the day it lands, not when a guest
notices. Same contract here for the paths every quantum touches:

- ledger ``resume``/``suspend`` (the writer's context-switch cost),
- ledger ``snapshot`` (the monitor's lock-free read),
- trace ``emit`` (per-event record cost),
- native vs Python-fallback variants when the C++ runtime is loaded.

Thresholds are deliberately loose (order-of-magnitude canaries, not
percent-level watchdogs): the failure mode being guarded is an
accidental O(slots) scan or a lock slipping into the per-quantum path,
which shows up as 10-100x, never 1.2x. ``pbst selftest`` runs it on
demand; tests assert the canary passes in CI.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from pbs_tpu.obs.trace import TraceBuffer
from pbs_tpu.telemetry.counters import NUM_COUNTERS
from pbs_tpu.telemetry.ledger import Ledger

#: ns/op ceilings — an order of magnitude above healthy, far below broken.
DEFAULT_THRESHOLDS_NS = {
    "ledger_resume_suspend": 500_000.0,  # healthy: ~5-40 µs (py), <1 µs (nat)
    "ledger_snapshot": 250_000.0,  # healthy: ~2-20 µs (py), <1 µs (nat)
    "trace_emit": 250_000.0,  # healthy: ~1-10 µs
    "doorbell_send_take": 250_000.0,  # healthy: ~1-10 µs
}


@dataclasses.dataclass
class CanaryResult:
    name: str
    variant: str  # 'python' | 'native'
    n_ops: int
    ns_per_op: float
    threshold_ns: float

    @property
    def ok(self) -> bool:
        return self.ns_per_op <= self.threshold_ns

    def row(self) -> str:
        state = "ok" if self.ok else "FAIL"
        return (f"{self.name:<24} {self.variant:<8} "
                f"{self.ns_per_op:>12.0f} ns/op  "
                f"(limit {self.threshold_ns:>9.0f})  {state}")


def _bench(fn, n: int) -> float:
    fn()  # warm (allocations, first-touch)
    t0 = time.perf_counter_ns()
    for _ in range(n):
        fn()
    return (time.perf_counter_ns() - t0) / n


def _ledger_canaries(native: bool, thresholds, n: int) -> list[CanaryResult]:
    variant = "native" if native else "python"
    try:
        led = Ledger(4, native=native)
    except RuntimeError:
        return []  # native requested but unavailable on this host
    if native and led._nat is None:
        return []
    deltas = np.arange(NUM_COUNTERS, dtype="<u8")
    out = []

    def cycle():
        led.resume(1, 12345)
        led.suspend(1, deltas)

    out.append(CanaryResult(
        "ledger_resume_suspend", variant, n, _bench(cycle, n),
        thresholds["ledger_resume_suspend"]))
    out.append(CanaryResult(
        "ledger_snapshot", variant, n,
        _bench(lambda: led.snapshot(1), n),
        thresholds["ledger_snapshot"]))
    return out


def run_selftest(thresholds: dict[str, float] | None = None,
                 n: int = 2000) -> list[CanaryResult]:
    """Run all canaries; returns per-path results (both byte-compatible
    ledger variants when the native runtime is present)."""
    th = dict(DEFAULT_THRESHOLDS_NS)
    th.update(thresholds or {})
    results: list[CanaryResult] = []
    results += _ledger_canaries(native=False, thresholds=th, n=n)
    results += _ledger_canaries(native=True, thresholds=th, n=n)

    tb = TraceBuffer()
    results.append(CanaryResult(
        "trace_emit", "native" if tb._nat is not None else "python", n,
        _bench(lambda: tb.emit(1, 7, 42, 43), n), th["trace_emit"]))

    from pbs_tpu.runtime.doorbell import Doorbell

    db = Doorbell(n_channels=8)

    def ring():
        db.send(3)
        db.take(3)

    results.append(CanaryResult(
        "doorbell_send_take",
        "native" if db._nat is not None else "python", n,
        _bench(ring, n), th["doorbell_send_take"]))
    return results


def selftest_ok(results: list[CanaryResult] | None = None) -> bool:
    return all(r.ok for r in (results if results is not None
                              else run_selftest()))
