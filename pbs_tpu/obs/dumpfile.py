"""Observability dump artifact: the sysctl-read seam for CLIs.

Reference: ``xenperf`` and ``xenlockprof`` read hypervisor-internal
counters through sysctl hypercalls (``tools/misc/xenperf.c``,
``tools/misc/xenlockprof.c``). Our CLIs attach to artifacts rather
than a live daemon (same decoupling as xentop over shared pages), so
the producing process publishes a JSON snapshot of its software
counters, lock profile, and effective boot params; ``pbst perf`` /
``pbst lockprof`` / ``pbst params`` format it.
"""

from __future__ import annotations

import json
import os

from pbs_tpu.obs import lockdep, lockprof
from pbs_tpu.obs.perfc import perfc
from pbs_tpu.utils import params


def write_obs_dump(path: str) -> dict:
    """Snapshot perfc + lockprof + lockdep + params to ``path``
    (atomic rename)."""
    snap = {
        "perfc": perfc.dump(),
        "lockprof": lockprof.dump(),
        "lockdep": lockdep.dump(),
        "params": params.dump(),
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(snap, f)
    os.replace(tmp, path)
    return snap


def read_obs_dump(path: str) -> dict:
    with open(path) as f:
        return json.load(f)
