"""Trace buffers: lockless event rings + taxonomy (xentrace analog).

Reference: per-CPU lockless trace rings in xen-heap pages
(``xen-4.2.1/xen/common/trace.c:53-120``, producers behind
``tb_init_done``), a structured event taxonomy (``TRC_SCHED_*`` etc.,
``xen/include/public/trace.h:35-74``), drained by the ``xentrace`` CLI
and post-processed by ``xentrace_format``; ``xenbaked``/``xenmon``
digest scheduler events into per-domain histories.

Here: one ring per executor over a flat u64 buffer (native SPSC ring in
``native/pbst_runtime.cc`` when available, Python fallback otherwise),
records of (timestamp, event, 6 args), a lost-record counter instead of
blocking, and host-side formatting/digestion in ``pbs_tpu.cli``.

**Hot-path contract** (``pbst perf`` pins it in both modes,
docs/PERF.md): ``emit`` writes the whole record with ONE
``struct.pack_into`` (no per-word store loop, nothing allocated per
event) — or one sub-µs vectorcall when the native runtime is loaded;
``emit_many``/``consume``/``peek`` move records in at most two
contiguous slice copies each (wrap-aware; one
``pbst_trace_emit_many``/``pbst_trace_consume`` C call when native);
and producers with bursty event streams stage through
:class:`EmitBatch` so N events cost one batched ring write instead of
N scalar ones. Native and Python paths are byte-identical — same ring
bytes, same drop counters (tests/test_native_fastpath.py).

**Batched-writer concurrency contract** (mirrors the ledger's): the
pure-Python vectorized producer paths (``emit_many``, and any
``EmitBatch`` over a non-native ring) are plain slice stores + a
header store with no fences — in-process SPSC is always safe (stores
are program-ordered under the GIL), and a cross-process consumer
attached to a file-backed ring is safe on TSO hosts (x86: the head
store cannot pass the record stores). A cross-process producer
needing release semantics on weaker memory models must use the native
paths (scalar ``emit`` or ``emit_many``, whose head store is an
atomic release).
"""

from __future__ import annotations

import enum
import struct

import numpy as np

from pbs_tpu import knobs
from pbs_tpu.utils.params import integer_param

TRACE_HEADER_WORDS = 4
TRACE_REC_WORDS = 8

# EmitBatch staging watermarks, declared in the knob registry
# (obs.trace.emit_batch_*): how many records one producer stages, and
# the staged-timestamp span that forces a flush.
EMIT_BATCH_CAPACITY = knobs.default("obs.trace.emit_batch_capacity")
EMIT_BATCH_FLUSH_NS = knobs.default("obs.trace.emit_batch_flush_ns")

_U64_MASK = 2**64 - 1

#: Pack formats for a record prefix of 2 + k words (k = 0..6 args):
#: one C-level struct.pack_into per staged/emitted record replaces the
#: per-word memoryview store loop — the "sub-µs emit" path. The
#: out-of-range fallback masks args exactly like the old store loop.
_PACK_FMTS = tuple("<" + "Q" * (2 + k) for k in range(7))
#: Zero padding for the unwritten tail words of a short record.
_ZERO_TAIL = tuple(bytes((6 - k) * 8) for k in range(7))

# ``tbuf_size=`` boot param analog (xen/common/trace.c): default ring
# capacity in records for rings whose creator doesn't size them.
_tbuf_size = integer_param("tbuf_size", 4096)


class Ev(enum.IntEnum):
    """Event taxonomy (TRC_* analog, public/trace.h:35-74). The top
    byte is the subsystem class, like TRC_SCHED/TRC_MEM/..."""

    # scheduler class (0x01xx)
    SCHED_PICK = 0x0101  # args: ctx_slot, quantum_ns
    SCHED_DESCHED = 0x0102  # args: ctx_slot, ran_ns, credit_mu
    SCHED_WAKE = 0x0103  # args: ctx_slot, boosted
    SCHED_SLEEP = 0x0104  # args: ctx_slot
    SCHED_STEAL = 0x0105  # args: ctx_slot, from_ex, to_ex
    SCHED_PARK = 0x0106  # args: ctx_slot
    SCHED_UNPARK = 0x0107  # args: ctx_slot
    SCHED_ACCT = 0x0108  # args: acct_count, weight_total
    # feedback class (0x02xx)
    FB_TICK = 0x0201  # args: job_slot, stall_rate_x1000, tslice_us
    FB_GROW = 0x0202  # args: job_slot, new_tslice_us
    FB_SHRINK = 0x0203  # args: job_slot, new_tslice_us
    FB_RESET = 0x0204  # args: job_slot
    # job lifecycle (0x03xx)
    JOB_ADD = 0x0301  # args: job_slot, n_contexts, weight
    JOB_REMOVE = 0x0302
    JOB_DONE = 0x0303
    JOB_FAILED = 0x0304  # args: ctx_slot
    # checkpoint (0x04xx)
    CKPT_BEGIN = 0x0401  # args: job_slot, step
    CKPT_END = 0x0402  # args: job_slot, bytes, dur_ns
    # contention channel (0x05xx) — the vcrd_op analog
    CONTENTION = 0x0501  # args: job_slot, wait_ns, events
    # serving gateway (0x06xx) — the front-door class (docs/GATEWAY.md);
    # tenant_slot is the gateway's stable per-tenant index, cls is the
    # SLO-class index (0=interactive, 1=batch)
    GW_ADMIT = 0x0601  # args: tenant_slot, cls, cost, queue_depth
    GW_SHED = 0x0602  # args: tenant_slot, cls, reason_code, retry_after_ns
    GW_DISPATCH = 0x0603  # args: tenant_slot, cls, backend_slot, qdelay_ns
    GW_COMPLETE = 0x0604  # args: tenant_slot, cls, backend_slot, service_ns
    GW_REQUEUE = 0x0605  # args: tenant_slot, cls, backend_slot
    GW_QDELAY = 0x0606  # args: cls, p50_ns, p99_ns, shed_ppm
    # telemetry sampling (0x07xx) — the i-mode overflow path
    # (telemetry/sampler.py): one record per threshold crossing, staged
    # through an EmitBatch so a quantum's firings cost one ring write
    TELEM_OVERFLOW = 0x0701  # args: ledger_slot, sample_id, counter, value
    # request spans (0x08xx) — the causal request timeline through the
    # serving tier (docs/TRACING.md; pbs_tpu.obs.spans). ``span`` is
    # the recorder-interned id of the gateway rid (stitching key across
    # federated members), ``member`` the interned gateway name. All
    # emitted through the SpanRecorder's EmitBatch, never scalar.
    SPAN_ADMIT = 0x0801  # args: span, tenant_slot, cls, cost, member
    SPAN_SHED = 0x0802  # args: tenant_slot, cls, reason_code, member
    SPAN_ENQUEUE = 0x0803  # args: span, tenant_slot, cls, member
    SPAN_DISPATCH = 0x0804  # args: span, backend_slot, qdelay_ns,
    #                               deficit_x1000, member
    SPAN_EXEC = 0x0805  # args: span, backend_slot, member
    SPAN_COMPLETE = 0x0806  # args: span, backend_slot, service_ns,
    #                               latency_ns, member
    SPAN_REQUEUE = 0x0807  # args: span, backend_slot, member
    SPAN_HANDOFF = 0x0808  # args: span, from_member, to_member
    SPAN_RECOVER = 0x0809  # args: span, member, generation — crash
    #   recovery re-anchored this request's chain (docs/DURABILITY.md):
    #   legal from ANY state (including as the chain's first record
    #   when the pre-crash span records died in a staging batch) and
    #   resets the chain to QUEUED — recovery requeues everything it
    #   recovers, and a COMPLETE whose frame never committed may
    #   legitimately be followed by a re-execution.
    # autopilot decisions (0x09xx) — the self-tuning loop's audit trail
    # (docs/AUTOPILOT.md; pbs_tpu.autopilot). Emitted through the
    # shared SpanRecorder ring so every decision lands in emission
    # order next to the request chains it affected; the assembler
    # ignores the class, chain validation is untouched.
    AP_PROPOSE = 0x0901  # args: cand_score_x1e6, live_score_x1e6,
    #                            margin_x1e6 (i64 two's complement —
    #                            scores can be negative), injected
    AP_CANARY = 0x0902  # args: n_members, guard_window_ns
    AP_PROMOTE = 0x0903  # args: n_members, reserved
    AP_ROLLBACK = 0x0904  # args: reason_code, max_burn_x1000


class TraceBuffer:
    """One SPSC ring. Producer: an executor. Consumer: a monitor."""

    def __init__(self, capacity: int | None = None, buf=None,
                 native: bool | str | None = None, _attach: bool = False):
        # ``native``: None auto-detects, True requires the C library,
        # False pins the pure-Python paths, "ctypes" pins the ctypes
        # binding tier (native minus the fastcall accelerator — the
        # tier a host without Python.h runs; tests/benches use it).
        self.capacity = capacity = (
            capacity if capacity is not None else _tbuf_size.value)
        nwords = TRACE_HEADER_WORDS + capacity * TRACE_REC_WORDS
        if buf is None:
            buf = bytearray(nwords * 8)
        self._arr = np.frombuffer(memoryview(buf), dtype="<u8", count=nwords)
        # Cached header/word views: plain-int loads and stores with no
        # numpy scalar boxing on the per-event path. Native-endian 'Q'
        # over the '<u8' layout — this framework targets little-endian
        # hosts (the native library shares the same assumption).
        words = memoryview(buf)[: nwords * 8].cast("B").cast("Q")
        self._hdr = words[:TRACE_HEADER_WORDS]
        self._words = words
        # Byte view for struct.pack_into: the pure-Python emit writes
        # the whole record in one C call, no per-word store loop.
        self._bytes = memoryview(buf)[: nwords * 8].cast("B")
        self._nat = None
        self._ptr = None
        self._fc = None
        self._addr = 0
        if native is not False:
            from pbs_tpu.runtime import native as native_mod

            lib = native_mod.load()
            if lib is not None:
                self._nat = lib
                self._ptr = native_mod.as_u64p(self._arr)
                # Fastcall tier (native/pbst_fastcall.cc): same C entry
                # points, ~7x lower call overhead than ctypes. The
                # address is cached once — .ctypes.data costs µs per
                # access. native="ctypes" pins the ctypes tier (tests).
                if native != "ctypes":
                    self._fc = native_mod.fastcall()
                    self._addr = self._arr.ctypes.data
            elif native is True:
                raise RuntimeError("native runtime requested but unavailable")
        if _attach:
            return  # consumer attach: the producer owns the header
        if self._nat is not None:
            self._nat.pbst_trace_init(self._ptr, capacity)
        else:
            self._arr[0] = 0
            self._arr[1] = 0
            self._arr[2] = capacity
            self._arr[3] = 0

    @classmethod
    def file_backed(cls, path: str, capacity: int | None = None,
                    native: bool | str | None = None,
                    attach: bool = False) -> "TraceBuffer":
        """Ring over a shared mmap — xenbaked's view of the hypervisor
        trace pages (``tools/xenmon/xenbaked.c`` maps the per-CPU rings
        dom0-side). ``attach=True`` joins an existing producer's ring as
        the (single) consumer: the header is left alone and capacity
        comes from the file. The mapping is read-write either way — the
        consumer must advance the shared tail word."""
        import mmap
        import os

        if attach:
            fd = os.open(path, os.O_RDWR)
            try:
                mm = mmap.mmap(fd, os.fstat(fd).st_size)
            finally:
                os.close(fd)
            cap = int(np.frombuffer(mm, dtype="<u8", count=3)[2])
            tb = cls(cap, buf=mm, native=native, _attach=True)
        else:
            capacity = capacity if capacity is not None else _tbuf_size.value
            nbytes = (TRACE_HEADER_WORDS + capacity * TRACE_REC_WORDS) * 8
            fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
            try:
                if os.fstat(fd).st_size < nbytes:
                    os.ftruncate(fd, nbytes)
                mm = mmap.mmap(fd, nbytes)
            finally:
                os.close(fd)
            tb = cls(capacity, buf=mm, native=native)
        tb._mmap = mm
        return tb

    # -- producer --------------------------------------------------------

    def emit(self, ts_ns: int, event: int, *args: int) -> bool:
        fc = self._fc
        if fc is not None:
            # Sub-µs native emit: one vectorcall, args masked in C.
            if len(args) > 6:
                args = args[:6]
            return fc.trace_emit(self._addr, ts_ns, event, *args)
        if self._nat is not None:
            a = [int(x) & _U64_MASK for x in args[:6]]
            a += [0] * (6 - len(a))
            return bool(
                self._nat.pbst_trace_emit(self._ptr, ts_ns, int(event), *a))
        hdr = self._hdr
        head = hdr[0]
        cap = self.capacity
        if head - hdr[1] >= cap:
            hdr[3] += 1
            return False
        off = (TRACE_HEADER_WORDS + (head % cap) * TRACE_REC_WORDS) * 8
        n = len(args)
        if n > 6:
            args = args[:6]
            n = 6
        b = self._bytes
        try:
            # Fast path: every field already a 0..2^64-1 int — one C
            # pack writes the whole record prefix.
            struct.pack_into(_PACK_FMTS[n], b, off, ts_ns, event, *args)
        except struct.error:
            # Every field masks to two's complement — including
            # ts_ns/event, matching the native tiers (a negative
            # clock-skew timestamp must not raise on one tier and
            # record on another).
            struct.pack_into(
                _PACK_FMTS[n], b, off, int(ts_ns) & _U64_MASK,
                int(event) & _U64_MASK,
                *[int(x) & _U64_MASK for x in args])
        if n < 6:
            b[off + (2 + n) * 8:off + TRACE_REC_WORDS * 8] = _ZERO_TAIL[n]
        hdr[0] = head + 1
        return True

    def emit_many(self, recs: np.ndarray) -> int:
        """Batched emit of an ``(n, 8)`` u64 record array in at most two
        contiguous slice copies (wrap-aware). Returns the number of
        records written; records that don't fit are dropped tail-first
        with the lost counter charged — exactly the per-record drop
        semantics of ``n`` scalar :meth:`emit` calls. See the module
        docstring for the batched-writer concurrency contract."""
        recs = np.ascontiguousarray(recs, dtype="<u8")
        if recs.ndim != 2 or recs.shape[1] != TRACE_REC_WORDS:
            raise ValueError(
                f"emit_many wants (n, {TRACE_REC_WORDS}) u64 records, "
                f"got shape {recs.shape}")
        n = recs.shape[0]
        if n == 0:
            return 0
        if self._fc is not None:
            return self._fc.trace_emit_many(self._addr, recs, n)
        if self._nat is not None:
            from pbs_tpu.runtime import native as native_mod

            return int(self._nat.pbst_trace_emit_many(
                self._ptr, native_mod.as_u64p(recs.reshape(-1)), n))
        hdr = self._hdr
        head, tail, cap = hdr[0], hdr[1], self.capacity
        space = cap - (head - tail)
        k = n if n <= space else space
        if k < n:
            hdr[3] += n - k
        if k == 0:
            return 0
        flat = recs.reshape(-1)
        arr = self._arr
        start = head % cap
        k1 = min(k, cap - start)
        off = TRACE_HEADER_WORDS + start * TRACE_REC_WORDS
        w = TRACE_REC_WORDS
        arr[off:off + k1 * w] = flat[:k1 * w]
        if k > k1:
            arr[TRACE_HEADER_WORDS:TRACE_HEADER_WORDS + (k - k1) * w] = (
                flat[k1 * w:k * w])
        hdr[0] = head + k
        return k

    # -- consumer --------------------------------------------------------

    def _copy_out(self, first: int, n: int) -> np.ndarray:
        """Wrap-aware bulk copy of records [first, first+n) into a fresh
        (n, 8) array — one or two contiguous slices, no per-record loop."""
        out = np.empty((n, TRACE_REC_WORDS), dtype="<u8")
        if n:
            flat = out.reshape(-1)
            arr = self._arr
            cap = self.capacity
            start = first % cap
            k1 = min(n, cap - start)
            off = TRACE_HEADER_WORDS + start * TRACE_REC_WORDS
            w = TRACE_REC_WORDS
            flat[:k1 * w] = arr[off:off + k1 * w]
            if n > k1:
                flat[k1 * w:] = arr[
                    TRACE_HEADER_WORDS:TRACE_HEADER_WORDS + (n - k1) * w]
        return out

    def consume(self, max_records: int = 1024) -> np.ndarray:
        """(n, 8) u64 array of drained records."""
        if self._fc is not None:
            out = np.empty(max_records * TRACE_REC_WORDS, dtype="<u8")
            n = self._fc.trace_consume(self._addr, out, max_records)
            return out[: n * TRACE_REC_WORDS].reshape(n, TRACE_REC_WORDS)
        if self._nat is not None:
            from pbs_tpu.runtime import native as native_mod

            out = np.empty(max_records * TRACE_REC_WORDS, dtype="<u8")
            n = self._nat.pbst_trace_consume(
                self._ptr, native_mod.as_u64p(out), max_records)
            return out[: n * TRACE_REC_WORDS].reshape(n, TRACE_REC_WORDS)
        hdr = self._hdr
        tail = hdr[1]
        n = min(hdr[0] - tail, max_records)
        recs = self._copy_out(tail, n)
        if n:
            hdr[1] = tail + n
        return recs

    def peek(self, max_records: int = 1024) -> np.ndarray:
        """Last ``max_records`` undrained records WITHOUT consuming them
        — postmortem readers (crash dumps) must not steal records from an
        attached live consumer. Reads the shared header words directly
        (same layout for the native ring), so it also works on a ring the
        native library owns; safe in-process where the producer is
        quiescent or slow relative to the copy."""
        hdr = self._hdr
        head, tail = hdr[0], hdr[1]
        avail = head - tail
        n = min(avail, max_records)
        return self._copy_out(tail + (avail - n), n)  # newest n records

    @property
    def lost(self) -> int:
        if self._nat is not None:
            return int(self._nat.pbst_trace_lost(self._ptr))
        return self._hdr[3]


class EmitBatch:
    """Per-producer staging buffer over one ring: N events become one
    wrap-aware ``emit_many`` instead of N scalar emits.

    Flush happens on a **size watermark** (the staging buffer fills) or
    a **time watermark** (the staged span of event timestamps exceeds
    ``flush_ns`` — timestamps, not wall time, so virtual-clock runs stay
    deterministic), or explicitly via :meth:`flush` (the partition's
    drain/peek paths flush before reading so batched records are never
    invisible to an in-process consumer).

    NOT thread-safe: one batch per producer thread, and only where that
    producer owns the ring (the SPSC contract). Producers needing
    cross-thread ordering keep scalar ``TraceBuffer.emit`` — a staged
    record does not reach the ring until flush, so two threads batching
    into one ring would interleave at flush granularity, not emit order.
    """

    __slots__ = ("ring", "capacity", "flush_ns", "_bytes", "_buf",
                 "_bufp", "_fc_flush", "_n", "_t0", "emitted",
                 "flushes")

    def __init__(self, ring: TraceBuffer, capacity: int = EMIT_BATCH_CAPACITY,
                 flush_ns: int = EMIT_BATCH_FLUSH_NS):
        if capacity <= 0:
            raise ValueError("EmitBatch capacity must be > 0")
        self.ring = ring
        self.capacity = int(capacity)
        self.flush_ns = int(flush_ns)
        # Staging block: a bytearray written by struct.pack_into (one C
        # call per staged record) with a (capacity, 8) u64 numpy view
        # over the same bytes for the flush.
        self._bytes = bytearray(self.capacity * TRACE_REC_WORDS * 8)
        self._buf = np.frombuffer(self._bytes, dtype="<u8").reshape(
            self.capacity, TRACE_REC_WORDS)
        # Precomputed staging pointers: when the ring is native, flush
        # is ONE C call with no per-flush pointer marshalling.
        self._bufp = None
        self._fc_flush = None
        if ring._fc is not None:
            self._fc_flush = (ring._fc.trace_emit_many, ring._addr,
                              self._buf.ctypes.data)
        elif ring._nat is not None:
            from pbs_tpu.runtime import native as native_mod

            self._bufp = native_mod.as_u64p(self._buf.reshape(-1))
        self._n = 0
        self._t0 = -1  # ts of the oldest staged record; -1 = empty
        self.emitted = 0
        self.flushes = 0

    def emit(self, ts_ns: int, event: int, *args: int) -> None:
        off = self._n * (TRACE_REC_WORDS * 8)
        n = len(args)
        if n > 6:
            args = args[:6]
            n = 6
        b = self._bytes
        try:
            struct.pack_into(_PACK_FMTS[n], b, off, ts_ns, event, *args)
        except struct.error:
            struct.pack_into(
                _PACK_FMTS[n], b, off, int(ts_ns) & _U64_MASK,
                int(event) & _U64_MASK,
                *[int(x) & _U64_MASK for x in args])
        if n < 6:
            b[off + (2 + n) * 8:off + TRACE_REC_WORDS * 8] = _ZERO_TAIL[n]
        self._n += 1
        ts_ns = int(ts_ns)
        if self._t0 < 0:
            self._t0 = ts_ns
        if self._n >= self.capacity or ts_ns - self._t0 >= self.flush_ns:
            self.flush()

    def pending(self) -> int:
        return self._n

    def drop_pending(self) -> int:
        """Discard staged records WITHOUT writing them — the kill-9
        model (gateway/chaos.py): records staged in a dead process's
        batch never reached the ring and must not leak into the
        recovered process's stream. Returns the count dropped."""
        n, self._n = self._n, 0
        self._t0 = -1
        return n

    def flush(self) -> int:
        """Push staged records to the ring; returns records written
        (staged minus any the full ring dropped). One
        ``pbst_trace_emit_many`` C call when the ring is native."""
        n, self._n = self._n, 0
        self._t0 = -1
        if not n:
            return 0
        self.flushes += 1
        if self._fc_flush is not None:
            f, ring_addr, buf_addr = self._fc_flush
            written = f(ring_addr, buf_addr, n)
        elif self._bufp is not None:
            ring = self.ring
            written = int(ring._nat.pbst_trace_emit_many(
                ring._ptr, self._bufp, n))
        else:
            written = self.ring.emit_many(self._buf[:n])
        self.emitted += written
        return written


def merge_records(chunks: list[np.ndarray]) -> np.ndarray:
    """Merge per-ring record batches into one time-sorted stream (the
    xentrace multi-CPU merge). Stable sort keeps same-timestamp records
    in ring order."""
    chunks = [c for c in chunks if len(c)]
    if not chunks:
        return np.empty((0, TRACE_REC_WORDS), dtype="<u8")
    allr = np.concatenate(chunks, axis=0)
    return allr[np.argsort(allr[:, 0], kind="stable")]


def format_records(recs: np.ndarray) -> list[str]:
    """xentrace_format analog: human-readable lines."""
    out = []
    # tolist() converts the whole batch to Python ints in one C pass —
    # per-element numpy scalar boxing dominates the scalar version.
    for ts, ev, *args in np.asarray(recs).tolist():
        try:
            name = Ev(ev).name
        except ValueError:
            name = f"0x{ev:04x}"
        out.append(f"[{ts / 1e9:.6f}] {name} {' '.join(map(str, args))}")
    return out


def chrome_trace(recs: np.ndarray, labels: dict[int, str] | None = None,
                 pid: int = 0) -> dict:
    """Convert drained records to the Chrome trace-event format (load
    in chrome://tracing or Perfetto) — the graphical leg of the
    xentrace_format analog. SCHED_PICK/SCHED_DESCHED pairs become
    duration ('X') events on a per-context track (tid = ctx slot, dur
    from the desched's device-true ran_ns); everything else becomes an
    instant event on its slot's track. ``labels`` maps ctx slots to
    display names (e.g. from the ledger sidecar meta)."""
    labels = labels or {}
    events: list[dict] = []
    open_pick: dict[int, int] = {}  # slot -> pick ts
    for ts, ev, *a in np.asarray(recs).tolist():
        slot = a[0] if a else 0
        try:
            name = Ev(ev).name
        except ValueError:
            name = f"0x{ev:04x}"
        if ev == Ev.SCHED_PICK:
            open_pick[slot] = ts
        elif ev == Ev.SCHED_DESCHED and slot in open_pick:
            t0 = open_pick.pop(slot)
            ran_ns = a[1] if len(a) > 1 else ts - t0
            events.append({
                "name": labels.get(slot, f"ctx{slot}"),
                "ph": "X", "cat": "sched",
                "ts": t0 / 1e3, "dur": max(ran_ns, 1) / 1e3,
                "pid": pid, "tid": slot,
                "args": {"ran_ns": ran_ns},
            })
        else:
            events.append({
                "name": name, "ph": "i", "s": "t",
                "cat": name.split("_")[0].lower(),
                "ts": ts / 1e3, "pid": pid, "tid": slot,
                "args": {f"a{i}": v for i, v in enumerate(a)},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
