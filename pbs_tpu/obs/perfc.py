"""Software performance counters (perfc analog).

Reference: hypervisor-internal counters behind ``PERF_COUNTERS``
(``xen/common/perfc.c``), bumped with ``perfc_incr``, dumped via console
keys 'p'/'P' (``keyhandler.c:556-559``) and the ``xenperf`` CLI
(``tools/misc/xenperf.c``). Cheap unconditional counters for framework
internals, distinct from the per-job telemetry ledger.
"""

from __future__ import annotations

import collections

from pbs_tpu.obs.lockprof import ProfiledLock


class Perfc:
    def __init__(self):
        self._c: dict[str, int] = collections.defaultdict(int)
        self._lock = ProfiledLock("perfc")

    def incr(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._c[name] += by

    def get(self, name: str) -> int:
        return self._c.get(name, 0)

    def dump(self) -> dict[str, int]:
        """The 'p' console key / xenperf surface."""
        with self._lock:
            return dict(sorted(self._c.items()))

    def reset(self) -> None:
        """The 'P' console key: zero all counters."""
        with self._lock:
            self._c.clear()


#: Process-global instance (perfc is global in the hypervisor too).
perfc = Perfc()
