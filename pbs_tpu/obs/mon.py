"""Scheduler-event digestion + live monitor (xenbaked / xenmon analog).

Reference: ``xenbaked`` (``tools/xenmon/xenbaked.c``) maps the
hypervisor's per-CPU trace rings dom0-side, consumes ``TRC_SCHED_*``
events, and folds them into rotating per-domain history windows
(gotten/blocked/waited time, exec counts, I/O counts) in a shared-memory
file that ``xenmon.py`` renders live (``tools/xenmon/README:1-25``).

Here the same two halves:

- :class:`SchedHistory` — folds trace records (``Ev.SCHED_PICK`` /
  ``SCHED_DESCHED`` / ``SCHED_WAKE``) into per-slot rotating windows of
  gotten-time, allocated-quantum, exec and wake counts.
- :class:`Monitor` — attaches to a partition's file-backed trace rings
  and ledger (``Partition(trace_dir=..., ledger_path=...)``), drains
  rings incrementally, and serves labeled rows for ``pbst mon``.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os

import numpy as np

from pbs_tpu.obs.trace import Ev, TraceBuffer

SEC = 1_000_000_000


@dataclasses.dataclass
class Window:
    """One history window for one slot (xenbaked ``struct cpu_history``
    row: gotten/allocated/blocked/waited per domain per period)."""

    gotten_ns: int = 0  # device time actually burned (DESCHED ran_ns)
    allocated_ns: int = 0  # quanta handed out (PICK quantum_ns)
    execs: int = 0  # times scheduled (DESCHED count)
    wakes: int = 0


class SchedHistory:
    """Rotating per-slot windows over a sched-event stream.

    Windows rotate on *trace* time (virtual or wall — whatever stamped
    the records), so digestion is deterministic and replayable from a
    saved trace dump, like xenbaked re-run over an xentrace log.
    """

    def __init__(self, window_ns: int = SEC, n_windows: int = 10):
        self.window_ns = window_ns
        self.n_windows = n_windows
        self._win_start: int | None = None  # start ts of current window
        self._cur: dict[int, Window] = collections.defaultdict(Window)
        self._hist: dict[int, collections.deque[Window]] = (
            collections.defaultdict(
                lambda: collections.deque(maxlen=n_windows)))
        self.records_seen = 0

    def _roll_to(self, ts: int) -> None:
        if self._win_start is None:
            self._win_start = ts - ts % self.window_ns
            return
        while ts >= self._win_start + self.window_ns:
            # close the current window for every slot ever seen
            for slot in set(self._hist) | set(self._cur):
                self._hist[slot].append(self._cur.get(slot, Window()))
            self._cur = collections.defaultdict(Window)
            self._win_start += self.window_ns

    def ingest(self, recs: np.ndarray) -> int:
        """Fold (n, 8) u64 trace records; returns records consumed."""
        if not len(recs):
            return 0
        # One bulk tolist() instead of 3-4 numpy scalar reads per
        # record: digestion is the monitor's hot loop (pbst mon polls
        # tens of thousands of records per refresh).
        for row in np.asarray(recs).tolist():
            ts, ev = row[0], row[1]
            self._roll_to(ts)
            self.records_seen += 1
            if ev == Ev.SCHED_PICK:
                self._cur[row[2]].allocated_ns += row[3]
            elif ev == Ev.SCHED_DESCHED:
                w = self._cur[row[2]]
                w.gotten_ns += row[3]
                w.execs += 1
            elif ev == Ev.SCHED_WAKE:
                self._cur[row[2]].wakes += 1
        return len(recs)

    def slots(self) -> list[int]:
        return sorted(set(self._hist) | set(self._cur))

    def summary(self, slot: int, windows: int | None = None,
                include_open: bool = True) -> Window:
        """Aggregate over the last ``windows`` closed windows, plus the
        open one unless ``include_open=False`` (None = everything held)."""
        agg = Window()
        hist = list(self._hist.get(slot, ()))
        if windows is not None:
            # NB: hist[-0:] would be the whole list, not none of it; and
            # the start must clamp at 0 or windows > len(hist) wraps
            # negative and silently drops the oldest closed windows.
            hist = hist[max(0, len(hist) - windows):] if windows > 0 else []
        if include_open:
            hist = hist + [self._cur.get(slot, Window())]
        for w in hist:
            agg.gotten_ns += w.gotten_ns
            agg.allocated_ns += w.allocated_ns
            agg.execs += w.execs
            agg.wakes += w.wakes
        return agg

    def cpu_pct(self, slot: int, windows: int = 1) -> float:
        """Share of trace time the slot burned over the last windows —
        xenmon's headline per-domain CPU% column. Requires ≥1 window
        (the open window alone has no fixed denominator). Only closed
        windows count: the open window's partial gotten_ns over a
        full-window denominator would understate early and let the
        column drift above 100% late."""
        if windows < 1:
            raise ValueError("cpu_pct needs windows >= 1")
        span = windows * self.window_ns
        got = self.summary(slot, windows, include_open=False).gotten_ns
        return 100.0 * got / span


class Monitor:
    """Live attachment to a partition's observability artifacts.

    The consumer side of the shared-memory contract: trace rings are
    drained destructively (this is THE consumer, like xenbaked), the
    ledger is snapshot lock-free read-only."""

    def __init__(self, meta_path: str, window_ns: int = SEC,
                 n_windows: int = 10):
        with open(meta_path) as f:
            self.meta = json.load(f)
        trace_dir = self.meta.get("trace_dir")
        if not trace_dir:
            raise ValueError(
                "partition has no trace_dir; create it with "
                "Partition(trace_dir=...) for live monitoring")
        self.rings = [
            TraceBuffer.file_backed(
                os.path.join(trace_dir, f"trace{i}.ring"), attach=True)
            for i in range(int(self.meta.get("n_rings", 1)))
        ]
        self.history = SchedHistory(window_ns, n_windows)
        self._meta_path = meta_path

    def refresh_meta(self) -> None:
        with open(self._meta_path) as f:
            self.meta = json.load(f)

    def poll(self, max_records: int = 65536) -> int:
        """Drain all rings into the history; returns records consumed."""
        from pbs_tpu.obs.trace import merge_records

        return self.history.ingest(
            merge_records([r.consume(max_records) for r in self.rings]))

    def rows(self, windows: int = 1) -> list[dict]:
        """Per-context rows labeled through the meta sidecar."""
        slot_meta = {int(k): v for k, v in self.meta.get("slots", {}).items()}
        out = []
        for slot in self.history.slots():
            info = slot_meta.get(slot, {})
            agg = self.history.summary(slot, windows)
            out.append({
                "slot": slot,
                "ctx": info.get("ctx", f"slot{slot}"),
                "job": info.get("job", "?"),
                "weight": info.get("weight"),
                "cpu_pct": round(self.history.cpu_pct(slot, windows), 2),
                "gotten_ms": round(agg.gotten_ns / 1e6, 3),
                "execs": agg.execs,
                "wakes": agg.wakes,
            })
        return out
