"""Per-job consoles: the ``xl console`` analog.

Reference: every domain has a console ring the hypervisor relays to
dom0 (``xenconsoled``), and ``xl console <dom>`` attaches to stream it
— the primary "what is my guest saying" channel. The TPU-native
analog: every job owns a bounded line ring; the runtime writes
lifecycle events into it (admit, wake/sleep, fault containment with
the error), the workload itself can write via ``Job.log``, and
monitors stream it by sequence number — locally, or over the control
plane (``pbst console``), which mirrors xenconsoled's relay role.

Sequence-numbered reads make the stream resumable and loss-visible:
a reader that fell behind sees the gap (``first_seq`` > its cursor),
exactly like a console ring overwriting old lines.

Besides the per-job rings there is one *system* console — the analog of
the hypervisor's own ``xl dmesg`` ring: infrastructure that must report
a condition but has no job to attribute it to (a leaked RPC server
thread, a quarantined agent) writes here via :func:`log`.
"""

from __future__ import annotations

import collections
import threading
import time


class Console:
    """Bounded per-job line ring with monotone sequence numbers."""

    def __init__(self, capacity: int = 1024):
        self.capacity = capacity
        self._lines: collections.deque[tuple[int, float, str]] = (
            collections.deque(maxlen=capacity))
        self._next_seq = 0
        self._lock = threading.Lock()

    def write(self, line: str) -> int:
        """Append one line; returns its sequence number."""
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._lines.append((seq, time.time(), str(line)))
            return seq

    def read(self, since: int = 0, max_lines: int = 256) -> dict:
        """Lines with seq >= ``since`` (up to ``max_lines``). The
        reply's ``next`` is the cursor for the following read;
        ``first_seq`` exposes ring loss to a lagging reader."""
        with self._lock:
            out = [(s, t, ln) for (s, t, ln) in self._lines if s >= since]
            first = self._lines[0][0] if self._lines else self._next_seq
            nxt = self._next_seq
        out = out[:max_lines]
        return {
            "lines": [
                {"seq": s, "time": t, "line": ln} for s, t, ln in out
            ],
            "next": out[-1][0] + 1 if out else nxt,
            "first_seq": first,
            "dropped": max(0, first - since) if since < first else 0,
        }


# -- system console (xl dmesg analog) ---------------------------------------

#: The one process-wide infrastructure ring. Bounded like every job
#: ring, so a wedged component that logs in a loop cannot grow memory.
_system = Console(capacity=1024)


def system_console() -> Console:
    return _system


def log(line: str) -> int:
    """Write one line to the system console ring. Returns its sequence
    number. This is where infrastructure reports conditions that have
    no owning job — operators read it with :func:`read_system`."""
    return _system.write(line)


def read_system(since: int = 0, max_lines: int = 256) -> dict:
    return _system.read(since, max_lines)
