"""Lock-order validation: the lockdep analog (race detection, §5).

Reference: the guest kernel ships lockdep
(``linux-3.2.30/kernel/lockdep.c``): every acquisition records edges
from the locks already held to the lock being taken; when a new edge
closes a cycle in that order graph, a potential AB-BA deadlock is
reported the FIRST time the inverted order is ever seen — no actual
deadlock needs to occur. Round-1 verdict listed race detection as the
one aux subsystem with no class-equivalent analog here.

Same design, framework-scale: a process-wide order graph over named
lock classes (the same per-name classing ``ProfiledLock`` uses for
stats), a per-thread held stack, and DFS cycle detection on each new
edge. Validation is gated by the ``lockdep`` boot param (off = zero
overhead, like the kernel's CONFIG gate); ``strict`` mode raises at
the violating acquisition (the development posture), default mode
records the violation with both witness chains (the AVC-log posture —
``pbst lockdep`` style dumps via :func:`violations`).
"""

from __future__ import annotations

import threading
from typing import Iterable

from pbs_tpu.utils.params import boolean_param

#: Validation gate (CONFIG_PROVE_LOCKING analog; off = no bookkeeping).
lockdep = boolean_param("lockdep", False)
#: Raise OrderViolation at the faulting acquire instead of only logging.
lockdep_strict = boolean_param("lockdep_strict", False)


class OrderViolation(RuntimeError):
    def __init__(self, holding: str, taking: str, cycle: list[str]):
        super().__init__(
            f"lock order violation: taking {taking!r} while holding "
            f"{holding!r}, but the order graph already requires "
            f"{' -> '.join(cycle)} (AB-BA deadlock possible)")
        self.holding = holding
        self.taking = taking
        self.cycle = cycle


class _Graph:
    """Order graph over lock-class names. Edge A->B = 'B was taken
    while A was held' (B nests inside A)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._edges: dict[str, set[str]] = {}
        self._witness: dict[tuple[str, str], str] = {}
        self._tls = threading.local()
        self.violations: list[dict] = []
        # One record per (holding, taking) class pair (the kernel
        # reports a pair once); repeats only bump the count — a hot
        # inverted path must not grow memory per quantum.
        self._seen_pairs: dict[tuple[str, str], dict] = {}
        self.checked_edges = 0

    # -- per-thread held stack ------------------------------------------

    def _held(self) -> list[str]:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    # -- graph ops -------------------------------------------------------

    def _path(self, src: str, dst: str) -> list[str] | None:
        """DFS path src -> ... -> dst in the existing order graph."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def note_acquire(self, name: str, where: str = "") -> None:
        held = self._held()
        if held:
            holding = held[-1]
            # Re-entrant same-class acquisition is legal no matter how
            # deep it sits in the stack (A, B, A-again cannot invert:
            # the thread already owns A).
            if holding != name and name not in held:
                with self._mu:
                    self.checked_edges += 1
                    # Inversion: does the graph already require name
                    # to be taken BEFORE holding (a path name->holding)?
                    cycle = self._path(name, holding)
                    if cycle is not None:
                        pair = (holding, name)
                        v = self._seen_pairs.get(pair)
                        if v is not None:
                            v["count"] += 1
                        else:
                            v = {
                                "holding": holding,
                                "taking": name,
                                "established_order": cycle,
                                "witness": self._witness.get(
                                    (cycle[0], cycle[1]), "")
                                if len(cycle) > 1 else "",
                                "where": where,
                                "count": 1,
                            }
                            self._seen_pairs[pair] = v
                            self.violations.append(v)
                        if lockdep_strict.value:
                            raise OrderViolation(holding, name,
                                                 cycle + [name])
                    else:
                        edge = (holding, name)
                        if name not in self._edges.setdefault(holding,
                                                              set()):
                            self._edges[holding].add(name)
                            self._witness.setdefault(edge, where)
        held.append(name)

    def note_release(self, name: str) -> None:
        held = self._held()
        # Out-of-order release is legal (hand-over-hand): remove the
        # LAST occurrence, preserving the rest of the stack.
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "classes": sorted(
                    set(self._edges) | {b for s in self._edges.values()
                                        for b in s}),
                "edges": {a: sorted(bs)
                          for a, bs in sorted(self._edges.items())},
                "violations": list(self.violations),
                "checked_edges": self.checked_edges,
            }

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._witness.clear()
            self.violations.clear()
            self._seen_pairs.clear()
            self.checked_edges = 0


_graph = _Graph()


def note_acquire(name: str, where: str = "") -> None:
    """Hook point: call on every (gated) lock acquisition."""
    if lockdep.value:
        _graph.note_acquire(name, where)


def note_release(name: str) -> None:
    # Deliberately NOT gated on the live param: flipping lockdep off
    # while locks are held must still pop the held stacks, or phantom
    # holds poison the graph when it is re-enabled. (The pop is a
    # cheap no-op for stacks that were never pushed.)
    _graph.note_release(name)


def violations() -> list[dict]:
    return list(_graph.violations)


def dump() -> dict:
    return _graph.snapshot()


def export_graph(snapshot: dict | None = None) -> dict:
    """The dynamic order graph in its *stable public* JSON form — the
    interface static/dynamic cross-checking consumes (``pbst lockdep
    --dump-graph`` producing, ``pbst check --lockdep-graph``
    consuming). Edges are sorted ``[holder, taken]`` pairs so two
    exports of the same graph are byte-identical; ``version`` gates
    schema evolution."""
    snap = snapshot if snapshot is not None else dump()
    edges = snap.get("edges", {})
    return {
        "version": 1,
        "classes": sorted(snap.get("classes", [])),
        "edges": sorted([a, b] for a, bs in edges.items() for b in bs),
        "violations": sorted(
            ({"holding": v["holding"], "taking": v["taking"],
              "count": v.get("count", 1)}
             for v in snap.get("violations", [])),
            key=lambda v: (v["holding"], v["taking"])),
    }


def reset() -> None:
    _graph.reset()


class OrderedLock:
    """A named lock with lockdep validation AND contention profiling —
    the composition the kernel gives every spinlock. Drop-in for
    ``ProfiledLock`` where order checking is wanted."""

    def __init__(self, name: str, recursive: bool = False):
        from pbs_tpu.obs.lockprof import ProfiledLock

        self.name = name
        self._inner = ProfiledLock(name, recursive=recursive)

    def acquire(self) -> None:
        note_acquire(self.name)
        try:
            self._inner.acquire()
        except BaseException:
            note_release(self.name)  # strict-mode raise or interrupt:
            raise  # the held stack must not wedge

    def release(self) -> None:
        self._inner.release()
        note_release(self.name)

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()
