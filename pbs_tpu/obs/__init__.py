from pbs_tpu.obs.lockprof import ProfiledLock
from pbs_tpu.obs.perfc import Perfc, perfc
from pbs_tpu.obs.trace import Ev, TraceBuffer, format_records

__all__ = [
    "Ev", "Perfc", "ProfiledLock", "TraceBuffer", "format_records", "perfc",
]
