from pbs_tpu.obs.perfc import Perfc, perfc
from pbs_tpu.obs.trace import Ev, TraceBuffer, format_records

__all__ = ["Ev", "Perfc", "TraceBuffer", "format_records", "perfc"]
