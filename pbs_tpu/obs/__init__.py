from pbs_tpu.obs.console import Console
from pbs_tpu.obs.lockprof import ProfiledLock
from pbs_tpu.obs.mon import Monitor, SchedHistory
from pbs_tpu.obs.oprofile import ProfileSession, ProfilerBusy
from pbs_tpu.obs.perfc import Perfc, perfc
from pbs_tpu.obs.selftest import CanaryResult, run_selftest, selftest_ok
from pbs_tpu.obs.spans import (
    HistBatch,
    LatencyHistograms,
    SpanAssembler,
    SpanRecorder,
)
from pbs_tpu.obs.trace import Ev, TraceBuffer, format_records

__all__ = [
    "CanaryResult", "Console", "Ev", "HistBatch", "LatencyHistograms",
    "Monitor", "Perfc", "ProfileSession", "ProfilerBusy",
    "ProfiledLock", "SchedHistory", "SpanAssembler", "SpanRecorder",
    "TraceBuffer", "format_records", "perfc", "run_selftest",
    "selftest_ok",
]
