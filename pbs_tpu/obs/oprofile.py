"""System-wide sampling profiler backend (xenoprof analog).

Reference: xenoprof (``xen/common/xenoprof.c``, 921 LoC +
``arch/x86/oprofile/``) lets one privileged domain drive system-wide
PMU sampling: it *reserves* the PMU (mutually exclusive with perfctr
and the NMI watchdog — ``perfctr_glue.h:38``), walks a state machine
(init → ready → start → stop), collects samples into per-domain shared
buffers with a lost-sample counter, and supports **passive domains** —
guests profiled without their cooperation.

TPU re-expression: one :class:`ProfileSession` per process may hold the
profiler reservation. It samples at a fixed period on the partition's
timer wheel (so sim/virtual-clock runs are deterministic), folding
per-context counter deltas into bounded per-job sample buffers. Passive
partitions — other processes' partitions that know nothing about the
profiler — are sampled through read-only attachment to their
file-backed telemetry ledgers, the same privileged-observer pattern as
xenoprof's passive-domain buffers.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
from typing import TYPE_CHECKING

from pbs_tpu.telemetry.counters import Counter
from pbs_tpu.telemetry.ledger import Ledger
from pbs_tpu.utils.clock import MS

if TYPE_CHECKING:
    from pbs_tpu.runtime.partition import Partition


class ProfilerBusy(RuntimeError):
    """The PMU-reservation analog: only one profiler at a time
    (``perfctr_cpu_reserve`` arbitration)."""


_res_lock = threading.Lock()
_owner: str | None = None


def reserve(owner: str) -> None:
    global _owner
    with _res_lock:
        if _owner is not None and _owner != owner:
            raise ProfilerBusy(f"profiler reserved by {_owner!r}")
        _owner = owner


def release(owner: str) -> None:
    global _owner
    with _res_lock:
        if _owner == owner:
            _owner = None


def current_owner() -> str | None:
    return _owner


class SessionState(enum.Enum):
    # xenoprof's lifecycle (xenoprof.c state machine)
    INIT = "init"
    READY = "ready"
    RUNNING = "running"
    STOPPED = "stopped"
    CLOSED = "closed"


@dataclasses.dataclass
class Sample:
    """One periodic observation of one context — the PC-sample analog:
    *where* a TPU job is, is its step index; *what it is doing* is the
    counter mix since the last sample."""

    ts_ns: int
    ctx: str
    step: int  # steps retired at sample time (the "program counter")
    device_dns: int  # device time delta since previous sample
    stall_dns: int  # HBM-stall delta
    coll_wait_dns: int  # collective-wait delta


class ProfileSession:
    """One system-wide sampling session over a partition.

    ``max_samples_per_job`` bounds memory like xenoprof's shared sample
    buffers; overflow increments ``lost`` instead of blocking (same
    contract as the trace rings).
    """

    def __init__(
        self,
        partition: "Partition | None",
        period_ns: int = 1 * MS,  # CSCHED_METRIC_TICK_PERIOD-class cadence
        max_samples_per_job: int = 4096,
    ):
        """``partition=None`` makes a passive-only MONITOR session
        (``pbst oprofile``): no active domains, no timer — the caller
        drives :meth:`sample_once` with explicit timestamps."""
        self.partition = partition
        self.period_ns = period_ns
        self.max_samples = max_samples_per_job
        self.samples: dict[str, list[Sample]] = {}
        self.lost: dict[str, int] = {}
        self._last: dict[str, tuple[int, int, int]] = {}  # ctx -> prev ctrs
        self._last_cw: dict[str, int] = {}  # ctx -> prev collective-wait
        self._passive: list[tuple[str, Ledger, str, dict]] = []
        self._passive_last: dict[str, dict[int, tuple[int, int, int]]] = {}
        self._timer = None
        # Unique per session: two sessions over the same partition must
        # still exclude each other.
        self._token = (
            f"oprofile:{partition.name if partition else 'monitor'}:"
            f"{id(self)}")
        reserve(self._token)
        self.state = SessionState.INIT

    # -- passive domains (profiled without their cooperation) ------------

    @staticmethod
    def _read_meta(ledger_path: str) -> dict:
        import json

        try:
            with open(ledger_path + ".meta.json") as f:
                return json.load(f)
        except (FileNotFoundError, ValueError):
            # Missing or mid-rewrite: keep the previous slot view.
            return {}

    def add_passive(self, name: str, ledger_path: str) -> None:
        """Attach another process's partition read-only through its
        file-backed ledger (the xenoprof passive-domain buffer)."""
        if self.state not in (SessionState.INIT, SessionState.READY):
            raise RuntimeError("passive domains attach before start")
        led = Ledger.file_backed(ledger_path, readonly=True)
        meta = self._read_meta(ledger_path) or {"slots": {}}
        self._passive.append((name, led, ledger_path, meta))
        self._passive_last[name] = {}
        self.state = SessionState.READY

    def refresh_passive_meta(self) -> None:
        """Re-read each passive domain's meta sidecar so jobs the live
        producer admits AFTER attach get sampled too (the same
        reload-per-iteration contract as ``pbst top``)."""
        for i, (name, led, path, meta) in enumerate(self._passive):
            fresh = self._read_meta(path)
            if fresh:
                self._passive[i] = (name, led, path, fresh)

    # -- lifecycle (xenoprof.c init/start/stop/close) --------------------

    def start(self) -> "ProfileSession":
        if self.state is SessionState.CLOSED:
            raise RuntimeError("session closed")
        if self.partition is None:
            raise RuntimeError(
                "passive-only monitor sessions have no timer wheel; "
                "drive them with sample_once()")
        self._prime()
        now = self.partition.clock.now_ns()
        self._timer = self.partition.timers.arm(
            now + self.period_ns, self._tick, period_ns=self.period_ns,
            name="oprofile")
        self.state = SessionState.RUNNING
        return self

    def _prime(self) -> None:
        """Capture counter baselines at start so the first sample covers
        only session time — never the job's whole pre-session history."""
        for job in (self.partition.jobs if self.partition else ()):
            for ctx in job.contexts:
                self._last[ctx.name] = (
                    int(ctx.counters[Counter.STEPS_RETIRED]),
                    int(ctx.counters[Counter.DEVICE_TIME_NS]),
                    int(ctx.counters[Counter.HBM_STALL_NS]),
                )
                self._last_cw[ctx.name] = int(
                    ctx.counters[Counter.COLLECTIVE_WAIT_NS])
        for name, led, _path, meta in self._passive:
            last = self._passive_last[name]
            slots = [int(s) for s in meta.get("slots", {})]
            snaps = led.snapshot_many(slots)
            for slot, snap in zip(slots, snaps):
                last[slot] = (
                    int(snap[Counter.STEPS_RETIRED]),
                    int(snap[Counter.DEVICE_TIME_NS]),
                    int(snap[Counter.HBM_STALL_NS]),
                )

    def sample_once(self, now_ns: int | None = None) -> None:
        """One manual sampling tick — the monitor-side path used by
        ``pbst oprofile`` to profile PASSIVE ledgers in real time
        without arming any (virtual) timer wheel.  The first call
        primes counter baselines, so the first window starts at attach
        exactly like :meth:`start`; every call re-reads the producers'
        meta so later-admitted jobs are sampled too."""
        if self.state is SessionState.CLOSED:
            raise RuntimeError("session closed")
        if now_ns is None:
            if self.partition is None:
                raise ValueError(
                    "passive-only sessions need an explicit now_ns")
            now_ns = self.partition.clock.now_ns()
        if self.state is not SessionState.RUNNING:
            self._prime()
            self.state = SessionState.RUNNING
        self.refresh_passive_meta()
        self._tick(now_ns)

    def stop(self) -> None:
        if self._timer is not None:
            self._timer.stop()
            self._timer = None
        if self.state is SessionState.RUNNING:
            self.state = SessionState.STOPPED

    def close(self) -> None:
        self.stop()
        release(self._token)
        self.state = SessionState.CLOSED

    def __enter__(self) -> "ProfileSession":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- sampling --------------------------------------------------------

    def _record(self, job: str, s: Sample) -> None:
        buf = self.samples.setdefault(job, [])
        if len(buf) >= self.max_samples:
            self.lost[job] = self.lost.get(job, 0) + 1
            return
        buf.append(s)

    @staticmethod
    def _reset(cur, prev) -> bool:
        """A producer restart zeroes its ledger slot (Partition.add_job
        resets at admission): any counter moving BACKWARD means the
        baseline belongs to a dead incarnation — re-baseline silently
        instead of recording a negative delta."""
        return any(c < p for c, p in zip(cur, prev))

    def _tick(self, now_ns: int) -> None:
        # Active domains: the hosting partition's own jobs.
        for job in (self.partition.jobs if self.partition else ()):
            for ctx in job.contexts:
                cur = (
                    int(ctx.counters[Counter.STEPS_RETIRED]),
                    int(ctx.counters[Counter.DEVICE_TIME_NS]),
                    int(ctx.counters[Counter.HBM_STALL_NS]),
                )
                cw = int(ctx.counters[Counter.COLLECTIVE_WAIT_NS])
                prev = self._last.get(ctx.name, (0, 0, 0))
                prev_cw = self._last_cw.get(ctx.name, 0)
                if cur == prev and cw == prev_cw:
                    # idle since last tick: no sample (unhalted cycles
                    # only, like PMU sampling). Baselines stay put so
                    # activity accrued across idle ticks lands on the
                    # next recorded sample rather than vanishing.
                    continue
                if self._reset(cur, prev) or cw < prev_cw:
                    self._last[ctx.name] = cur
                    self._last_cw[ctx.name] = cw
                    continue
                self._last[ctx.name] = cur
                self._last_cw[ctx.name] = cw
                self._record(job.name, Sample(
                    ts_ns=now_ns, ctx=ctx.name, step=cur[0],
                    device_dns=cur[1] - prev[1],
                    stall_dns=cur[2] - prev[2],
                    coll_wait_dns=cw - prev_cw,
                ))
        # Passive domains: lock-free ledger snapshots of foreign
        # partitions — one vectorized snapshot_many per domain per tick
        # (the sample-window fast path) instead of a per-slot loop.
        for name, led, _path, meta in self._passive:
            last = self._passive_last[name]
            slot_meta = meta.get("slots", {})
            snaps = led.snapshot_many([int(s) for s in slot_meta])
            for (slot_s, info), snap in zip(slot_meta.items(), snaps):
                slot = int(slot_s)
                cur = (
                    int(snap[Counter.STEPS_RETIRED]),
                    int(snap[Counter.DEVICE_TIME_NS]),
                    int(snap[Counter.HBM_STALL_NS]),
                )
                prev = last.get(slot, (0, 0, 0))
                if cur == prev:
                    continue
                last[slot] = cur
                if self._reset(cur, prev):
                    continue  # producer restarted: window discarded
                self._record(f"{name}/{info.get('job', slot)}", Sample(
                    ts_ns=now_ns, ctx=info.get("ctx", str(slot)),
                    step=cur[0],
                    device_dns=cur[1] - prev[1],
                    stall_dns=cur[2] - prev[2],
                    coll_wait_dns=0,
                ))

    # -- report ----------------------------------------------------------

    def report(self) -> dict:
        """Flat profile per job: sample counts and where device time
        went (the opreport analog)."""
        out = {}
        for job, samples in self.samples.items():
            dev = sum(s.device_dns for s in samples)
            stall = sum(s.stall_dns for s in samples)
            coll = sum(s.coll_wait_dns for s in samples)
            out[job] = {
                "samples": len(samples),
                "lost": self.lost.get(job, 0),
                "device_ms": round(dev / 1e6, 3),
                "stall_pct": round(100.0 * stall / dev, 2) if dev else 0.0,
                "collective_wait_ms": round(coll / 1e6, 3),
                "last_step": samples[-1].step if samples else 0,
            }
        return out
