"""Request-scoped span tracing + per-tenant SLO observability.

PBS's premise is that scheduling should be driven by cheap, always-on
performance observation; this module gives the serving tier the
*causal* half of that story. Every admitted gateway request becomes a
**span chain** keyed on its ``rid``: admission, fair-queue entry, DRR
dispatch (deficit attached), backend execution, completion — and,
across the federated tier, custody transfers (``adopt`` /
``adopt_tenant`` after a gateway death or drain), so a request that
survives a front-door death has ONE continuous timeline stitched
across members. Three pieces:

- :class:`SpanRecorder` — the producer. Interns rids and member names
  to dense u64 ids and emits ``SPAN_*`` records (``obs.trace.Ev``,
  class 0x08xx) through an :class:`~pbs_tpu.obs.trace.EmitBatch`, so
  the hot path stays on the PR 5 batched, allocation-free staging path
  (one vectorized ring write per watermark, never a scalar emit per
  event).
- :class:`LatencyHistograms` — allocation-free log2-bucketed latency
  histograms per ``(who, class, stage)``, living in telemetry
  **ledger slots** (one seqlock slot per histogram; the 18 counter
  words ARE the buckets), so monitors snapshot them lock-free like any
  other ledger and quantiles come from :func:`hist_quantile` — the
  nearest-rank estimator over bucket upper edges, never an
  interpolated value. ``record`` fuses bucket+seqlock into one native
  call when the runtime is loaded (byte-identical to the Python
  fallback), and :class:`HistBatch` stages a pump tick's samples into
  one ``record_many`` flush (docs/PERF.md "Native fast path").
- :class:`SpanAssembler` — the consumer. Reconstructs per-rid
  timelines from drained trace records, validates **gap-free chain**
  invariants (the ``pbst chaos`` federation harness gates on them),
  exports Chrome trace JSON (chrome://tracing / Perfetto), and builds
  the ``pbst slo report`` view: per-tenant p50/p95/p99 and SLO
  burn-rate against the tenant's latency target.

Determinism: the recorder adds no randomness and consults no fault
streams, so arming it in a chaos run leaves the run's digests
untouched — span continuity is a pure *observer* invariant.
"""

from __future__ import annotations

import json
import os

import numpy as np

from pbs_tpu.obs.trace import (
    TRACE_REC_WORDS,
    _U64_MASK,
    EmitBatch,
    Ev,
    TraceBuffer,
)
from pbs_tpu.telemetry.counters import NUM_COUNTERS
from pbs_tpu.telemetry.ledger import Ledger
from pbs_tpu.utils.clock import MS

# -- log2 latency histograms -------------------------------------------------

#: Buckets per histogram == counter words per ledger slot: the slot IS
#: the histogram, so every existing ledger surface (file-backed attach,
#: seqlock snapshot, snapshot_many) works on histograms unchanged.
HIST_BUCKETS = NUM_COUNTERS
#: Bucket 0 upper edge is 2**(HIST_SHIFT+1) ns (~16 us): everything
#: faster is "instant" at serving-tier resolution. The top bucket opens
#: at 2**(HIST_SHIFT+HIST_BUCKETS-1) ns (~1.07 s): everything slower
#: is an SLO catastrophe whose exact value no longer matters.
HIST_SHIFT = 13

#: Request lifecycle stages a histogram is kept for (docs/TRACING.md):
#: ``queue`` = admit->dispatch wait, ``service`` = backend execution,
#: ``e2e`` = admit->complete.
SPAN_STAGES = ("queue", "service", "e2e")

#: Default per-class SLO latency targets (e2e) the burn-rate report
#: uses when the tenant spec doesn't pin one (TenantSpec.slo_target_ns).
DEFAULT_SLO_TARGET_NS = {"interactive": 50 * MS, "batch": 500 * MS}
#: The SLO objective burn rates are normalized against: 99% of
#: requests under target; burn 1.0 = exactly spending the 1% budget.
SLO_OBJECTIVE = 0.99


def hist_bucket(value_ns: int) -> int:
    """Bucket index for a latency: pure int ops, nothing allocated.
    Bucket b (0 < b < last) covers [2**(SHIFT+b), 2**(SHIFT+b+1))."""
    b = int(value_ns).bit_length() - 1 - HIST_SHIFT
    if b < 0:
        return 0
    last = HIST_BUCKETS - 1
    return b if b < last else last


def bucket_edges() -> np.ndarray:
    """Upper edges (inclusive representative values) per bucket — the
    value :func:`hist_quantile` reports for a sample landing in the
    bucket. One vectorized table, computed once."""
    return np.array(
        [(1 << (HIST_SHIFT + b + 1)) - 1 for b in range(HIST_BUCKETS)],
        dtype=np.int64)


_EDGES = bucket_edges()


def hist_quantile(counts: np.ndarray, q: float) -> int:
    """Nearest-rank quantile over a bucket-count vector: the bucket
    holding the ``ceil(q*n)``-th smallest sample (1-indexed), reported
    as that bucket's upper edge — the same estimator family as
    ``utils.stats.nearest_rank`` (an edge a real sample sat under,
    never an interpolated value), at log2 resolution. 0 for empty.
    Vectorized (one cumsum + searchsorted): never a per-bucket Python
    scan in a hot path (the ``obs-hist-scan`` rule)."""
    c = np.asarray(counts, dtype=np.int64)
    total = int(c.sum())
    if total <= 0:
        return 0
    k = max(1, int(np.ceil(q * total)))
    b = int(np.searchsorted(np.cumsum(c), k))
    return int(_EDGES[min(b, HIST_BUCKETS - 1)])


class LatencyHistograms:
    """Log2 latency histograms in ledger slots, keyed ``(who, cls,
    stage)`` (``who`` is a tenant name or a ``be:<backend>`` row).

    ``record`` is the hot path: one dict hit + one ledger counter add
    (bucket + seqlock fused into a single native call when the runtime
    is loaded) — no allocation beyond the interning of a key the first
    time it is seen. Slots are allocated densely; when the ledger is
    full, new keys fold into a per-``(cls, stage)`` overflow row
    (counts are never dropped, attribution degrades to the class).
    """

    __slots__ = ("path", "ledger", "num_slots", "_slots", "_next",
                 "_overflow_slot", "_nat", "_natp", "_fc", "_addr",
                 "_fc_record")

    def __init__(self, num_slots: int = 256, path: str | None = None,
                 native: bool | str | None = None):
        if num_slots < 2:
            raise ValueError("LatencyHistograms needs >= 2 slots "
                             "(one is the reserved overflow row)")
        self.path = path
        if path is not None:
            self.ledger = Ledger.file_backed(path, num_slots=num_slots,
                                             native=native)
            for slot in range(num_slots):
                self.ledger.reset(slot)  # never inherit a previous run
        else:
            self.ledger = Ledger(num_slots, native=native)
        # The fused native paths (pbst_hist_record[_many]: log2 bucket
        # + seqlock add in one call) ride the ledger's binding tiers;
        # byte-identical slot state either way (docs/PERF.md).
        self._nat = getattr(self.ledger, "_nat", None)
        self._natp = getattr(self.ledger, "_ptr", None)
        self._fc = getattr(self.ledger, "_fc", None)
        self._addr = getattr(self.ledger, "_addr", 0)
        self._fc_record = (self._fc.hist_record
                           if self._fc is not None else None)
        self.num_slots = int(num_slots)
        self._slots: dict[tuple[str, str, str], int] = {}
        self._next = 0
        #: The last slot is RESERVED as the shared overflow row: it is
        #: never handed to a normal key, so overflow can never corrupt
        #: an allocated histogram (only the overflow row itself mixes
        #: keys, and only once every same-(cls, stage) fold target is
        #: also exhausted).
        self._overflow_slot = self.num_slots - 1

    def slot_of(self, who: str, cls: str, stage: str) -> int:
        """Interned ledger slot for a key (allocating on first sight).
        Public so staged producers (:class:`HistBatch`) can intern at
        record time — slot-allocation order, and therefore the meta
        sidecar, must not depend on when a batch flushes."""
        key = (who, cls, stage)
        slot = self._slots.get(key)
        if slot is not None:
            return slot
        if self._next < self._overflow_slot:
            slot = self._slots[key] = self._next
            self._next += 1
            if self.path is not None:
                self._write_meta()
            return slot
        # Full: fold into an existing row of the same (cls, stage) —
        # counts are never dropped, per-tenant attribution degrades to
        # the class (class aggregates stay exact, and class_counts
        # de-dupes shared slots).
        for (w, c, st), s in sorted(self._slots.items()):
            if c == cls and st == stage and s != self._overflow_slot:
                self._slots[key] = s
                return s
        # No same-class row exists either: the reserved shared
        # overflow row — mixed attribution, but never another
        # histogram's slot.
        slot = self._slots[key] = self._overflow_slot
        return slot

    def record(self, who: str, cls: str, stage: str,
               value_ns: int) -> None:
        """One latency sample: bucket + seqlock add, fused into one
        native call when the runtime is loaded. Values clamp to
        [0, 2^64): a negative (clock-skew) sample lands in bucket 0 on
        every tier."""
        slot = self._slots.get((who, cls, stage))
        if slot is None:
            slot = self.slot_of(who, cls, stage)
        fcr = self._fc_record
        if fcr is not None:
            # Negatives clamp to 0 (= bucket 0, the Python tier's
            # result); values are ns-scale by contract, far below the
            # u64 range where the C mask could matter.
            fcr(self._addr, slot,
                value_ns if value_ns >= 0 else 0, HIST_SHIFT)
            return
        if self._nat is not None:
            v = int(value_ns)
            if not 0 <= v <= _U64_MASK:
                v = 0 if v < 0 else v & _U64_MASK
            self._nat.pbst_hist_record(self._natp, slot, v, HIST_SHIFT)
            return
        self.ledger.add(slot, hist_bucket(value_ns), 1)

    def record_many(self, slots: np.ndarray, values: np.ndarray) -> None:
        """Batched :meth:`record` over parallel (slot, value) vectors
        — slots from :meth:`slot_of`, interned at stage time. One C
        call when native; the pure-Python fallback replays the scalar
        per-record protocol, so every tier leaves byte-identical
        ledger state (per-record seqlock version bumps included)."""
        slots = np.ascontiguousarray(slots, dtype=np.int64)
        values = np.ascontiguousarray(values, dtype="<u8")
        n = slots.size
        if values.size != n:
            raise ValueError(
                f"record_many wants parallel vectors, got {n} slots / "
                f"{values.size} values")
        if n == 0:
            return
        if self._fc is not None:
            self._fc.hist_record_many(self._addr, self.num_slots,
                                      slots, values, n, HIST_SHIFT)
            return
        if self._nat is not None:
            from pbs_tpu.runtime import native as native_mod

            rc = self._nat.pbst_hist_record_many(
                self._natp, self.num_slots, native_mod.as_i64p(slots),
                native_mod.as_u64p(values), n, HIST_SHIFT)
            if rc == -2:
                raise IndexError("hist_record_many: slot out of range")
            return
        if ((slots < 0) | (slots >= self.num_slots)).any():
            # Prevalidated like the C path: a bad batch mutates nothing.
            raise IndexError("hist_record_many: slot out of range")
        add = self.ledger.add
        for s, v in zip(slots.tolist(), values.tolist()):
            add(s, hist_bucket(v), 1)

    # -- read side -------------------------------------------------------

    def counts(self, who: str, cls: str, stage: str) -> np.ndarray:
        slot = self._slots.get((who, cls, stage))
        if slot is None:
            return np.zeros(HIST_BUCKETS, dtype="<u8")
        return self.ledger.snapshot(slot)

    def quantile(self, who: str, cls: str, stage: str, q: float) -> int:
        return hist_quantile(self.counts(who, cls, stage), q)

    def class_counts(self, cls: str, stage: str) -> np.ndarray:
        """Aggregate bucket counts across every tenant of a class
        (backend ``be:`` rows excluded) — one vectorized
        ``snapshot_many`` + column sum, the monitors' fast path."""
        slots = sorted({
            s for (who, c, st), s in self._slots.items()
            if c == cls and st == stage and not who.startswith("be:")})
        if not slots:
            return np.zeros(HIST_BUCKETS, dtype="<u8")
        return self.ledger.snapshot_many(slots).sum(axis=0)

    def class_quantile(self, cls: str, stage: str, q: float) -> int:
        return hist_quantile(self.class_counts(cls, stage), q)

    def over_target(self, who: str, cls: str, stage: str,
                    target_ns: int) -> tuple[int, int]:
        """``(over, total)`` sample counts against an SLO latency
        target, at log2 resolution: a sample counts as over only when
        its whole bucket sits above the target's bucket (the sample
        provably exceeded the target; samples sharing the target's
        bucket count as under — conservative, so a burn rate built on
        this never cries wolf from quantization). The autopilot canary
        guard reads this delta-style over its guard window
        (docs/AUTOPILOT.md)."""
        c = self.counts(who, cls, stage).astype(np.int64)
        first_over = hist_bucket(int(target_ns)) + 1
        return int(c[first_over:].sum()), int(c.sum())

    def keys(self) -> list[tuple[str, str, str]]:
        return sorted(self._slots)

    # -- sidecar (pbst gateway stats / slo report attach) ----------------

    def _write_meta(self) -> None:
        meta = {
            "version": 1,
            "buckets": HIST_BUCKETS,
            "shift": HIST_SHIFT,
            "slots": {str(s): list(k)
                      for k, s in sorted(self._slots.items(),
                                         key=lambda kv: kv[1])},
        }
        tmp = self.path + ".meta.json.tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=1, sort_keys=True)
        os.replace(tmp, self.path + ".meta.json")

    @classmethod
    def attach(cls, path: str) -> "LatencyHistograms":
        """Monitor attach to a producer's file-backed histogram ledger
        (read side only; the meta sidecar restores the key map)."""
        with open(path + ".meta.json") as f:
            meta = json.load(f)
        self = cls.__new__(cls)
        self.path = None
        self.ledger = Ledger.file_backed(path, readonly=True)
        self.num_slots = self.ledger.num_slots
        # Monitor attach never records; reads go through the ledger's
        # own snapshot paths (which keep their native tiers).
        self._nat = self._natp = self._fc = self._fc_record = None
        self._addr = 0
        self._slots = {tuple(k): int(s)
                       for s, k in meta["slots"].items()}
        self._next = len(self._slots)
        return self


class HistBatch:
    """Per-tick staging for histogram samples — the
    :class:`~pbs_tpu.obs.trace.EmitBatch` of the latency layer: a
    pump's worth of ``record()`` calls land as ONE
    :meth:`LatencyHistograms.record_many` flush (one C call on the
    native tiers) instead of an interpreter round-trip per sample.

    Staging changes WHEN a sample reaches its ledger slot, never the
    bytes: keys intern at record() time (slot-allocation order — and
    therefore the meta sidecar — identical to scalar calls), values
    land in record order, and the flush keeps the per-record seqlock
    protocol. NOT thread-safe: one batch per pump thread, flushed at
    tick end and before any read of the histograms.

    On the pure-Python tier the batch degrades to DIRECT scalar
    records (flush is then a no-op): replaying staged scalars at flush
    would cost strictly more than recording in place, and the
    degraded mode keeps today's verified behavior exactly.
    """

    __slots__ = ("hist", "capacity", "_direct", "_s", "_v", "_sm",
                 "_vm", "_n", "recorded", "flushes")

    def __init__(self, hist: LatencyHistograms, capacity: int = 512):
        if capacity <= 0:
            raise ValueError("HistBatch capacity must be > 0")
        self.hist = hist
        self.capacity = int(capacity)
        self._direct = hist._nat is None and hist._fc is None
        self._s = np.zeros(self.capacity, dtype=np.int64)
        self._v = np.zeros(self.capacity, dtype="<u8")
        self._sm = memoryview(self._s)
        self._vm = memoryview(self._v)
        self._n = 0
        self.recorded = 0
        self.flushes = 0

    def record(self, who: str, cls: str, stage: str,
               value_ns: int) -> None:
        self.recorded += 1
        hist = self.hist
        if self._direct:
            hist.record(who, cls, stage, value_ns)
            return
        slot = hist._slots.get((who, cls, stage))
        if slot is None:
            slot = hist.slot_of(who, cls, stage)
        v = int(value_ns)
        if not 0 <= v <= _U64_MASK:  # the record() clamp contract
            v = 0 if v < 0 else v & _U64_MASK
        i = self._n
        self._sm[i] = slot
        self._vm[i] = v
        self._n = i + 1
        if self._n >= self.capacity:
            self.flush()

    def pending(self) -> int:
        return self._n

    def flush(self) -> int:
        """Land staged samples in the ledger; returns samples flushed."""
        n, self._n = self._n, 0
        if not n:
            return 0
        self.flushes += 1
        self.hist.record_many(self._s[:n], self._v[:n])
        return n


# -- the producer ------------------------------------------------------------


class SpanRecorder:
    """Interns rids/member names and stages ``SPAN_*`` records.

    One recorder per pump thread (the EmitBatch contract). A federated
    tier shares ONE recorder across members — all members pump on the
    federation's single thread, and a shared ring keeps the stitched
    chain in emission order with no cross-ring merge.
    """

    def __init__(self, ring: TraceBuffer | None = None,
                 batch: EmitBatch | None = None, capacity: int = 8192,
                 batch_capacity: int = 128,
                 max_spans: int = 262_144):
        self.ring = ring if ring is not None else TraceBuffer(capacity)
        self.batch = (batch if batch is not None
                      else EmitBatch(self.ring, capacity=batch_capacity))
        #: Intern-table bound: the rid table must stay reconstructable
        #: by the assembler, so ids are never recycled — instead, once
        #: ``max_spans`` rids have been seen, NEW spans are dropped
        #: (counted in ``dropped_spans``; existing chains keep
        #: emitting), the same graceful degradation as a full trace
        #: ring. A long-lived gateway therefore has bounded memory;
        #: size the bound to the run like the ring capacity.
        self.max_spans = int(max_spans)
        self.dropped_spans = 0
        self._span_ids: dict[str, int] = {}
        self._rids: list[str] = []
        self._member_ids: dict[str, int] = {}
        self._members: list[str] = []
        self._tenant_ids: dict[str, int] = {}
        self._tenants: list[str] = []
        self.spans_started = 0
        self.sheds = 0

    def span_id(self, rid: str) -> int | None:
        """Interned id for ``rid``; None once the table is full and
        the rid is new (the caller drops that span's events)."""
        sid = self._span_ids.get(rid)
        if sid is None:
            if len(self._rids) >= self.max_spans:
                self.dropped_spans += 1
                return None
            sid = self._span_ids[rid] = len(self._rids)
            self._rids.append(rid)
        return sid

    def member_id(self, name: str) -> int:
        mid = self._member_ids.get(name)
        if mid is None:
            mid = self._member_ids[name] = len(self._members)
            self._members.append(name)
        return mid

    def tenant_id(self, name: str) -> int:
        """Tenant slots are RECORDER-interned, not per-member: two
        federated members emitting about one tenant agree on the slot,
        so stitched chains attribute uniformly."""
        tid = self._tenant_ids.get(name)
        if tid is None:
            tid = self._tenant_ids[name] = len(self._tenants)
            self._tenants.append(name)
        return tid

    def rid_table(self) -> list[str]:
        return list(self._rids)

    def member_table(self) -> list[str]:
        return list(self._members)

    def tenant_table(self) -> list[str]:
        return list(self._tenants)

    # -- lifecycle emits (all through the batch; docs/TRACING.md) --------

    def admit(self, now: int, rid: str, tenant: str, cls: int,
              cost: int, member: str) -> None:
        sid = self.span_id(rid)
        if sid is None:
            return
        self.spans_started += 1
        self.batch.emit(now, Ev.SPAN_ADMIT, sid,
                        self.tenant_id(tenant), cls, cost,
                        self.member_id(member))

    def shed(self, now: int, tenant: str, cls: int,
             reason_code: int, member: str) -> None:
        self.sheds += 1
        self.batch.emit(now, Ev.SPAN_SHED, self.tenant_id(tenant), cls,
                        reason_code, self.member_id(member))

    def enqueue(self, now: int, rid: str, tenant: str, cls: int,
                member: str) -> None:
        sid = self.span_id(rid)
        if sid is None:
            return
        self.batch.emit(now, Ev.SPAN_ENQUEUE, sid,
                        self.tenant_id(tenant), cls,
                        self.member_id(member))

    def dispatch(self, now: int, rid: str, backend_slot: int,
                 qdelay_ns: int, deficit_x1000: int,
                 member: str) -> None:
        sid = self.span_id(rid)
        if sid is None:
            return
        self.batch.emit(now, Ev.SPAN_DISPATCH, sid,
                        backend_slot, qdelay_ns, deficit_x1000,
                        self.member_id(member))

    def exec(self, now: int, rid: str, backend_slot: int,
             member: str) -> None:
        sid = self.span_id(rid)
        if sid is None:
            return
        self.batch.emit(now, Ev.SPAN_EXEC, sid,
                        backend_slot, self.member_id(member))

    def complete(self, now: int, rid: str, backend_slot: int,
                 service_ns: int, latency_ns: int, member: str) -> None:
        sid = self.span_id(rid)
        if sid is None:
            return
        self.batch.emit(now, Ev.SPAN_COMPLETE, sid,
                        backend_slot, service_ns, latency_ns,
                        self.member_id(member))

    def requeue(self, now: int, rid: str, backend_slot: int,
                member: str) -> None:
        sid = self.span_id(rid)
        if sid is None:
            return
        self.batch.emit(now, Ev.SPAN_REQUEUE, sid,
                        backend_slot, self.member_id(member))

    def handoff(self, now: int, rid: str, from_member: str,
                to_member: str) -> None:
        sid = self.span_id(rid)
        if sid is None:
            return
        self.batch.emit(now, Ev.SPAN_HANDOFF, sid,
                        self.member_id(from_member),
                        self.member_id(to_member))

    def recover(self, now: int, rid: str, member: str,
                generation: int) -> None:
        """Crash-recovery stitch (docs/DURABILITY.md): emitted for
        every request the journal replay re-materialized, re-anchoring
        its chain in the recovery epoch ``generation`` at the member
        that now holds custody. Legal anywhere in a chain — including
        first, when the pre-crash span records died staged in the
        dead process's batch."""
        sid = self.span_id(rid)
        if sid is None:
            return
        self.batch.emit(now, Ev.SPAN_RECOVER, sid,
                        self.member_id(member), int(generation))

    def emit_event(self, now: int, ev: int, *args: int) -> None:
        """Non-span audit record sharing this recorder's ring (the
        autopilot decision events, class 0x09xx): rides the same
        EmitBatch, lands in emission order next to the chains it
        explains. The assembler ignores non-0x08xx classes, so chain
        validation is untouched."""
        self.batch.emit(now, ev, *args)

    def flush(self) -> None:
        self.batch.flush()

    def drain(self) -> np.ndarray:
        """All staged + ringed records, flushed first so a consumer
        never sees a partial stream (the PR 5 drain contract)."""
        self.flush()
        chunks = []
        while True:
            recs = self.ring.consume(4096)
            if not len(recs):
                break
            chunks.append(recs)
        if not chunks:
            return np.empty((0, TRACE_REC_WORDS), dtype="<u8")
        return np.concatenate(chunks, axis=0)

    # -- artifact export (pbst gateway demo --obs) -----------------------

    def export(self, obs_dir: str, run_meta: dict | None = None,
               tenants: dict[str, dict] | None = None,
               recs: np.ndarray | None = None) -> dict[str, str]:
        """Write the span artifacts ``pbst trace spans`` / ``pbst slo
        report`` read: ``spans.npy`` (drained records) + ``spans.json``
        (rid/member tables, per-tenant SLO info, run metadata)."""
        os.makedirs(obs_dir, exist_ok=True)
        recs = recs if recs is not None else self.drain()
        npy = os.path.join(obs_dir, "spans.npy")
        np.save(npy, recs)
        sidecar = {
            "version": 1,
            "rids": self.rid_table(),
            "members": self.member_table(),
            "tenant_table": self.tenant_table(),
            "tenants": tenants or {},
            "run": run_meta or {},
            "lost": int(self.ring.lost),
        }
        side = os.path.join(obs_dir, "spans.json")
        tmp = side + ".tmp"
        with open(tmp, "w") as f:
            json.dump(sidecar, f, indent=1, sort_keys=True)
        os.replace(tmp, side)
        return {"spans": npy, "sidecar": side}


# -- the consumer ------------------------------------------------------------

#: Per-event arg layout AFTER the span id (chain entries store
#: ``(ts, ev, *args_after_span)``): how many args are real (the ring
#: pads to 6) and which one is the member id (None = HANDOFF carries
#: from/to member pair instead).
SPAN_ARGS: dict[int, tuple[int, int | None]] = {
    int(Ev.SPAN_ADMIT): (4, 3),     # tenant, cls, cost, member
    int(Ev.SPAN_ENQUEUE): (3, 2),   # tenant, cls, member
    int(Ev.SPAN_DISPATCH): (4, 3),  # backend, qdelay, deficit, member
    int(Ev.SPAN_EXEC): (2, 1),      # backend, member
    int(Ev.SPAN_COMPLETE): (4, 3),  # backend, service, latency, member
    int(Ev.SPAN_REQUEUE): (2, 1),   # backend, member
    int(Ev.SPAN_HANDOFF): (2, None),  # from_member, to_member
    int(Ev.SPAN_RECOVER): (2, 0),   # member, generation
}

_SPAN_CLASS = 0x0800
_TERMINAL = frozenset({int(Ev.SPAN_COMPLETE)})
#: Events legal FROM each chain state; the assembler walks the machine
#: and any other (state, event) pair is a GAP — the chain invariant the
#: federation chaos harness gates on.
_QUEUED, _INFLIGHT, _DONE = 0, 1, 2
_NEXT_STATE = {
    (_QUEUED, int(Ev.SPAN_ENQUEUE)): _QUEUED,
    (_QUEUED, int(Ev.SPAN_DISPATCH)): _INFLIGHT,
    (_QUEUED, int(Ev.SPAN_HANDOFF)): _QUEUED,
    (_QUEUED, int(Ev.SPAN_REQUEUE)): _QUEUED,
    (_INFLIGHT, int(Ev.SPAN_EXEC)): _INFLIGHT,
    (_INFLIGHT, int(Ev.SPAN_COMPLETE)): _DONE,
    (_INFLIGHT, int(Ev.SPAN_REQUEUE)): _QUEUED,
    (_INFLIGHT, int(Ev.SPAN_HANDOFF)): _QUEUED,
}


class SpanAssembler:
    """Reconstructs rid-keyed timelines from drained trace records.

    Records MUST arrive in emission order (one shared recorder ring —
    the federation stitches by construction; ``merge_records`` streams
    from several rings would interleave same-timestamp events). Only
    0x08xx records are consumed; a mixed GW_*/SPAN_* stream is fine.
    """

    def __init__(self, recs: np.ndarray, rid_table: list[str],
                 member_table: list[str] | None = None,
                 tenant_table: list[str] | None = None):
        self.rids = list(rid_table)
        self.members = list(member_table or [])
        self.tenant_table = list(tenant_table or [])
        #: rid -> [(ts, ev, args...)] in emission order.
        self.chains: dict[str, list[tuple]] = {}
        self.shed_events = 0
        self.unknown_spans = 0
        for row in np.asarray(recs).tolist():
            ts, ev, a = row[0], row[1], row[2:]
            if (ev & 0xFF00) != _SPAN_CLASS:
                continue
            if ev == Ev.SPAN_SHED:
                self.shed_events += 1
                continue
            sid = a[0]
            if not 0 <= sid < len(self.rids):
                self.unknown_spans += 1
                continue
            self.chains.setdefault(self.rids[sid], []).append(
                (ts, ev, *a[1:]))

    # -- the gap-free chain invariant ------------------------------------

    def validate(self, admitted: list[str] | None = None,
                 require_complete: bool = True,
                 aborted: "set[str] | None" = None) -> list[str]:
        """Problems list (empty = every chain holds). ``admitted`` pins
        the expected universe: every admitted rid must HAVE a chain
        (a rid with no records at all is the worst gap), and every
        chain must start with SPAN_ADMIT, walk only legal transitions,
        and (``require_complete``) end in exactly one SPAN_COMPLETE.

        SPAN_RECOVER (docs/DURABILITY.md) is legal from ANY state —
        including as the chain's first record, and after a terminal
        SPAN_COMPLETE whose journal frame never committed — and resets
        the chain to QUEUED with the completion count cleared: the
        recovered request re-executes, and "exactly one complete"
        means one per final recovery epoch.

        ``aborted`` names rids whose admission was never durable (the
        crash harness's unacked suffix): their partial chains are
        excluded from the extras complaint instead of read as
        never-admitted records."""
        problems: list[str] = []
        universe = admitted if admitted is not None else sorted(self.chains)
        for rid in universe:
            chain = self.chains.get(rid)
            if not chain:
                problems.append(f"span {rid}: admitted but no records")
                continue
            ts0, ev0 = chain[0][0], chain[0][1]
            if ev0 not in (Ev.SPAN_ADMIT, Ev.SPAN_RECOVER):
                problems.append(
                    f"span {rid}: chain starts with "
                    f"{Ev(ev0).name}, not SPAN_ADMIT")
                continue
            state = _QUEUED
            completes = 0
            for ts, ev, *a in chain[1:]:
                if ev == Ev.SPAN_RECOVER:
                    # Crash-recovery re-anchor: every recovered
                    # request is requeued, and completes count from
                    # the epoch that finally delivered.
                    state = _QUEUED
                    completes = 0
                    continue
                if ev == Ev.SPAN_ADMIT:
                    problems.append(f"span {rid}: duplicate SPAN_ADMIT")
                    break
                if state == _DONE:
                    problems.append(
                        f"span {rid}: {Ev(ev).name} after terminal "
                        "SPAN_COMPLETE")
                    break
                nxt = _NEXT_STATE.get((state, int(ev)))
                if nxt is None:
                    problems.append(
                        f"span {rid}: gap — {Ev(ev).name} while "
                        f"{'queued' if state == _QUEUED else 'inflight'}")
                    break
                state = nxt
                if ev == Ev.SPAN_COMPLETE:
                    completes += 1
            else:
                if require_complete and completes != 1:
                    problems.append(
                        f"span {rid}: {completes} SPAN_COMPLETE "
                        "records (want exactly 1; chain reaches no "
                        "terminal state)" if completes == 0 else
                        f"span {rid}: {completes} SPAN_COMPLETE records")
        if admitted is not None:
            extras = set(self.chains) - set(admitted) - set(aborted or ())
            for rid in sorted(extras):
                problems.append(
                    f"span {rid}: records exist for a rid never "
                    "admitted")
        if self.unknown_spans:
            problems.append(
                f"{self.unknown_spans} span record(s) referenced ids "
                "outside the rid table")
        return problems

    # -- summaries -------------------------------------------------------

    def summary(self) -> dict:
        handoffs = sum(
            1 for chain in self.chains.values()
            for ts, ev, *a in chain if ev == Ev.SPAN_HANDOFF)
        recovers = sum(
            1 for chain in self.chains.values()
            for ts, ev, *a in chain if ev == Ev.SPAN_RECOVER)
        completes = sum(
            1 for chain in self.chains.values()
            if any(ev == Ev.SPAN_COMPLETE for _, ev, *a in chain))
        return {
            "chains": len(self.chains),
            "complete": completes,
            "handoff_events": handoffs,
            "recover_events": recovers,
            "shed_events": self.shed_events,
        }

    def latencies(self) -> dict[str, dict[str, int]]:
        """Per rid: e2e latency, queue wait (sum across dispatches of
        post-admit waits is overkill; the SLO view is admit->first
        dispatch), service (dispatch->complete), handoffs/requeues."""
        out: dict[str, dict[str, int]] = {}
        for rid, chain in self.chains.items():
            admit_ts = chain[0][0]
            first_dispatch = next(
                (ts for ts, ev, *a in chain if ev == Ev.SPAN_DISPATCH),
                None)
            complete = next(
                ((ts, a) for ts, ev, *a in chain
                 if ev == Ev.SPAN_COMPLETE), None)
            if complete is None:
                continue
            ts_done, args = complete
            out[rid] = {
                "e2e_ns": ts_done - admit_ts,
                "queue_ns": ((first_dispatch - admit_ts)
                             if first_dispatch is not None else 0),
                "service_ns": int(args[1]),
                "requeues": sum(1 for _, ev, *a in chain
                                if ev == Ev.SPAN_REQUEUE),
                "handoffs": sum(1 for _, ev, *a in chain
                                if ev == Ev.SPAN_HANDOFF),
            }
        return out

    # -- chrome trace (the SchedHistory.chrome_trace idiom) --------------

    def chrome_trace(self, pid: int = 0) -> dict:
        """Duration ('X') events per request: one ``queue`` slice from
        admit to each dispatch, one ``service`` slice from dispatch to
        complete, instant events for requeues/handoffs — tid is the
        span id so one request is one track, labelled
        ``tenant/rid`` via the sidecar tenant table."""
        events: list[dict] = []
        sid_of = {rid: i for i, rid in enumerate(self.rids)}
        for rid, chain in sorted(self.chains.items()):
            sid = sid_of.get(rid, 0)
            tslot = chain[0][2]  # admit args: tenant slot
            tenant = (self.tenant_table[tslot]
                      if 0 <= tslot < len(self.tenant_table)
                      else f"tenant{tslot}")
            label = f"{tenant}/{rid}"
            open_ts = chain[0][0]  # queue opens at admit
            for ts, ev, *a in chain:
                if ev == Ev.SPAN_DISPATCH:
                    events.append({
                        "name": f"{label} queue", "ph": "X",
                        "cat": "span.queue",
                        "ts": open_ts / 1e3,
                        "dur": max(ts - open_ts, 1) / 1e3,
                        "pid": pid, "tid": sid,
                        "args": {"qdelay_ns": a[1],
                                 "deficit_x1000": a[2]},
                    })
                    open_ts = ts  # service opens at dispatch
                elif ev in (Ev.SPAN_REQUEUE, Ev.SPAN_HANDOFF):
                    name = ("requeue" if ev == Ev.SPAN_REQUEUE
                            else "handoff")
                    events.append({
                        "name": f"{label} {name}", "ph": "i", "s": "t",
                        "cat": f"span.{name}", "ts": ts / 1e3,
                        "pid": pid, "tid": sid,
                        "args": {f"a{i}": v for i, v in enumerate(a)},
                    })
                    open_ts = ts  # back in a queue somewhere
                elif ev == Ev.SPAN_COMPLETE:
                    events.append({
                        "name": f"{label} service", "ph": "X",
                        "cat": "span.service",
                        "ts": open_ts / 1e3,
                        "dur": max(ts - open_ts, 1) / 1e3,
                        "pid": pid, "tid": sid,
                        "args": {"service_ns": a[1],
                                 "latency_ns": a[2]},
                    })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    # -- the SLO view (pbst slo report) ----------------------------------

    def slo_report(self, tenants: dict[str, dict] | None = None,
                   run_meta: dict | None = None) -> dict:
        """Stable per-tenant SLO JSON. ``tenants`` maps tenant name ->
        {"slo": class, "slo_target_ns": int|None}; rid->tenant comes
        from the recorder sidecar when available, else from the chain's
        tenant slot (opaque int labels)."""
        tenants = tenants or {}
        lat = self.latencies()
        # rid -> tenant: the admit record carries the recorder-interned
        # tenant slot; the tenant table (sidecar) names it.
        per_tenant: dict[str, list[tuple[str, dict]]] = {}
        for rid, m in lat.items():
            slot = self.chains[rid][0][2]  # admit args: tenant slot
            t = (self.tenant_table[slot]
                 if 0 <= slot < len(self.tenant_table)
                 else f"tenant{slot}")
            per_tenant.setdefault(t, []).append((rid, m))
        report_tenants: dict[str, dict] = {}
        for t in sorted(per_tenant):
            rows = per_tenant[t]
            e2e = sorted(m["e2e_ns"] for _, m in rows)
            n = len(e2e)
            info = tenants.get(t, {})
            cls = info.get("slo", "batch")
            target = info.get("slo_target_ns") or \
                DEFAULT_SLO_TARGET_NS.get(cls, DEFAULT_SLO_TARGET_NS["batch"])
            over = sum(1 for v in e2e if v > target)
            budget = 1.0 - SLO_OBJECTIVE
            burn = (over / n) / budget if n else 0.0

            def _pct(q: float) -> float:
                k = max(1, int(np.ceil(q * n))) - 1 if n else 0
                return round(e2e[min(k, n - 1)] / 1e6, 3) if n else 0.0

            report_tenants[t] = {
                "slo": cls,
                "requests": n,
                "p50_ms": _pct(0.50),
                "p95_ms": _pct(0.95),
                "p99_ms": _pct(0.99),
                "target_ms": round(target / 1e6, 3),
                "over_target": over,
                "burn_rate": round(burn, 4),
                "handoffs": sum(m["handoffs"] for _, m in rows),
                "requeues": sum(m["requeues"] for _, m in rows),
            }
        return {
            "version": 1,
            "objective": SLO_OBJECTIVE,
            "run": run_meta or {},
            "spans": self.summary(),
            "tenants": report_tenants,
        }


def load_span_artifacts(obs_dir: str) -> tuple[np.ndarray, dict]:
    """The reader half of :meth:`SpanRecorder.export`."""
    recs = np.load(os.path.join(obs_dir, "spans.npy"))
    with open(os.path.join(obs_dir, "spans.json")) as f:
        sidecar = json.load(f)
    return recs, sidecar
