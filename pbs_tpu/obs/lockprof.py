"""Lock-contention profiling (LOCK_PROFILE / xenlockprof analog).

Reference: Xen's ``LOCK_PROFILE`` infrastructure wraps spinlocks with
per-lock block counts and cumulative block time
(``xen-4.2.1/xen/common/spinlock.c:1-608``), dumped/reset via console
keys 'l'/'L' (``keyhandler.c:561-563``) and read from dom0 by the
``xenlockprof`` CLI (``tools/misc/xenlockprof.c``). The same capability
here: ``ProfiledLock`` wraps framework locks, a global registry
aggregates per-lock acquire counts, contended-acquire counts, wait and
hold times, and the CLI exposes it as ``pbst lockprof``.

Profiling is gated by the ``lock_profile`` boot param (off by default,
like Xen's compile-time gate): when off, acquire/release take the
no-bookkeeping fast path.
"""

from __future__ import annotations

import threading
import time

from pbs_tpu.utils.params import boolean_param

#: Gate (Xen builds LOCK_PROFILE in conditionally; we flip at runtime).
lock_profile = boolean_param("lock_profile", False)


class LockStats:
    """Shared by every lock with the same name (Xen aggregates per lock
    *site*), so updates are serialized by ``_mu``, not by any one
    instance's underlying lock."""

    __slots__ = ("name", "acquires", "contended", "wait_ns", "hold_ns",
                 "max_wait_ns", "_mu")

    def __init__(self, name: str):
        self.name = name
        self._mu = threading.Lock()
        self._zero()

    def _zero(self) -> None:
        self.acquires = 0
        self.contended = 0
        self.wait_ns = 0
        self.hold_ns = 0
        self.max_wait_ns = 0

    def note_acquire(self, wait_ns: int | None) -> None:
        with self._mu:
            self.acquires += 1
            if wait_ns is not None:
                self.contended += 1
                self.wait_ns += wait_ns
                if wait_ns > self.max_wait_ns:
                    self.max_wait_ns = wait_ns

    def note_hold(self, hold_ns: int) -> None:
        with self._mu:
            self.hold_ns += hold_ns

    def reset(self) -> None:
        with self._mu:
            self._zero()

    def as_dict(self) -> dict:
        with self._mu:
            return {
                "name": self.name,
                "acquires": self.acquires,
                "contended": self.contended,
                "wait_ns": self.wait_ns,
                "hold_ns": self.hold_ns,
                "max_wait_ns": self.max_wait_ns,
            }


_reg_lock = threading.Lock()
_stats: dict[str, LockStats] = {}


def _stats_for(name: str) -> LockStats:
    with _reg_lock:
        s = _stats.get(name)
        if s is None:
            s = _stats[name] = LockStats(name)
        return s


class ProfiledLock:
    """A named lock with optional contention bookkeeping.

    Mirrors ``struct lock_profile`` hanging off ``spinlock_t``
    (``spinlock.c``): the stats object is shared by every lock with the
    same name (Xen aggregates per lock *site*).
    """

    def __init__(self, name: str, recursive: bool = False):
        self._lock = threading.RLock() if recursive else threading.Lock()
        self.stats = _stats_for(name)
        # Owner-only state: touched strictly between acquire and release,
        # so the underlying lock serializes access. _t_acq is the
        # outermost-acquire timestamp (None when hold isn't being timed,
        # e.g. profiling was off at acquire time); _depth handles RLock
        # re-entry so nested acquires neither re-stamp nor double-count.
        self._depth = 0
        self._t_acq: int | None = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # Signature-compatible with threading.Lock so a ProfiledLock
        # can drop in anywhere a raw lock was (RemusSession's
        # time-bounded epoch quiesce depends on ``timeout=``); a failed
        # try/timed acquire touches no owner-only state.
        if not lock_profile.value:
            if not self._lock.acquire(blocking, timeout):
                return False
            self._depth += 1
            return True
        wait: int | None = None
        if not self._lock.acquire(blocking=False):
            if not blocking:
                return False
            t0 = time.monotonic_ns()
            if not self._lock.acquire(timeout=timeout):
                return False
            wait = time.monotonic_ns() - t0
        self._depth += 1
        self.stats.note_acquire(wait)
        if self._depth == 1:
            self._t_acq = time.monotonic_ns()
        return True

    def release(self) -> None:
        self._depth -= 1
        if self._depth == 0 and self._t_acq is not None:
            self.stats.note_hold(time.monotonic_ns() - self._t_acq)
            self._t_acq = None
        self._lock.release()

    def __enter__(self) -> "ProfiledLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def dump() -> list[dict]:
    """The 'l' console key / xenlockprof surface: per-lock stats sorted
    by cumulative wait time (worst first)."""
    with _reg_lock:
        rows = [s.as_dict() for s in _stats.values()]
    return sorted(rows, key=lambda r: -r["wait_ns"])


def reset() -> None:
    """The 'L' console key: zero all lock statistics."""
    with _reg_lock:
        for s in _stats.values():
            s.reset()
