"""Per-host agent: exposes one host's partition over the control plane.

Reference mapping: the xend management daemon (``tools/python``, one per
host) plus the privcmd hypercall surface — every operation the ``xl``/
``xm`` toolstack performs on a host (create/destroy/pause/unpause a
domain, adjust scheduler parameters, read telemetry, dump state) becomes
a registered RPC op against the host's :class:`Partition`. Workload
*factories* stand in for domain images: the controller names a workload,
the agent instantiates it locally (like ``xl create`` building a guest
from a config).
"""

from __future__ import annotations

import time as _time
from typing import Any, Callable

from pbs_tpu.dist.rpc import RpcServer
from pbs_tpu.faults import injector as faults
from pbs_tpu.faults.injector import InjectedFault
from pbs_tpu.runtime.xsm import XsmDenied, xsm_check
from pbs_tpu.runtime.job import ContextState, Job, SchedParams
from pbs_tpu.runtime.partition import Partition
from pbs_tpu.telemetry.counters import counters_dict
from pbs_tpu.telemetry.source import SimBackend, SimPhase, SimProfile

WorkloadFactory = Callable[[Partition, str, dict], Job]


def sim_workload(partition: Partition, job_name: str, spec: dict) -> Job:
    """Default workload: a synthetic SimBackend job.

    spec keys: phases=[{steps, step_time_ns, stall_frac, ...}] or flat
    SimPhase kwargs; sched={weight, cap, tslice_us, boost_on_wake};
    n_contexts; gang; max_steps.
    """
    if not isinstance(partition.source, SimBackend):
        raise TypeError("sim workload needs a SimBackend partition")
    if "phases" in spec:
        prof = SimProfile([SimPhase(**p) for p in spec["phases"]])
    else:
        keys = ("step_time_ns", "hbm_bytes", "stall_frac",
                "collective_wait_ns", "flops", "tokens")
        prof = SimProfile.steady(**{k: spec[k] for k in keys if k in spec})
    partition.source.register(job_name, prof)
    job = Job(
        job_name,
        params=SchedParams(**spec.get("sched", {})),
        n_contexts=int(spec.get("n_contexts", 1)),
        micro_per_step=int(spec.get("micro_per_step", 1)),
        gang=bool(spec.get("gang", False)),
        max_steps=spec.get("max_steps"),
        label=str(spec.get("label", "user")),
    )
    return partition.add_job(job)


class Agent:
    """One host's control-plane endpoint."""

    def __init__(
        self,
        name: str,
        partition: Partition | None = None,
        workloads: dict[str, WorkloadFactory] | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        n_executors: int = 2,
        scheduler: str = "credit",
        auth_token: str | None = None,
    ):
        self.name = name
        if partition is None:
            partition = Partition(
                f"{name}.pool", SimBackend(), scheduler=scheduler,
                n_executors=n_executors,
            )
        self.partition = partition
        from pbs_tpu.runtime.image import image_workload

        self.workloads: dict[str, WorkloadFactory] = {
            "sim": sim_workload,
            # pygrub analog: boot a job from an on-disk image directory
            # (spec={"path": ...}) — `xl create <image>` over the wire.
            "image": image_workload,
        }
        self.workloads.update(workloads or {})
        self.server = RpcServer(host=host, port=port, auth_token=auth_token,
                                fault_key=name)
        self._auth_token = auth_token
        # Remus surfaces: replicas this host holds for OTHER hosts' jobs
        # (job -> {"epoch", "saved", "source", "received_at"}) and the
        # replication sessions pumping THIS host's jobs to peers.
        self.replicas: dict[str, dict] = {}
        self.remus: dict[str, Any] = {}
        for op in ("create_job", "remove_job", "sched_setparams",
                   "pause_job", "unpause_job", "run", "dump", "telemetry",
                   "list_jobs", "save_job", "restore_job", "push_replica",
                   "get_replica", "list_replicas", "drop_replica",
                   "replicate_start", "replicate_stop", "replicate_status",
                   "console"):
            self.server.register(op, self._faulted(op, getattr(self,
                                                               "op_" + op)))
        # info answers without the dispatch lock: it only reads counts
        # (torn reads are fine for a placement heuristic) and the
        # controller ranks hosts with it while long `run` ops hold the
        # lock — blocking would freeze placement cluster-wide.
        self.server.register("info", self.op_info, lockfree=True)

    def _faulted(self, op_name: str, fn: Callable[..., Any]):
        """Dispatch seam: the ``agent.op`` injection point (stream key
        ``<agent>:<op>``). 'crash' raises :class:`InjectedFault` out of
        the op mid-dispatch — marshalled to the caller exactly like a
        real agent failure; 'slow' stretches the op (the lock-holder
        preemption analog the controller's breaker must tolerate).
        ``info`` is registered unwrapped: liveness/placement probes
        must stay transport-only signals."""
        key = f"{self.name}:{op_name}"

        def dispatch(**kwargs: Any) -> Any:
            f = faults.consult("agent.op", key)
            if f is not None:
                if f.fault == "crash":
                    raise InjectedFault(f"injected agent crash in {key}")
                if f.fault == "slow":
                    _time.sleep(float(f.args.get("delay_s", 0.001)))
            return fn(**kwargs)

        dispatch.__name__ = f"op_{op_name}"
        return dispatch

    # -- ops (the per-host hypercall surface) ----------------------------

    def op_info(self) -> dict:
        part = self.partition
        return {
            "agent": self.name,
            "partition": part.name,
            "scheduler": part.scheduler.name,
            "n_executors": len(part.executors),
            "n_jobs": len(part.jobs),
            "n_contexts": sum(len(j.contexts) for j in part.jobs),
        }

    def op_create_job(self, job: str, workload: str = "sim",
                      spec: dict | None = None,
                      subject: str = "remote") -> dict:
        # XSM hook at the dispatch surface (do_domctl placement): the
        # subject is the caller's declared label, checked against the
        # policy like any other — but privileged subjects ("system",
        # which bypasses all policy rules) are stripped at the RPC
        # layer unless the connection authenticated with the agent's
        # token (RpcServer trust model; Xen derives dom0 identity from
        # the calling domain, never from hypercall payload).
        xsm_check(subject, "job.create", (spec or {}).get("label", "user"))
        factory = self.workloads.get(workload)
        if factory is None:
            raise LookupError(f"unknown workload {workload!r}")
        if any(j.name == job for j in self.partition.jobs):
            raise ValueError(f"job {job!r} already exists")
        j = factory(self.partition, job, spec or {})
        # Re-check against the label the factory ACTUALLY assigned — a
        # custom factory may ignore spec['label'], and the pre-check
        # must not be the last word. Denial rolls the job back.
        try:
            xsm_check(subject, "job.create", j.label)
        except XsmDenied:
            self.partition.remove_job(j)
            raise
        # Remember provenance so save records are self-contained and a
        # restore can't silently rebuild a different workload.
        j.workload_name = workload
        j.spec = dict(spec or {})
        return {"job": j.name, "n_contexts": len(j.contexts)}

    def op_remove_job(self, job: str, subject: str = "remote") -> bool:
        j = self.partition.job(job)
        xsm_check(subject, "job.destroy", j.label)
        sess = self.remus.pop(job, None)
        if sess is not None:  # dead job needs no protection pump
            sess.stop()
        self.partition.remove_job(j)
        return True

    def op_sched_setparams(self, job: str, weight: int | None = None,
                           cap: int | None = None,
                           tslice_us: int | None = None,
                           subject: str = "remote") -> dict:
        j = self.partition.job(job)
        xsm_check(subject, "job.sched_cntl", j.label)
        changes = {k: int(v) for k, v in
                   (("weight", weight), ("cap", cap), ("tslice_us", tslice_us))
                   if v is not None}
        # Through the scheduler's control-plane hook (csched_dom_cntl),
        # so policies that react to param changes see them.
        self.partition.scheduler.adjust_job(j, **changes)
        p = j.params
        return {"weight": p.weight, "cap": p.cap, "tslice_us": p.tslice_us}

    def op_pause_job(self, job: str, subject: str = "remote") -> bool:
        j = self.partition.job(job)
        xsm_check(subject, "job.pause", j.label)
        self.partition.sleep_job(j)
        return True

    def op_unpause_job(self, job: str, subject: str = "remote") -> bool:
        j = self.partition.job(job)
        xsm_check(subject, "job.unpause", j.label)
        self.partition.wake_job(j)
        return True

    # -- save/restore (xc_domain_save/restore over DCN) ------------------

    def _save_record(self, j: Job) -> dict:
        """Serialize one (already-quiesced) job: the xc_domain_save
        record body, shared by migration save and Remus snapshots."""
        p = j.params
        saved: dict = {
            "job": j.name,
            "label": j.label,
            # provenance (set by op_create_job/op_restore_job; None for
            # jobs added out-of-band) — restore defaults to these
            "workload": getattr(j, "workload_name", None),
            "spec": getattr(j, "spec", None),
            "max_steps": j.max_steps,
            "gang": j.gang,
            "sched": {"weight": p.weight, "cap": p.cap,
                      "tslice_us": p.tslice_us,
                      "boost_on_wake": p.boost_on_wake},
            "contexts": [
                {"sched_count": c.sched_count,
                 # Mid-accumulation position: must travel with the job
                 # or step retirement desyncs from the model's own
                 # micro cursor after a mid-step migration.
                 "micro_progress": c.micro_progress,
                 "counters": [int(x) for x in c.counters]}
                for c in j.contexts
            ],
            "contention": [j.contention_wait_ns, j.contention_events],
            "backend": {},
        }
        if isinstance(self.partition.source, SimBackend):
            saved["backend"]["sim_steps_done"] = (
                self.partition.source.position(j.name))
        return saved

    def op_save_job(self, job: str, subject: str = "remote") -> dict:
        """Quiesce and serialize one job for migration (``xl save``:
        pause, then extract state). Unlike the reference — where perfctr
        shared-page PMU state is NOT in the save records and counters
        silently reset on migration (SURVEY.md §5) — the telemetry
        counters travel with the job."""
        j = self.partition.job(job)
        xsm_check(subject, "job.save", j.label)
        # stop-and-copy quiesce, not a lifecycle event (the job is
        # about to continue elsewhere; destroy hooks fire at remove)
        self.partition.sleep_job(j, notify=False)
        return self._save_record(j)

    def snapshot_record(self, job: str) -> dict:
        """Remus epoch capture: quiesce → record → resume. Unlike
        ``op_save_job`` the job keeps running afterwards — suspension
        lasts only the host-side record build (the reference's
        sub-second suspend/resume cycle, tools/remus/README). A job the
        user paused stays paused. Callers must hold ``dispatch_lock``
        (RemusSession does); this is not itself an RPC op."""
        j = self.partition.job(job)
        # 'paged' implies asleep too: the epoch capture must not wake
        # (and thereby page back in!) a parked/evicted tenant.
        was_asleep = self._job_state(j) in ("paused", "paged")
        self.partition.sleep_job(j, notify=False)  # epoch quiesce is
        saved = self._save_record(j)  # not a lifecycle event
        if not was_asleep:
            self.partition.wake_job(j, notify=False)
        return saved

    def op_restore_job(self, job: str, workload: str | None = None,
                       spec: dict | None = None, saved: dict | None = None,
                       subject: str = "remote") -> dict:
        """Recreate a saved job and overlay its runtime state
        (``xc_domain_restore``): scheduler params, per-context telemetry
        counters (into fresh ledger slots), contention accumulators, and
        the backend cursor. Workload/spec default to the save record's
        provenance so the restored job rebuilds the workload that was
        saved, not a default one."""
        import numpy as np

        if saved is None:
            raise ValueError("restore requires a 'saved' record")
        if workload is None:
            workload = saved.get("workload") or "sim"
        if spec is None:
            spec = saved.get("spec")
        xsm_check(subject, "job.restore", saved.get("label", "user"))
        factory = self.workloads.get(workload)
        if factory is None:
            raise LookupError(f"unknown workload {workload!r}")
        if any(j.name == job for j in self.partition.jobs):
            raise ValueError(f"job {job!r} already exists")
        j = factory(self.partition, job, spec or {})
        # Overlay + label re-check under rollback: a malformed wire
        # record or a denial must not leave a half-restored orphan
        # running (the migration retry would then always collide).
        try:
            j.label = saved.get("label", j.label)
            # Re-check the label the job ACTUALLY carries — wire dicts
            # are arbitrary and spec['label'] must not launder a target
            # the policy never authorized.
            xsm_check(subject, "job.restore", j.label)
            j.max_steps = saved.get("max_steps", j.max_steps)
            for k, v in saved.get("sched", {}).items():
                setattr(j.params, k, v)
            j.contention_wait_ns, j.contention_events = saved.get(
                "contention", (0, 0))
            for ctx, cstate in zip(j.contexts, saved.get("contexts", ())):
                ctx.sched_count = int(cstate.get("sched_count", 0))
                ctx.micro_progress = int(cstate.get("micro_progress", 0))
                ctrs = np.array(cstate.get("counters", []), dtype=np.uint64)
                if len(ctrs) == len(ctx.counters):
                    ctx.counters = ctrs
                    if ctx.ledger_slot >= 0:
                        # fresh slot is zeroed: adding restores the sums
                        self.partition.ledger.add_many(ctx.ledger_slot, ctrs)
            be = saved.get("backend", {})
            if ("sim_steps_done" in be
                    and isinstance(self.partition.source, SimBackend)):
                self.partition.source.seek(job, be["sim_steps_done"])
        except BaseException:
            self.partition.remove_job(j)
            raise
        j.workload_name = workload  # provenance survives re-migration
        j.spec = dict(spec or {})
        return {"job": j.name, "steps": j.steps_retired()}

    # -- Remus over the wire (tools/remus: continuous replication) -------

    def op_push_replica(self, job: str, epoch: int, saved: dict,
                        source: str = "?",
                        subject: str = "remote") -> dict:
        """Backup side of the Remus channel: store the newest epoch of a
        peer host's job. The reply IS the commit ack — the source only
        counts the epoch once this returns. Only newer epochs are
        accepted so a delayed duplicate can't roll the replica back."""
        xsm_check(subject, "job.replicate", saved.get("label", "user"))
        cur = self.replicas.get(job)
        if cur is not None:
            # Overwriting an existing replica is an operation on THAT
            # replica too: a subject allowed to replicate label "user"
            # must not be able to replace a "tenantA" replica by
            # shipping a crafted record with a label it controls.
            xsm_check(subject, "job.replicate",
                      cur["saved"].get("label", "user"))
        if cur is not None and int(epoch) < cur["epoch"]:
            return {"job": job, "epoch": cur["epoch"], "stale": True}
        self.replicas[job] = {
            "epoch": int(epoch),
            "saved": saved,
            "source": source,
            "received_at": _time.time(),
        }
        return {"job": job, "epoch": int(epoch), "stale": False}

    def op_get_replica(self, job: str,
                       subject: str = "remote") -> dict | None:
        r = self.replicas.get(job)
        if r is not None:
            # The record carries the job's full state (weights,
            # counters, sched params) — guard the read like the save op
            # guards the identical data.
            xsm_check(subject, "job.replicate",
                      r["saved"].get("label", "user"))
        return r

    def op_list_replicas(self, subject: str = "remote") -> list[dict]:
        from pbs_tpu.runtime.xsm import get_policy

        now = _time.time()
        pol = get_policy()
        return [
            {"job": job, "epoch": r["epoch"], "source": r["source"],
             "age_s": round(now - r["received_at"], 3)}
            for job, r in sorted(self.replicas.items())
            # metadata only, but existence still leaks: filter to what
            # the subject could replicate
            if pol.check(subject, "job.replicate",
                         r["saved"].get("label", "user"))
        ]

    def op_drop_replica(self, job: str, subject: str = "remote") -> bool:
        r = self.replicas.get(job)
        if r is None:
            return False
        # Check BEFORE mutating: a denied request must not destroy what
        # may be the only surviving copy of the job's state.
        xsm_check(subject, "job.replicate",
                  r["saved"].get("label", "user"))
        del self.replicas[job]
        return True

    def op_replicate_start(self, job: str, peer_host: str, peer_port: int,
                           period_s: float = 0.5,
                           subject: str = "remote") -> dict:
        """Start a replication session pumping ``job`` to a peer agent
        (the remus daemon the reference runs in dom0 of the primary)."""
        from pbs_tpu.dist.remus import RemusSession

        j = self.partition.job(job)
        xsm_check(subject, "job.replicate", j.label)
        old = self.remus.pop(job, None)
        if old is not None:
            old.stop()
        sess = RemusSession(
            self, job, (peer_host, int(peer_port)),
            period_s=float(period_s), subject=subject,
            auth_token=self._auth_token,
        )
        # First epoch ships synchronously so "replication enabled"
        # means "a committed replica exists", not "one is scheduled" —
        # a crash in the first period would otherwise lose everything.
        # NB: called under the dispatch lock, so ship directly (the
        # session's locked tick path would deadlock here).
        try:
            # Resume numbering past any replica the peer already holds
            # (a restarted session must not ship "epoch 0" into a
            # backup at epoch N — the stale-reject would freeze the
            # replica while the session reported healthy commits).
            existing = sess.client.call("get_replica", job=job,
                                        subject=subject)
            if existing is not None:
                sess.epochs_committed = int(existing["epoch"]) + 1
            saved = self.snapshot_record(job)
            sess.client.call("push_replica", job=job,
                             epoch=sess.epochs_committed, saved=saved,
                             source=self.name, subject=subject)
        except BaseException:
            sess.client.close()  # unreachable peer: no half-open session
            raise
        sess.epochs_committed += 1
        self.remus[job] = sess.start()
        return sess.status()

    def op_replicate_stop(self, job: str, subject: str = "remote") -> bool:
        sess = self.remus.get(job)
        if sess is None:
            return False
        try:
            label = self.partition.job(job).label
        except Exception:  # job already gone; session is an orphan
            label = "user"
        xsm_check(subject, "job.replicate", label)
        self.remus.pop(job).stop()
        return True

    def op_replicate_status(self, job: str | None = None,
                            subject: str = "remote") -> list[dict]:
        from pbs_tpu.runtime.xsm import get_policy

        pol = get_policy()

        def _visible(name: str) -> bool:
            # Session status names jobs and peer topology — filter like
            # op_list_replicas (same information, one op over).
            try:
                label = self.partition.job(name).label
            except KeyError:
                label = "user"
            return pol.check(subject, "job.replicate", label)

        if job is not None:
            sess = self.remus.get(job)
            return ([sess.status()] if sess is not None and _visible(job)
                    else [])
        return [s.status() for name, s in sorted(self.remus.items())
                if _visible(name)]

    def op_run(self, max_rounds: int | None = None,
               for_us: int | None = None) -> int:
        until = None
        if for_us is not None:
            until = self.partition.clock.now_ns() + 1000 * int(for_us)
        return self.partition.run(until_ns=until, max_rounds=max_rounds)

    def op_dump(self) -> dict:
        return self.partition.dump()

    @staticmethod
    def _job_state(j: Job) -> str:
        if j.error is not None:
            return "failed"
        if j.finished():
            return "finished"
        if getattr(j, "paged", None) is not None:
            return "paged"  # evicted to host (xenpaging state)
        live = {c.state for c in j.contexts}
        if live and live <= {ContextState.BLOCKED, ContextState.DONE}:
            return "paused"
        return "running"

    def op_list_jobs(self) -> list[dict]:
        return [
            {
                "job": j.name,
                "state": self._job_state(j),
                "weight": j.params.weight,
                "cap": j.params.cap,
                "tslice_us": j.params.tslice_us,
                "gang": j.gang,
                "steps": j.steps_retired(),
                "finished": j.finished(),
            }
            for j in self.partition.jobs
        ]

    def op_console(self, job: str, since: int = 0, max_lines: int = 256,
                   subject: str = "remote") -> dict:
        """Stream a job's console ring (xenconsoled relay role): the
        reply carries lines from ``since`` plus the next cursor, so
        ``pbst console -f`` polls without duplication."""
        j = self.partition.job(job)
        # Console content is the guest's own output: gate like the
        # telemetry-grade save path.
        xsm_check(subject, "job.console", j.label)
        return {"job": j.name, **j.console.read(int(since), int(max_lines))}

    def op_telemetry(self, job: str) -> dict:
        j = self.partition.job(job)
        return {
            "job": j.name,
            "contexts": [
                {
                    "ctx": c.name,
                    "sched_count": c.sched_count,
                    "counters": counters_dict(c.counters),
                }
                for c in j.contexts
            ],
        }

    # -- lifecycle -------------------------------------------------------

    @property
    def address(self) -> tuple[str, int]:
        return self.server.address

    @property
    def dispatch_lock(self):
        """The server's op-serializing lock; non-RPC entry points that
        mutate the partition (RemusSession ticks) must hold it."""
        return self.server._lock

    def start(self) -> "Agent":
        self.server.start()
        return self

    def stop(self) -> None:
        for sess in list(self.remus.values()):
            sess.stop()
        self.remus.clear()
        self.server.stop()
