"""Distributed control plane: controller <-> per-host agents over DCN.

The data plane (tensors) always rides XLA collectives over ICI/DCN
inside compiled programs; this package is only the *control* plane —
the toolstack surface (xend/xl, ``tools/python``, ``tools/libxl``)
re-expressed as a framed-JSON RPC between one controller and one agent
per host, with multicall batching (``xen/common/multicall.c``),
heartbeat failure detection (``tools/misc/xenwatchdogd.c``), and
restore-elsewhere recovery (``tools/remus``).
"""

from pbs_tpu.dist.agent import Agent, sim_workload
from pbs_tpu.dist.controller import (
    ClusterRoundError,
    Controller,
    JobRecord,
    MemberRef,
)
from pbs_tpu.dist.remus import RemusSession
from pbs_tpu.dist.rpc import RpcClient, RpcError, RpcServer

__all__ = [
    "Agent",
    "ClusterRoundError",
    "Controller",
    "JobRecord",
    "MemberRef",
    "RemusSession",
    "RpcClient",
    "RpcError",
    "RpcServer",
    "sim_workload",
]
