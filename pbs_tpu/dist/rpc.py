"""DCN control-plane RPC: length-prefixed JSON over TCP.

Reference mapping: the dom0 toolstack reaches the hypervisor through
privcmd ioctls -> hypercalls and reaches remote hosts over plain TCP
(live migration, ``tools/libxc/xc_domain_save.c``); batches of hypercalls
are issued through the multicall interface (``xen/common/multicall.c``)
to amortize boundary crossings. Here the boundary is the data-center
network between the controller and per-host agents, so the same three
ideas appear as: a tiny framed-JSON RPC (the hypercall ABI), a
server-side op table (the hypercall dispatch table,
``arch/x86/x86_64/entry.S:663-770``), and a first-class ``multicall``
op executing a batch in one round trip.

Deliberately dependency-free (stdlib sockets): the data plane never
touches this path — tensors move over ICI/DCN inside XLA collectives;
this carries only control messages, telemetry summaries, and checkpoint
metadata.
"""

from __future__ import annotations

import hmac
import json
import socket
import socketserver
import struct
import threading
from typing import Any, Callable

from pbs_tpu.obs.lockprof import ProfiledLock

MAX_MSG_BYTES = 64 << 20
_LEN = struct.Struct(">I")


class RpcError(Exception):
    """Remote op raised; .remote_type / .remote_message carry details."""

    def __init__(self, op: str, remote_type: str, remote_message: str):
        super().__init__(f"{op}: {remote_type}: {remote_message}")
        self.op = op
        self.remote_type = remote_type
        self.remote_message = remote_message


def send_msg(sock: socket.socket, obj: Any) -> None:
    data = json.dumps(obj, separators=(",", ":")).encode()
    if len(data) > MAX_MSG_BYTES:
        raise ValueError(f"message too large: {len(data)} bytes")
    sock.sendall(_LEN.pack(len(data)) + data)


def recv_msg(sock: socket.socket) -> Any:
    hdr = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(hdr)
    if n > MAX_MSG_BYTES:
        raise ValueError(f"message too large: {n} bytes")
    return json.loads(_recv_exact(sock, n).decode())


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


class RpcServer:
    """Threaded TCP server with a registered op table.

    Dispatch is serialized by a single lock — the moral equivalent of
    entering the hypervisor: op handlers may freely mutate the hosted
    partition without their own locking.

    Subject trust model: XSM subjects in request args are *advisory
    labels* checked against the policy — except privileged subjects
    (``system`` by default, the label that bypasses every policy rule).
    Those are only honored on connections that authenticated with the
    server's ``auth_token`` (built-in ``auth`` op), so a remote caller
    cannot claim hypervisor identity through a request field the way a
    Xen domain cannot forge being dom0 (the subject there derives from
    the calling domain, not from hypercall payload). With no token
    configured, no connection can ever be privileged.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 auth_token: str | None = None,
                 privileged_subjects: frozenset[str] = frozenset({"system"})):
        self.ops: dict[str, Callable[..., Any]] = {}
        self.auth_token = auth_token
        self.privileged_subjects = privileged_subjects
        self._lock = ProfiledLock("rpc_dispatch")
        # Connection bookkeeping must never wait on the dispatch lock,
        # or a fresh ping connection blocks behind a long-running op.
        self._conns_lock = ProfiledLock("rpc_conns")
        self._conns: set[socket.socket] = set()
        # Liveness probes must answer while a long op holds the dispatch
        # lock — otherwise a busy host reads as dead and gets its jobs
        # double-placed (the NMI watchdog answers from interrupt context
        # for the same reason, xen/arch/x86/nmi.c).
        self._lockfree_ops = {"ping", "ops"}
        self.register("ping", lambda: "pong")
        self.register("ops", lambda: sorted(self.ops))

        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one connection = many requests
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn = {"trusted": False}  # connection-level identity
                with outer._conns_lock:
                    outer._conns.add(sock)
                try:
                    while True:
                        req = recv_msg(sock)
                        send_msg(sock, outer._handle(req, conn))
                except (ConnectionError, OSError, ValueError):
                    return
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(sock)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.address: tuple[str, int] = self._server.server_address[:2]
        self._thread: threading.Thread | None = None

    # -- op table (hypercall registry) -----------------------------------

    def register(self, name: str, fn: Callable[..., Any],
                 lockfree: bool = False) -> None:
        """``lockfree=True`` ops dispatch without the serializing lock —
        only for handlers that are read-only and tolerate torn reads
        (liveness probes, load heuristics)."""
        self.ops[name] = fn
        if lockfree:
            self._lockfree_ops.add(name)

    def _handle(self, req: Any, conn: dict | None = None) -> dict:
        # A malformed request must produce an error reply, never kill
        # the connection (the client would block until timeout).
        conn = conn if conn is not None else {"trusted": False}
        try:
            if not isinstance(req, dict) or "op" not in req:
                raise ValueError("bad request")
            op = req["op"]
            kwargs = req.get("args") or {}
            if op == "auth":
                # Connection-level identity: the only way a connection
                # may later present a privileged subject.
                token = (kwargs or {}).get("token")
                if (self.auth_token is not None and isinstance(token, str)
                        and hmac.compare_digest(token, self.auth_token)):
                    conn["trusted"] = True
                    return {"ok": True, "result": True}
                raise PermissionError("bad or missing auth token")
            if op == "multicall":
                # xen/common/multicall.c: execute each entry in order; a
                # failing entry doesn't abort the batch — per-entry status.
                calls = req.get("calls", [])
                if not isinstance(calls, list) or not all(
                        isinstance(c, dict) for c in calls):
                    raise ValueError("multicall 'calls' must be a list of "
                                     "{op, args} objects")
                results = [self._call_one(c.get("op"), c.get("args") or {},
                                          conn)
                           for c in calls]
                return {"ok": True, "result": results}
            if not isinstance(kwargs, dict):
                raise ValueError("'args' must be an object")
            return self._call_one(op, kwargs, conn)
        except Exception as e:  # noqa: BLE001 — marshalled to caller
            return {"ok": False, "error": type(e).__name__, "message": str(e)}

    def _call_one(self, op: str, kwargs: dict,
                  conn: dict | None = None) -> dict:
        fn = self.ops.get(op)
        if fn is None:
            return {"ok": False, "error": "LookupError",
                    "message": f"unknown op {op!r}"}
        try:
            # Inside the try: a malformed entry (non-dict args) must
            # yield a per-entry error status, never abort a multicall.
            if not isinstance(kwargs, dict):
                raise ValueError("'args' must be an object")
            subj = kwargs.get("subject")
            if (isinstance(subj, str) and subj in self.privileged_subjects
                    and not (conn or {}).get("trusted")):
                raise PermissionError(
                    f"subject {subj!r} requires an authenticated "
                    "connection")
            if op in self._lockfree_ops:
                return {"ok": True, "result": fn(**kwargs)}
            with self._lock:
                return {"ok": True, "result": fn(**kwargs)}
        except Exception as e:  # noqa: BLE001 — marshalled to caller
            return {"ok": False, "error": type(e).__name__, "message": str(e)}

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "RpcServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True, name=f"rpc-server-{self.address[1]}",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        # shutdown() blocks on an acknowledgment from the serve_forever
        # loop — which never comes if start() was never called (the
        # stdlib primitive hangs forever). Only signal a loop that ran.
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()
        # Handler threads outlive shutdown(); sever their connections so
        # a stopped host really goes silent (heartbeats must fail).
        with self._conns_lock:
            conns = list(self._conns)
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            s.close()
        if self._thread is not None:
            self._thread.join(timeout=2)


class RpcClient:
    """Persistent connection to one RpcServer.

    ``auth_token`` (if given) is presented on every (re)connect, so the
    connection-level trust survives transparent reconnects."""

    def __init__(self, address: tuple[str, int], timeout_s: float = 5.0,
                 auth_token: str | None = None):
        self.address = (address[0], int(address[1]))
        self.timeout_s = timeout_s
        self.auth_token = auth_token
        self._sock: socket.socket | None = None
        # Serializes request/response pairs on the one socket; held
        # across the round trip BY DESIGN (framing would interleave
        # otherwise) — visible to lockprof as "rpc_client" so that
        # wait time shows up in contention stats instead of hiding.
        self._lock = ProfiledLock("rpc_client")

    def _ensure(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(self.address, timeout=self.timeout_s)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
            if self.auth_token is not None:
                send_msg(s, {"op": "auth",
                             "args": {"token": self.auth_token}})
                resp = recv_msg(s)
                if not resp.get("ok"):
                    self._sock = None
                    s.close()
                    raise RpcError("auth", resp.get("error", "?"),
                                   resp.get("message", ""))
        return self._sock

    def _roundtrip(self, req: dict, timeout_s: float | None = None) -> Any:
        with self._lock:
            try:
                sock = self._ensure()
                if timeout_s is not None:
                    sock.settimeout(timeout_s)
                try:
                    send_msg(sock, req)
                    return recv_msg(sock)
                finally:
                    if timeout_s is not None:
                        sock.settimeout(self.timeout_s)
            except (ConnectionError, OSError):
                self.close()
                raise

    def call(self, op: str, _timeout: float | None = None,
             **kwargs: Any) -> Any:
        """One op. ``_timeout`` overrides the connection timeout for this
        call only (long-running ops like agent ``run``)."""
        resp = self._roundtrip({"op": op, "args": kwargs},
                               timeout_s=_timeout)
        if not resp.get("ok"):
            raise RpcError(op, resp.get("error", "?"), resp.get("message", ""))
        return resp["result"]

    def multicall(self, calls: list[tuple[str, dict]]) -> list[Any]:
        """Batch of (op, kwargs) in one round trip; per-entry results.
        Raises only on transport failure — op errors come back in-band
        as ``{"ok": False, ...}`` entries, like multicall entry status."""
        resp = self._roundtrip({
            "op": "multicall",
            "calls": [{"op": op, "args": kw} for op, kw in calls],
        })
        if not resp.get("ok"):
            raise RpcError("multicall", resp.get("error", "?"),
                           resp.get("message", ""))
        return resp["result"]

    def try_ping(self) -> bool:
        try:
            return self.call("ping") == "pong"
        except Exception:  # noqa: BLE001 — liveness probe
            return False

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
