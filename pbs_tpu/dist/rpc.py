"""DCN control-plane RPC: length-prefixed JSON over TCP.

Reference mapping: the dom0 toolstack reaches the hypervisor through
privcmd ioctls -> hypercalls and reaches remote hosts over plain TCP
(live migration, ``tools/libxc/xc_domain_save.c``); batches of hypercalls
are issued through the multicall interface (``xen/common/multicall.c``)
to amortize boundary crossings. Here the boundary is the data-center
network between the controller and per-host agents, so the same three
ideas appear as: a tiny framed-JSON RPC (the hypercall ABI), a
server-side op table (the hypercall dispatch table,
``arch/x86/x86_64/entry.S:663-770``), and a first-class ``multicall``
op executing a batch in one round trip.

Deliberately dependency-free (stdlib sockets): the data plane never
touches this path — tensors move over ICI/DCN inside XLA collectives;
this carries only control messages, telemetry summaries, and checkpoint
metadata.
"""

from __future__ import annotations

import collections
import hmac
import itertools
import json
import os
import socket
import socketserver
import struct
import threading
import time
import zlib
from typing import Any, Callable

from pbs_tpu.faults import injector as faults
from pbs_tpu import knobs
from pbs_tpu.obs import console as _console
from pbs_tpu.obs.lockprof import ProfiledLock

MAX_MSG_BYTES = 64 << 20

# Transport retry/backoff envelope, declared in the knob registry
# (dist.rpc.*): the constructor defaults every client rides unless a
# caller overrides per-connection.
RPC_MAX_RETRIES = knobs.default("dist.rpc.max_retries")
RPC_BACKOFF_BASE_S = knobs.default("dist.rpc.backoff_base_s")
RPC_BACKOFF_CAP_S = knobs.default("dist.rpc.backoff_cap_s")
RPC_TIMEOUT_S = knobs.default("dist.rpc.timeout_s")
_LEN = struct.Struct(">I")

#: Process-unique client ids feeding idempotency-token prefixes.
_CLIENT_SEQ = itertools.count()


class RpcError(Exception):
    """Remote op raised; .remote_type / .remote_message carry details."""

    def __init__(self, op: str, remote_type: str, remote_message: str):
        super().__init__(f"{op}: {remote_type}: {remote_message}")
        self.op = op
        self.remote_type = remote_type
        self.remote_message = remote_message


def send_msg(sock: socket.socket, obj: Any) -> None:
    data = json.dumps(obj, separators=(",", ":")).encode()
    if len(data) > MAX_MSG_BYTES:
        raise ValueError(f"message too large: {len(data)} bytes")
    sock.sendall(_LEN.pack(len(data)) + data)


def recv_msg(sock: socket.socket) -> Any:
    hdr = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(hdr)
    if n > MAX_MSG_BYTES:
        raise ValueError(f"message too large: {n} bytes")
    return json.loads(_recv_exact(sock, n).decode())


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


class RpcServer:
    """Threaded TCP server with a registered op table.

    Dispatch is serialized by a single lock — the moral equivalent of
    entering the hypervisor: op handlers may freely mutate the hosted
    partition without their own locking.

    Subject trust model: XSM subjects in request args are *advisory
    labels* checked against the policy — except privileged subjects
    (``system`` by default, the label that bypasses every policy rule).
    Those are only honored on connections that authenticated with the
    server's ``auth_token`` (built-in ``auth`` op), so a remote caller
    cannot claim hypervisor identity through a request field the way a
    Xen domain cannot forge being dom0 (the subject there derives from
    the calling domain, not from hypercall payload). With no token
    configured, no connection can ever be privileged.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 auth_token: str | None = None,
                 privileged_subjects: frozenset[str] = frozenset({"system"}),
                 fault_key: str = "server"):
        self.ops: dict[str, Callable[..., Any]] = {}
        self.auth_token = auth_token
        self.privileged_subjects = privileged_subjects
        #: Logical name for fault-injection streams (``rpc.server`` point
        #: keys are ``<fault_key>:<op>``); agents pass their own name so
        #: chaos streams stay stable across runs (ports are ephemeral).
        self.fault_key = fault_key
        #: How long stop() waits for the serve_forever thread.
        self.join_timeout_s = 2.0
        self._lock = ProfiledLock("rpc_dispatch")
        # Exactly-once for retried mutations: replies are cached by the
        # caller's idempotency token, so a client retrying into us after
        # a lost reply gets the ORIGINAL reply instead of a re-execution
        # (the Remus ack model generalized to every op). Bounded LRU —
        # a retry storms within seconds, not hours.
        self._idem_lock = ProfiledLock("rpc_idem")
        self._idem_cache: collections.OrderedDict[str, dict] = (
            collections.OrderedDict())
        # Tokens whose op is STILL EXECUTING: the cache only fills on
        # completion, so without this a retry racing a slow op (the
        # per-attempt timeout fired mid-execution) would re-execute the
        # mutation. A duplicate parks on the event and replays.
        self._idem_inflight: dict[str, threading.Event] = {}
        self.idem_capacity = 1024
        self.idem_hits = 0
        #: Per-op real execution counts (dedup cache hits excluded) —
        #: the observable tests/chaos assert exactly-once against.
        self.op_executions: dict[str, int] = {}
        # Connection bookkeeping must never wait on the dispatch lock,
        # or a fresh ping connection blocks behind a long-running op.
        self._conns_lock = ProfiledLock("rpc_conns")
        self._conns: set[socket.socket] = set()
        # Liveness probes must answer while a long op holds the dispatch
        # lock — otherwise a busy host reads as dead and gets its jobs
        # double-placed (the NMI watchdog answers from interrupt context
        # for the same reason, xen/arch/x86/nmi.c).
        self._lockfree_ops = {"ping", "ops"}
        self.register("ping", lambda: "pong")
        self.register("ops", lambda: sorted(self.ops))

        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one connection = many requests
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                conn = {"trusted": False}  # connection-level identity
                with outer._conns_lock:
                    outer._conns.add(sock)
                try:
                    while True:
                        req = recv_msg(sock)
                        resp = outer._handle(req, conn)
                        # rpc.server injection point (reply path): the op
                        # already ran — 'crash' loses the reply and the
                        # connection, forcing the caller through its
                        # retry + idempotency machinery. Lockfree probes
                        # (ping/info) and auth are exempt: liveness must
                        # stay a transport-only signal.
                        op = req.get("op") if isinstance(req, dict) else None
                        if (isinstance(op, str) and op != "auth"
                                and op not in outer._lockfree_ops):
                            f = faults.consult(
                                "rpc.server", f"{outer.fault_key}:{op}")
                            if f is not None:
                                if f.fault == "crash":
                                    raise ConnectionResetError(
                                        "injected server crash")
                                if f.fault == "delay":
                                    time.sleep(float(
                                        f.args.get("delay_s", 0.001)))
                        send_msg(sock, resp)
                except (ConnectionError, OSError, ValueError):
                    return
                finally:
                    with outer._conns_lock:
                        outer._conns.discard(sock)

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.address: tuple[str, int] = self._server.server_address[:2]
        self._thread: threading.Thread | None = None

    # -- op table (hypercall registry) -----------------------------------

    def register(self, name: str, fn: Callable[..., Any],
                 lockfree: bool = False) -> None:
        """``lockfree=True`` ops dispatch without the serializing lock —
        only for handlers that are read-only and tolerate torn reads
        (liveness probes, load heuristics)."""
        self.ops[name] = fn
        if lockfree:
            self._lockfree_ops.add(name)

    def _handle(self, req: Any, conn: dict | None = None) -> dict:
        # Idempotency dedup wraps the whole dispatch: a token seen
        # before re-delivers the cached reply without touching the op
        # table, so a duplicated frame or a retry after a lost reply is
        # exactly-once. Tokens are client-generated and stable across
        # the retries of ONE call only. Lockfree probes (ping/info) are
        # exempt: they are read-only, retried freely, and caching their
        # replies would churn the mutation replies out of the LRU.
        tok = req.get("idem") if isinstance(req, dict) else None
        op = req.get("op") if isinstance(req, dict) else None
        if not isinstance(tok, str) or op in self._lockfree_ops:
            return self._handle_uncached(req, conn)
        while True:
            with self._idem_lock:
                hit = self._idem_cache.get(tok)
                if hit is not None:
                    self._idem_cache.move_to_end(tok)
                    self.idem_hits += 1
                    return hit
                ev = self._idem_inflight.get(tok)
                if ev is None:
                    ev = self._idem_inflight[tok] = threading.Event()
                    break
            # Another connection is executing this very token (a retry
            # overtook its own still-running first attempt): wait for
            # it to finish, then replay its reply from the cache —
            # never execute a mutation a second time.
            ev.wait()
        try:
            resp = self._handle_uncached(req, conn)
            with self._idem_lock:
                self._idem_cache[tok] = resp
                while len(self._idem_cache) > self.idem_capacity:
                    self._idem_cache.popitem(last=False)
            return resp
        finally:
            with self._idem_lock:
                self._idem_inflight.pop(tok, None)
            ev.set()

    def _handle_uncached(self, req: Any, conn: dict | None = None) -> dict:
        # A malformed request must produce an error reply, never kill
        # the connection (the client would block until timeout).
        conn = conn if conn is not None else {"trusted": False}
        try:
            if not isinstance(req, dict) or "op" not in req:
                raise ValueError("bad request")
            op = req["op"]
            kwargs = req.get("args") or {}
            if op == "auth":
                # Connection-level identity: the only way a connection
                # may later present a privileged subject.
                token = (kwargs or {}).get("token")
                if (self.auth_token is not None and isinstance(token, str)
                        and hmac.compare_digest(token, self.auth_token)):
                    conn["trusted"] = True
                    return {"ok": True, "result": True}
                raise PermissionError("bad or missing auth token")
            if op == "multicall":
                # xen/common/multicall.c: execute each entry in order; a
                # failing entry doesn't abort the batch — per-entry status.
                calls = req.get("calls", [])
                if not isinstance(calls, list) or not all(
                        isinstance(c, dict) for c in calls):
                    raise ValueError("multicall 'calls' must be a list of "
                                     "{op, args} objects")
                results = [self._call_one(c.get("op"), c.get("args") or {},
                                          conn)
                           for c in calls]
                return {"ok": True, "result": results}
            if not isinstance(kwargs, dict):
                raise ValueError("'args' must be an object")
            return self._call_one(op, kwargs, conn)
        except Exception as e:  # noqa: BLE001 — marshalled to caller
            return {"ok": False, "error": type(e).__name__, "message": str(e)}

    def _call_one(self, op: str, kwargs: dict,
                  conn: dict | None = None) -> dict:
        fn = self.ops.get(op)
        if fn is None:
            return {"ok": False, "error": "LookupError",
                    "message": f"unknown op {op!r}"}
        try:
            # Inside the try: a malformed entry (non-dict args) must
            # yield a per-entry error status, never abort a multicall.
            if not isinstance(kwargs, dict):
                raise ValueError("'args' must be an object")
            subj = kwargs.get("subject")
            if (isinstance(subj, str) and subj in self.privileged_subjects
                    and not (conn or {}).get("trusted")):
                raise PermissionError(
                    f"subject {subj!r} requires an authenticated "
                    "connection")
            if op in self._lockfree_ops:
                self.op_executions[op] = self.op_executions.get(op, 0) + 1
                return {"ok": True, "result": fn(**kwargs)}
            with self._lock:
                # Counted under the dispatch lock: mutating-op execution
                # counts are the exactly-once evidence and must be exact.
                self.op_executions[op] = self.op_executions.get(op, 0) + 1
                return {"ok": True, "result": fn(**kwargs)}
        except Exception as e:  # noqa: BLE001 — marshalled to caller
            return {"ok": False, "error": type(e).__name__, "message": str(e)}

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "RpcServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True, name=f"rpc-server-{self.address[1]}",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        # shutdown() blocks on an acknowledgment from the serve_forever
        # loop — which never comes if start() was never called (the
        # stdlib primitive hangs forever). Only signal a loop that ran.
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()
        # Handler threads outlive shutdown(); sever their connections so
        # a stopped host really goes silent (heartbeats must fail).
        with self._conns_lock:
            conns = list(self._conns)
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            s.close()
        if self._thread is not None:
            self._thread.join(timeout=self.join_timeout_s)
            if self._thread.is_alive():
                # A leaked serve_forever thread means a handler is
                # wedged and the port stays half-alive — silently
                # dropping that hid real hangs; say so where operators
                # look (the system console ring, pbs_tpu.obs.console).
                _console.log(
                    f"rpc-server {self.address[0]}:{self.address[1]} "
                    f"({self.fault_key}): thread failed to join within "
                    f"{self.join_timeout_s:.1f}s; leaking daemon thread")


class RpcClient:
    """Persistent connection to one RpcServer.

    ``auth_token`` (if given) is presented on every (re)connect, so the
    connection-level trust survives transparent reconnects.

    Transport failures (drop, reset, timeout) are absorbed by bounded
    retries with capped exponential backoff and *deterministic* jitter
    (derived from (fault_key, op, attempt) — no RNG state, so chaos
    runs replay); every request carries an idempotency token the server
    deduplicates, making a retried mutating op exactly-once. A per-op
    deadline (``deadline_s`` / per-call ``_deadline``) bounds the whole
    retry loop. ``fault_key`` is the logical stream label for the
    ``rpc.client`` injection point — callers use stable names (agent
    name, not host:port) so seeded chaos runs are reproducible.
    """

    def __init__(self, address: tuple[str, int],
                 timeout_s: float = RPC_TIMEOUT_S,
                 auth_token: str | None = None, fault_key: str = "client",
                 max_retries: int = RPC_MAX_RETRIES,
                 backoff_base_s: float = RPC_BACKOFF_BASE_S,
                 backoff_cap_s: float = RPC_BACKOFF_CAP_S,
                 deadline_s: float | None = None):
        self.address = (address[0], int(address[1]))
        self.timeout_s = timeout_s
        self.auth_token = auth_token
        self.fault_key = fault_key
        self.max_retries = int(max_retries)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.deadline_s = deadline_s
        self.retries = 0  # transport retries performed (observability)
        # Random component: token prefixes must be unguessable (a
        # guessable token lets another connection replay or pre-poison
        # a cached reply) and collision-free across process restarts
        # (pid reuse + a reset counter would resurrect a dead
        # incarnation's cached replies). os.urandom touches no seeded
        # RNG, so chaos-run determinism is unaffected.
        self._idem_prefix = (f"{os.getpid():x}.{next(_CLIENT_SEQ):x}."
                             f"{os.urandom(8).hex()}")
        self._idem_seq = itertools.count()
        self._sock: socket.socket | None = None
        # Serializes request/response pairs on the one socket; held
        # across the round trip BY DESIGN (framing would interleave
        # otherwise) — visible to lockprof as "rpc_client" so that
        # wait time shows up in contention stats instead of hiding.
        self._lock = ProfiledLock("rpc_client")

    def _token(self) -> str:
        return f"{self._idem_prefix}.{next(self._idem_seq):x}"

    def _backoff(self, op: str, attempt: int) -> float:
        """Capped exponential backoff with deterministic jitter in
        [0.5, 1.0)× — a hash of (fault_key, op, attempt), not RNG
        state, so two same-seed chaos runs sleep identically."""
        base = min(self.backoff_cap_s,
                   self.backoff_base_s * (2 ** (attempt - 1)))
        h = zlib.crc32(f"{self.fault_key}:{op}:{attempt}".encode())
        return base * (0.5 + (h % 1024) / 2048.0)

    def _ensure(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(self.address, timeout=self.timeout_s)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
            if self.auth_token is not None:
                send_msg(s, {"op": "auth",
                             "args": {"token": self.auth_token}})
                resp = recv_msg(s)
                if not resp.get("ok"):
                    self._sock = None
                    s.close()
                    raise RpcError("auth", resp.get("error", "?"),
                                   resp.get("message", ""))
        return self._sock

    def _roundtrip(self, req: dict, timeout_s: float | None = None) -> Any:
        op = req.get("op", "?")
        # Consult the injector BEFORE taking the round-trip lock: a
        # 'delay' fault sleeps here, and sleeping under the lock would
        # be exactly the lock-blocking pathology pbst check hunts.
        fault = faults.consult("rpc.client", f"{self.fault_key}:{op}")
        if fault is not None and fault.fault == "delay":
            time.sleep(float(fault.args.get("delay_s", 0.001)))
            fault = None
        with self._lock:
            try:
                if fault is not None and fault.fault == "reset":
                    self.close()
                    raise ConnectionResetError("injected connection reset")
                if fault is not None and fault.fault == "drop_request":
                    # The frame vanished on the wire; the caller's read
                    # would time out — simulated without the wait. The
                    # socket dies with it (see the except note below).
                    self.close()
                    raise socket.timeout("injected request drop")
                sock = self._ensure()
                if timeout_s is not None:
                    sock.settimeout(timeout_s)
                try:
                    if fault is not None and fault.fault == "garble":
                        # Valid length header, corrupt body: the server
                        # kills the stream, we read the close.
                        payload = b'\x16{"__garbled frame__'
                        sock.sendall(_LEN.pack(len(payload)) + payload)
                        return recv_msg(sock)
                    send_msg(sock, req)
                    if fault is not None and fault.fault == "duplicate":
                        # Retransmit: two frames land server-side. Both
                        # replies must be drained or every later call
                        # reads its predecessor's reply; the idem cache
                        # makes the second a non-execution.
                        send_msg(sock, req)
                        recv_msg(sock)
                        return recv_msg(sock)
                    resp = recv_msg(sock)
                    if fault is not None and fault.fault == "drop_reply":
                        self.close()
                        raise socket.timeout("injected reply drop")
                    return resp
                finally:
                    if timeout_s is not None and self._sock is not None:
                        try:
                            self._sock.settimeout(self.timeout_s)
                        except OSError:  # closed/reset mid-call
                            pass
            except (ConnectionError, socket.timeout, OSError):
                # A timeout mid-frame leaves the stream desynced (a
                # partial send/recv cannot be resumed): the socket must
                # die with the call, or every later reply on the reused
                # connection would be parsed against the wrong length
                # header. socket.timeout is spelled out even though
                # 3.10+ folds it into OSError — this line IS the
                # contract, not an accident of the exception tree.
                self.close()
                raise

    def _call_raw(self, req: dict, op: str,
                  _timeout: float | None = None,
                  _deadline: float | None = None) -> dict:
        """Shared retry loop: bounded attempts, capped backoff with
        deterministic jitter, overall deadline. Only transport errors
        retry — an in-band op error means the server executed and
        answered, and re-executing is the caller's decision."""
        budget = self.deadline_s if _deadline is None else _deadline
        deadline = None if budget is None else time.monotonic() + budget
        attempt = 0
        while True:
            try:
                t = _timeout
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise socket.timeout(f"{op}: deadline exhausted")
                    t = min(t if t is not None else self.timeout_s, left)
                return self._roundtrip(req, timeout_s=t)
            except (ConnectionError, socket.timeout, OSError):
                attempt += 1
                if attempt > self.max_retries:
                    raise
                if deadline is not None and time.monotonic() >= deadline:
                    raise
                self.retries += 1
                delay = self._backoff(op, attempt)
                if deadline is not None:
                    # Clamp the sleep to the remaining budget: a capped
                    # backoff larger than what's left would overshoot
                    # the deadline by up to a whole backoff period —
                    # the loop must wake AT the deadline and raise, not
                    # after it (supervisor pumps schedule against this).
                    delay = min(delay, max(0.0, deadline - time.monotonic()))
                time.sleep(delay)

    def call(self, op: str, _timeout: float | None = None,
             _deadline: float | None = None, **kwargs: Any) -> Any:
        """One op, exactly-once. ``_timeout`` overrides the per-attempt
        socket timeout (long-running ops like agent ``run``);
        ``_deadline`` bounds the WHOLE call including retries (default
        ``self.deadline_s``). The request carries an idempotency token
        stable across its retries, so a retry after a lost reply
        re-delivers the original result instead of re-executing."""
        req = {"op": op, "args": kwargs, "idem": self._token()}
        resp = self._call_raw(req, op, _timeout=_timeout,
                              _deadline=_deadline)
        if not resp.get("ok"):
            raise RpcError(op, resp.get("error", "?"), resp.get("message", ""))
        return resp["result"]

    def multicall(self, calls: list[tuple[str, dict]]) -> list[Any]:
        """Batch of (op, kwargs) in one round trip; per-entry results.
        Raises only on transport failure (after retries) — op errors
        come back in-band as ``{"ok": False, ...}`` entries, like
        multicall entry status. One idempotency token covers the whole
        batch: a retried multicall replays the cached entry statuses."""
        req = {
            "op": "multicall",
            "calls": [{"op": op, "args": kw} for op, kw in calls],
            "idem": self._token(),
        }
        resp = self._call_raw(req, "multicall")
        if not resp.get("ok"):
            raise RpcError("multicall", resp.get("error", "?"),
                           resp.get("message", ""))
        return resp["result"]

    def try_ping(self) -> bool:
        try:
            return self.call("ping") == "pong"
        except Exception:  # noqa: BLE001 — liveness probe
            return False

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
