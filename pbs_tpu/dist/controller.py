"""Cluster controller: placement, gang coordination, failure recovery.

Reference mapping: the controller is the cluster-wide analog of the
toolstack brain — ``xl``/xend issuing domain lifecycle and scheduler
ops (``tools/libxl/xl_cmdimpl.c:4805-4896``), plus the pieces the
reference only has in single-host form, generalized across hosts:

- *Placement* re-expresses the atc variant's least-loaded, anti-stacking
  vCPU placement (``sched_credit_atc.c:545-570``): gang members are
  never co-located on one host, because a gang spanning hosts dies by
  lock-holder preemption if any one host stalls (SURVEY.md §7 risks).
- *Gang rounds* are barrier-coordinated lockstep quanta across agents —
  the distributed form of "never split a ring across a preemption
  boundary".
- *Failure detection* is the xenwatchdogd / heartbeat analog
  (``tools/misc/xenwatchdogd.c``): agents that miss pings are declared
  dead and their jobs re-placed on live hosts (recovery = restore
  elsewhere, exactly the reference's Remus model, ``tools/remus``).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any

from pbs_tpu.dist.rpc import RpcClient, RpcError
from pbs_tpu.utils.clock import SEC, MonotonicClock


class ClusterRoundError(RuntimeError):
    """One or more agents failed during a lockstep round."""

    def __init__(self, errors: dict[str, Exception], quanta: dict[str, int]):
        super().__init__(
            "round failed on " + ", ".join(sorted(errors)))
        self.errors = errors
        self.quanta = quanta


@dataclasses.dataclass
class AgentHandle:
    name: str
    client: RpcClient  # control ops (may be busy for a whole round)
    probe: RpcClient  # liveness pings only — never blocked behind ops
    address: tuple[str, int] = ("", 0)
    alive: bool = True
    missed: int = 0
    info: dict = dataclasses.field(default_factory=dict)
    # Circuit breaker (degraded-mode quarantine): a host that answers
    # pings but keeps faulting ops is ALIVE-but-untrustworthy — killing
    # it would re-place jobs that are fine; keeping it in rounds burns
    # every round on its failures. 'open' = quarantined (no ops, no
    # placement), 'half_open' = one probe round decides.
    consecutive_faults: int = 0
    breaker: str = "closed"  # closed | open | half_open
    breaker_cooldown: int = 0
    #: When the controller last OBSERVED this agent (heartbeat answered
    #: or missed, op completed or faulted — any interaction that
    #: informed alive/breaker state). 0 = never observed. The
    #: backend_health() staleness stamp derives from this.
    observed_ns: int = 0
    #: Backend attribution (docs/TRACING.md): observed service-time p99
    #: for the co-named serving backend, published by a gateway's
    #: histogram export (``note_backend_service``). 0 = never reported.
    service_p99_ns: int = 0


@dataclasses.dataclass
class MemberRef:
    agent: str
    job: str  # job name on that agent


@dataclasses.dataclass
class JobRecord:
    """Controller-side record of a (possibly multi-host) job."""

    name: str
    workload: str
    spec: dict
    members: list[MemberRef]
    gang: bool = False
    # Remus: member job name -> backup agent name (replication enabled)
    replica_peers: dict[str, str] = dataclasses.field(default_factory=dict)
    replica_period_s: float = 0.5


class Controller:
    def __init__(self, dead_after_missed: int = 2,
                 subject: str = "controller",
                 auth_token: str | None = None,
                 breaker_threshold: int = 3,
                 breaker_cooldown: int = 2,
                 clock=None,
                 health_ttl_ns: int | None = None):
        self.agents: dict[str, AgentHandle] = {}
        self.jobs: dict[str, JobRecord] = {}
        self.dead_after_missed = dead_after_missed
        #: Consecutive op faults before an agent is quarantined, and how
        #: many healthy heartbeats an open breaker waits before the
        #: half-open probe round.
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown
        #: Observation-time source (injectable for deterministic tests).
        self.clock = clock if clock is not None else MonotonicClock()
        #: Staleness bound on the backend_health() view: an agent not
        #: observed within this window reads "stale" and the gateway
        #: treats its entry as unknown instead of trusting it. Default:
        #: the breaker's half-open window — ``breaker_cooldown``
        #: heartbeats at the nominal 1 Hz heartbeat cadence.
        self.health_ttl_ns = (int(health_ttl_ns) if health_ttl_ns is not None
                              else max(1, int(breaker_cooldown)) * SEC)
        #: Lease authority for the federated gateway tier — attached by
        #: FederatedGateway (gateway/federation.py) so per-tenant
        #: token-bucket levels are leased through the controller.
        self.admission_broker = None
        self.last_round_errors: dict[str, Exception] = {}
        # XSM identity presented on every job-mutating agent op; under
        # an enforcing agent policy, grant this label (or pass your own).
        # Privileged subjects additionally require ``auth_token`` to
        # match the agents' token (connection-level trust, rpc.py).
        self.subject = subject
        self.auth_token = auth_token

    # -- membership ------------------------------------------------------

    @staticmethod
    def _check_name(kind: str, name: str) -> None:
        # Names become Store path segments in save_state; a '/' would
        # silently splinter the persisted record (and an empty name is
        # unaddressable everywhere).
        if not name or "/" in name:
            raise ValueError(f"invalid {kind} name {name!r}: must be "
                             "non-empty and contain no '/'")

    def add_agent(self, name: str, address: tuple[str, int]) -> AgentHandle:
        self._check_name("agent", name)
        # fault_key: LOGICAL labels (ports are ephemeral — chaos streams
        # keyed by them would reseed every run). Probes never retry: a
        # missed ping must stay a missed ping or dead-host detection
        # stretches by the whole retry budget.
        # deadline_s bounds the WHOLE retry loop (retries included) —
        # a flaky agent must shed a control op, not pin the controller.
        h = AgentHandle(name, RpcClient(address, auth_token=self.auth_token,
                                        fault_key=name, deadline_s=30.0),
                        probe=RpcClient(address, timeout_s=2.0,
                                        auth_token=self.auth_token,
                                        fault_key=f"{name}/probe",
                                        max_retries=0, deadline_s=2.0),
                        address=(address[0], int(address[1])))
        h.info = h.client.call("info")
        h.observed_ns = self.clock.now_ns()
        self.agents[name] = h
        return h

    def live_agents(self) -> list[AgentHandle]:
        return [h for h in self.agents.values() if h.alive]

    def available_agents(self) -> list[AgentHandle]:
        """Live agents not quarantined by the circuit breaker.
        Half-open agents are included — they carry the probe op that
        decides whether the breaker closes."""
        return [h for h in self.live_agents() if h.breaker != "open"]

    # -- circuit breaker (degraded-mode quarantine) ----------------------

    def _op_fault(self, h: AgentHandle) -> None:
        """An op on ``h`` failed (in-band error or transport gave up
        after retries). Enough consecutive faults — or one fault on a
        half-open probe — quarantines the host."""
        h.consecutive_faults += 1
        h.observed_ns = self.clock.now_ns()  # a fault IS an observation
        if (h.breaker == "half_open"
                or h.consecutive_faults >= self.breaker_threshold):
            h.breaker = "open"
            h.breaker_cooldown = self.breaker_cooldown

    def _op_ok(self, h: AgentHandle) -> None:
        h.consecutive_faults = 0
        h.breaker = "closed"
        h.observed_ns = self.clock.now_ns()

    def _op(self, h: AgentHandle, op: str, **kwargs: Any) -> Any:
        """A mutating agent op with breaker bookkeeping: EVERY op path
        feeds the quarantine, not just run_round — a host whose
        create_job/migrate/replicate keep faulting must stop taking
        placements just like one whose rounds fault. Re-raises
        unchanged, so call-site error semantics are untouched."""
        try:
            r = h.client.call(op, **kwargs)
        except Exception:
            self._op_fault(h)
            raise
        self._op_ok(h)
        return r

    # -- failure detection (xenwatchdogd analog) -------------------------

    def heartbeat(self) -> dict[str, bool]:
        """Ping every agent once (concurrently — a hung host must not
        delay detection of the others); mark dead after N consecutive
        misses. Pings ride each handle's dedicated probe connection and
        the server answers them lock-free, so a host busy in a long
        ``run`` op still reads alive. Returns {agent: alive}."""

        def _beat(h: AgentHandle) -> None:
            # Either outcome is an observation: the view's freshness is
            # about how recently we LOOKED, not about what we saw.
            h.observed_ns = self.clock.now_ns()
            if h.probe.try_ping():
                if not h.alive and not self._reconcile(h):
                    # Fence failed: keep it dead; a later heartbeat
                    # retries the fence before readmission.
                    return
                h.missed = 0
                h.alive = True
                if h.breaker == "open":
                    # Healthy transport ticks the quarantine down; at
                    # zero the breaker half-opens and the next round
                    # carries the probe op.
                    h.breaker_cooldown -= 1
                    if h.breaker_cooldown <= 0:
                        h.breaker = "half_open"
            else:
                h.missed += 1
                if h.missed >= self.dead_after_missed:
                    h.alive = False

        self._fanout(list(self.agents.values()), _beat)
        return {name: h.alive for name, h in self.agents.items()}

    def _reconcile(self, h: AgentHandle) -> bool:
        """Remove jobs on ``h`` the controller no longer maps there.
        Returns True only if the host is verifiably clean — an agent
        declared dead may have had its jobs re-placed by recover(), and
        readmitting it with a stale member still running is split-brain
        (the failure mode Remus fences with its commit protocol,
        tools/remus)."""
        expected = {m.job for rec in self.jobs.values()
                    for m in rec.members if m.agent == h.name}
        try:
            present = {j["job"] for j in h.client.call("list_jobs")}
            stale = present - expected
            if stale:
                results = h.client.multicall(
                    [("remove_job", {"job": j, "subject": self.subject})
                     for j in sorted(stale)])
                if not all(r.get("ok") for r in results):
                    return False
        except Exception:  # noqa: BLE001 — it may have died again
            h.missed += 1
            return False
        return True

    # -- placement -------------------------------------------------------

    def _load(self, h: AgentHandle) -> tuple[int, int]:
        """Placement heuristic only — a failed info read ranks the host
        last but NEVER counts toward liveness. Rides the probe
        connection (short timeout, never queued behind a long ``run`` op
        on the shared control connection) and the server answers info
        lock-free, so one busy host cannot stall place()/recover()."""
        try:
            info = h.probe.call("info")
            h.info = info
            return (info["n_contexts"], info["n_jobs"])
        except Exception:  # noqa: BLE001 — rank last, don't condemn
            return (1 << 30, 1 << 30)

    def _ranked_live(self, candidates: list[AgentHandle]) -> list[AgentHandle]:
        # Collect loads concurrently (one wedged probe adds its timeout
        # once, not once per comparison in a serial sorted(key=...)).
        loads: dict[str, tuple[int, int]] = {}

        def _collect(h: AgentHandle) -> None:
            loads[h.name] = self._load(h)

        self._fanout(candidates, _collect)
        ranked = sorted(candidates, key=lambda h: loads[h.name])
        return [h for h in ranked if h.alive]

    @staticmethod
    def _fanout(handles: list[AgentHandle], fn) -> None:
        threads = [threading.Thread(target=fn, args=(h,), daemon=True)
                   for h in handles]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def place(self, n: int, distinct: bool = False) -> list[AgentHandle]:
        """Pick n target agents, least-loaded first. ``distinct`` forces
        n different hosts (gang anti-stacking); otherwise hosts repeat in
        load order. Quarantined (breaker-open) hosts never take new
        placements."""
        live = self.available_agents()
        if not live:
            raise RuntimeError("no live agents")
        ranked = self._ranked_live(live)
        if not ranked:
            raise RuntimeError("no live agents")
        if distinct:
            if len(ranked) < n:
                raise RuntimeError(
                    f"gang of {n} needs {n} live hosts, have {len(ranked)}")
            return ranked[:n]
        return [ranked[i % len(ranked)] for i in range(n)]

    # -- job lifecycle ---------------------------------------------------

    def create_job(
        self,
        name: str,
        workload: str = "sim",
        spec: dict | None = None,
        n_members: int = 1,
        gang: bool = False,
    ) -> JobRecord:
        """Create a job with ``n_members`` member jobs placed across
        agents; gang members land on distinct hosts."""
        self._check_name("job", name)
        if name in self.jobs:
            raise ValueError(f"job {name!r} already exists")
        spec = dict(spec or {})
        targets = self.place(n_members, distinct=gang and n_members > 1)
        members: list[MemberRef] = []
        try:
            for i, h in enumerate(targets):
                member_name = name if n_members == 1 else f"{name}.{i}"
                self._op(h, "create_job", job=member_name,
                         workload=workload, spec=spec,
                         subject=self.subject)
                members.append(MemberRef(h.name, member_name))
        except Exception:
            # Roll back already-placed members so a failed fan-out
            # leaves no orphans and the name stays retryable.
            for m in members:
                try:
                    self._op(self.agents[m.agent],
                             "remove_job", job=m.job, subject=self.subject)
                except Exception:  # noqa: BLE001 — host may be dead too
                    pass
            raise
        rec = JobRecord(name, workload, spec, members, gang=gang)
        self.jobs[name] = rec
        return rec

    def remove_job(self, name: str) -> None:
        if self.jobs.get(name) is not None and self.jobs[name].replica_peers:
            self.disable_replication(name)  # stop pumps, drop replicas
        rec = self.jobs.pop(name)
        for m in rec.members:
            h = self.agents.get(m.agent)
            if h is None or not h.alive:
                continue
            try:
                self._op(h, "remove_job", job=m.job, subject=self.subject)
            except Exception:  # noqa: BLE001 — host may have just died
                pass

    def sched_setparams(self, name: str, **params: Any) -> None:
        """One batched multicall per agent (the multicall.c pattern)."""
        rec = self.jobs[name]
        by_agent: dict[str, list] = {}
        for m in rec.members:
            by_agent.setdefault(m.agent, []).append(
                ("sched_setparams",
                 {"job": m.job, "subject": self.subject, **params}))
        for agent, calls in by_agent.items():
            for call, r in zip(
                    calls, self.agents[agent].client.multicall(calls)):
                if not r.get("ok"):
                    raise RpcError(f"{agent}:{call[0]}",
                                   r.get("error", "?"), r.get("message", ""))

    def migrate_job(self, name: str, to: str | None = None) -> dict[str, str]:
        """Live-migrate a job's members off their current hosts (``xl
        migrate``: save on source, ship over DCN, restore on target,
        tear down source). Telemetry counters travel with each member —
        fixing the reference's silent PMU-state reset (SURVEY.md §5).

        ``to`` pins every member to one named agent; otherwise each
        member goes to the least-loaded *other* live host (gang members
        keep anti-stacking). On restore failure the source copy is
        unpaused and keeps running — migration never destroys the only
        good copy."""
        rec = self.jobs[name]
        if to is not None and rec.gang and len(rec.members) > 1:
            # create_job enforces distinct hosts per gang member
            # (place(distinct=True)); a pin-everything migrate would
            # silently co-locate the gang and break the barrier model.
            raise ValueError(
                f"gang job {name!r} has {len(rec.members)} members; "
                "cannot pin them all to one host — migrate without 'to'")
        moved: dict[str, str] = {}
        for m in rec.members:
            src = self.agents[m.agent]
            if to is not None:
                dst = self.agents[to]
                if not dst.alive:
                    raise RuntimeError(f"target agent {to!r} is dead")
            else:
                exclude = {m.agent}
                if rec.gang:
                    exclude |= {mm.agent for mm in rec.members}
                ranked = self._ranked_live(
                    [h for h in self.available_agents()
                     if h.name not in exclude])
                if not ranked:
                    raise RuntimeError(f"no live migration target for "
                                       f"{rec.name}/{m.job}")
                dst = ranked[0]
            if dst.name == m.agent:
                continue
            saved = self._op(src, "save_job", job=m.job,
                             subject=self.subject)
            try:
                self._op(dst, "restore_job", job=m.job,
                         workload=rec.workload, spec=rec.spec,
                         saved=saved, subject=self.subject)
            except Exception:
                # Abort: resume the source copy (xl migrate's abort path
                # leaves the domain running at the source).
                src.client.call("unpause_job", job=m.job,
                                subject=self.subject)
                raise
            try:
                self._op(src, "remove_job", job=m.job,
                         subject=self.subject)
            except Exception:  # noqa: BLE001 — source may have died; the
                pass  # reconcile fence removes the stale copy later
            m.agent = dst.name
            moved[m.job] = dst.name
            # Replication does not survive the source teardown
            # (remove_job stops the pump): drop the now-stale replica —
            # a failover must never restore pre-migration state — and
            # re-arm from the new home so protection continues.
            self._drop_and_rearm(rec, m)
        return moved

    def _drop_and_rearm(self, rec: JobRecord, m: MemberRef) -> None:
        """After a member changed homes: retire the old (now stale)
        replica and restart replication from the new home. Best-effort
        on both legs; failure leaves the member VISIBLY unprotected
        (absent from replica_peers, replicate_status == [])."""
        old_peer = rec.replica_peers.pop(m.job, None)
        if old_peer is None:
            return
        ph = self.agents.get(old_peer)
        if ph is not None and ph.alive:
            try:
                ph.client.call("drop_replica", job=m.job,
                               subject=self.subject)
            except Exception:  # noqa: BLE001 — backup may be dead
                pass
        try:
            self._replicate_member(rec, m, rec.replica_period_s)
        except Exception:  # noqa: BLE001 — no eligible backup host
            pass

    # -- Remus replication (tools/remus: continuous backup) --------------

    def enable_replication(self, name: str, period_s: float = 0.5,
                           to: str | None = None) -> dict[str, str]:
        """Continuously replicate each member of ``name`` to a backup
        host (``to`` pins one; default: least-loaded live host that is
        neither the member's home nor, for gangs, a sibling's home).
        Returns {member job: backup agent}. The first epoch ships
        synchronously, so on return every member has a committed
        replica somewhere else."""
        rec = self.jobs[name]
        peers: dict[str, str] = {}
        for m in rec.members:
            peers[m.job] = self._replicate_member(rec, m, period_s, to)
        rec.replica_period_s = period_s
        return peers

    def _replicate_member(self, rec: JobRecord, m: MemberRef,
                          period_s: float, to: str | None = None) -> str:
        src = self.agents[m.agent]
        if to is not None:
            dst = self.agents[to]
            if dst.name == m.agent:
                raise ValueError(
                    f"backup host {to!r} is {m.job}'s own home")
            if not dst.alive:
                raise RuntimeError(f"backup agent {to!r} is dead")
        else:
            exclude = {m.agent}
            if rec.gang:
                # Anti-stacking extends to the backups: siblings' homes
                # AND siblings' backup peers, else one double failure
                # funnels two gang members onto the same host.
                exclude |= {mm.agent for mm in rec.members}
                exclude |= {p for j, p in rec.replica_peers.items()
                            if j != m.job}
            ranked = self._ranked_live(
                [h for h in self.available_agents()
                 if h.name not in exclude])
            if not ranked:
                raise RuntimeError(
                    f"no live backup host for {rec.name}/{m.job}")
            dst = ranked[0]
        self._op(src, "replicate_start", job=m.job, peer_host=dst.address[0],
                 peer_port=dst.address[1], period_s=period_s,
                 subject=self.subject)
        rec.replica_peers[m.job] = dst.name
        return dst.name

    def disable_replication(self, name: str) -> None:
        rec = self.jobs[name]
        for m in rec.members:
            h = self.agents.get(m.agent)
            if h is not None and h.alive and m.job in rec.replica_peers:
                try:
                    h.client.call("replicate_stop", job=m.job,
                                  subject=self.subject)
                except Exception:  # noqa: BLE001 — source may be dead
                    pass
            peer = rec.replica_peers.pop(m.job, None)
            ph = self.agents.get(peer) if peer else None
            if ph is not None and ph.alive:
                try:
                    ph.client.call("drop_replica", job=m.job,
                                   subject=self.subject)
                except Exception:  # noqa: BLE001 — backup may be dead
                    pass

    def _find_replica(self, job: str, preferred: str | None
                      ) -> tuple[AgentHandle, dict] | None:
        """Newest committed replica of ``job`` on a live host. Queries
        ride each handle's probe connection and fan out concurrently —
        recovery must not queue behind one busy host's control
        connection (the heartbeat/_load lesson: one wedged host adds
        its timeout once, not serially). The recorded backup wins ties
        so a split-brain pair of equal epochs restores predictably."""
        candidates = self.live_agents()
        found: dict[str, dict] = {}

        def _ask(h: AgentHandle) -> None:
            try:
                r = h.probe.call("get_replica", job=job,
                                 subject=self.subject)
            except Exception:  # noqa: BLE001 — host may be dying
                return
            if r is not None:
                found[h.name] = r

        self._fanout(candidates, _ask)
        best: tuple[AgentHandle, dict] | None = None
        for h in candidates:
            r = found.get(h.name)
            if r is None:
                continue
            if (best is None or r["epoch"] > best[1]["epoch"]
                    or (r["epoch"] == best[1]["epoch"]
                        and h.name == preferred)):
                best = (h, r)
        return best

    # -- gang rounds (barrier-coordinated lockstep) ----------------------

    def run_round(self, max_rounds: int = 64,
                  strict: bool = True) -> dict[str, int]:
        """One cluster round: every live agent runs up to ``max_rounds``
        scheduler rounds concurrently, with a barrier at the end — no
        agent starts round k+1 until all finished round k. This is the
        distributed gang-switch: a ring job spanning hosts advances in
        lockstep, so no member outruns a preempted peer.

        A failed agent breaks the lockstep guarantee, so with
        ``strict`` (default) the round raises :class:`ClusterRoundError`
        after the barrier; the caller heartbeats/recovers and retries.
        With ``strict=False`` errors are kept on ``self.last_round_errors``
        and surviving agents' quanta are returned."""
        quanta: dict[str, int] = {}
        errs: dict[str, Exception] = {}

        def _one(h: AgentHandle) -> None:
            try:
                quanta[h.name] = h.client.call(
                    "run", _timeout=600.0, max_rounds=max_rounds)
                self._op_ok(h)
            except RpcError as e:
                # The host ANSWERED: it is alive, the op faulted. Only
                # the breaker reacts — counting this toward liveness
                # would re-place jobs off a host that is still running
                # them (the split-brain the reconcile fence exists for).
                errs[h.name] = e
                self._op_fault(h)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errs[h.name] = e
                self._op_fault(h)
                h.missed += 1
                if h.missed >= self.dead_after_missed:
                    h.alive = False

        # Quarantined agents sit rounds out (their jobs stall — the
        # degraded mode); half-open agents run as their own probe.
        self._fanout(self.available_agents(), _one)  # join = the barrier
        self.last_round_errors = errs
        if errs and strict:
            raise ClusterRoundError(errs, quanta)
        return quanta

    def run_rounds(self, n: int, max_rounds: int = 64,
                   strict: bool = True) -> int:
        total = 0
        for _ in range(n):
            total += sum(
                self.run_round(max_rounds=max_rounds, strict=strict).values())
        return total

    # -- recovery (Remus model: restore elsewhere) -----------------------

    def recover(self) -> list[str]:
        """Re-place member jobs stranded on dead agents. Returns the
        names of jobs that were moved.

        Replicated members fail over to their newest committed replica
        (``restore_job`` from the shipped record — steps, telemetry
        counters, and sched params survive, the full Remus promise);
        unreplicated members restart fresh from their spec, exactly
        what an unprotected domain loses on host death. Where possible
        replication is re-armed from the new home so the job isn't left
        permanently unprotected after one failover."""
        moved = []
        for rec in self.jobs.values():
            for m in rec.members:
                h = self.agents.get(m.agent)
                if h is not None and h.alive:
                    continue
                live = self.available_agents()
                if not live:
                    raise RuntimeError(f"no live host for {rec.name}/{m.job}")

                replica = self._find_replica(
                    m.job, rec.replica_peers.get(m.job))
                if replica is not None:
                    # Failover target = the host already holding the
                    # state (restoring elsewhere would copy it twice) —
                    # UNLESS gang anti-stacking forbids it: the saved
                    # record is portable, so a sibling-occupied holder
                    # ships it to a clean host instead of co-locating
                    # gang members (the invariant create_job and the
                    # from-spec branch both enforce).
                    holder, r = replica
                    target = holder
                    if rec.gang:
                        sibling_homes = {mm.agent for mm in rec.members
                                         if mm is not m}
                        if holder.name in sibling_homes:
                            ranked = self._ranked_live(
                                [a for a in live
                                 if a.name not in sibling_homes])
                            if not ranked:
                                raise RuntimeError(
                                    f"no anti-stacking host for "
                                    f"{rec.name}/{m.job}")
                            target = ranked[0]
                    self._op(target, "restore_job", job=m.job,
                             workload=rec.workload, spec=rec.spec,
                             saved=r["saved"], subject=self.subject)
                    holder.client.call("drop_replica", job=m.job,
                                       subject=self.subject)
                else:
                    # Prefer a host with no sibling (anti-stacking); fall
                    # back to least-loaded when the cluster has shrunk
                    # below the gang width — same fallback as
                    # anti_stack_pick returning None
                    # (sched_credit_atc.c:545-570).
                    exclude = {mm.agent for mm in rec.members if mm is not m}
                    candidates = [a for a in live
                                  if not (rec.gang and a.name in exclude)]
                    ranked = self._ranked_live(candidates or live)
                    if not ranked:
                        raise RuntimeError(
                            f"no live host for {rec.name}/{m.job}")
                    target = ranked[0]
                    self._op(target, "create_job", job=m.job,
                             workload=rec.workload, spec=rec.spec,
                             subject=self.subject)
                m.agent = target.name
                moved.append(m.job)
                self._drop_and_rearm(rec, m)
        return moved

    # -- persistence (xend-restart story) --------------------------------
    #
    # xend persisted its domain map in xenstore so a restarted daemon
    # rediscovered the world instead of orphaning every guest. Same
    # split here: durable intent (job records, membership, replication
    # topology) goes into the Store under /cluster; live state (which
    # hosts answer, what their load is) is re-learned by heartbeats.

    def save_state(self, store, prefix: str = "/cluster",
                   subject: str = "system") -> None:
        """Persist membership + job records; one transaction so a
        reader never sees a half-written cluster map."""
        tx = store.transaction(subject=subject)
        tx.rm(prefix)
        for name, h in self.agents.items():
            tx.write(f"{prefix}/agents/{name}",
                     {"host": h.address[0], "port": h.address[1]})
        for name, rec in self.jobs.items():
            tx.write(f"{prefix}/jobs/{name}", {
                "workload": rec.workload,
                "spec": rec.spec,
                "gang": rec.gang,
                "members": [{"agent": m.agent, "job": m.job}
                            for m in rec.members],
                "replica_peers": dict(rec.replica_peers),
                "replica_period_s": rec.replica_period_s,
            })
        tx.commit()

    @classmethod
    def load_state(cls, store, prefix: str = "/cluster",
                   store_subject: str = "system", **kw) -> "Controller":
        """Rebuild a controller from the persisted map. Agents are
        re-dialed CONCURRENTLY (N dead hosts cost one connect timeout,
        not N — the heartbeat lesson); unreachable hosts come up
        present-but-dead and surface through the normal heartbeat
        path, so a restarted daemon is usable even with half the fleet
        down. ``store_subject`` is the XSM label for the store reads;
        the controller's own RPC identity passes through ``**kw``
        (``subject=...``) untouched."""
        ctl = cls(**kw)
        names = store.ls(f"{prefix}/agents", subject=store_subject)
        addrs = {
            name: store.read(f"{prefix}/agents/{name}",
                             subject=store_subject)
            for name in names
        }

        def _dial(name: str) -> None:
            addr = addrs[name]
            try:
                ctl.add_agent(name, (addr["host"], addr["port"]))
            except Exception:  # noqa: BLE001 — host down: mark dead,
                h = AgentHandle(  # heartbeat/recover() handle the rest
                    name,
                    RpcClient((addr["host"], addr["port"]),
                              auth_token=ctl.auth_token,
                              deadline_s=30.0),
                    probe=RpcClient((addr["host"], addr["port"]),
                                    timeout_s=2.0,
                                    auth_token=ctl.auth_token,
                                    deadline_s=2.0),
                    address=(addr["host"], addr["port"]),
                    alive=False, missed=ctl.dead_after_missed)
                ctl.agents[name] = h

        cls._fanout(names, _dial)
        for name in store.ls(f"{prefix}/jobs", subject=store_subject):
            rec = store.read(f"{prefix}/jobs/{name}",
                             subject=store_subject)
            ctl.jobs[name] = JobRecord(
                name=name,
                workload=rec["workload"],
                spec=rec["spec"],
                members=[MemberRef(m["agent"], m["job"])
                         for m in rec["members"]],
                gang=rec.get("gang", False),
                replica_peers=dict(rec.get("replica_peers", {})),
                replica_period_s=rec.get("replica_period_s", 0.5),
            )
        return ctl

    # -- observability ---------------------------------------------------

    def backend_health(self) -> dict[str, dict[str, Any]]:
        """Routing view for the serving gateway (pbs_tpu.gateway): the
        controller's last-OBSERVED liveness, breaker state, and load
        per agent — no RPC here, so the gateway's dispatch loop can
        consult it every tick. The gateway vetoes backends whose names
        match agents that are dead or breaker-open, reusing exactly the
        health state ``place()``/``available_agents()`` rank on.

        Every entry carries its observation time and a ``stale`` flag
        (older than ``health_ttl_ns``, the breaker's half-open window):
        a view nobody has refreshed is NOT truth, and the gateway
        treats stale entries as unknown — no veto, ranked last —
        instead of trusting them."""
        now = self.clock.now_ns()
        return {
            name: {
                "alive": h.alive,
                "breaker": h.breaker,
                "load": int(h.info.get("n_jobs", 0)),
                "observed_ns": h.observed_ns,
                "stale": now - h.observed_ns > self.health_ttl_ns,
                "service_p99_ns": h.service_p99_ns,
            }
            for name, h in self.agents.items()
        }

    def note_backend_service(self, name: str, p99_ns: int) -> None:
        """Backend attribution from the serving tier: a gateway
        publishes the co-named backend's histogram-derived service p99
        (pbs_tpu.obs.spans) so the health view carries a *measured*
        service figure, not just a job-count load proxy. Unknown names
        are ignored — the gateway may front backends the cluster
        controller does not manage."""
        h = self.agents.get(name)
        if h is not None:
            h.service_p99_ns = int(p99_ns)

    # -- admission leasing (the federated gateway tier's authority) ------

    def attach_admission_broker(self, broker) -> None:
        """Install the lease authority for federated admission
        (gateway/federation.py): per-tenant token-bucket levels are
        minted in one global bank and reach a gateway only through a
        lease grant routed here, so a tenant spraying requests across N
        gateways cannot get N× its global rate."""
        self.admission_broker = broker

    def admission_lease(self, tenant: str, gateway: str, want: float,
                        now_ns: int, ttl_ns: int):
        """Grant ``gateway`` up to ``want`` tokens of ``tenant``'s
        global bucket (bounded by the bank's level) under a lease that
        expires at ``now_ns + ttl_ns``. Returns the Lease, or None for
        an unknown tenant."""
        if self.admission_broker is None:
            raise RuntimeError("no admission broker attached")
        return self.admission_broker.grant(tenant, gateway, want,
                                           now_ns, ttl_ns)

    def admission_deposit(self, tenant: str, gateway: str, tokens: float,
                          now_ns: int) -> float:
        """Return a draining gateway's unspent lease tokens to the
        bank (capped at the global burst; the excess is destroyed —
        conservative, never inflationary). Returns the amount the bank
        accepted."""
        if self.admission_broker is None:
            raise RuntimeError("no admission broker attached")
        return self.admission_broker.deposit(tenant, gateway, tokens,
                                             now_ns)

    def cluster_dump(self) -> dict[str, Any]:
        out: dict[str, Any] = {"agents": {}, "jobs": {}}
        for name, h in self.agents.items():
            if not h.alive:
                out["agents"][name] = {"alive": False}
                continue
            try:
                out["agents"][name] = {"alive": True, **h.client.call("dump")}
            except Exception as e:  # noqa: BLE001 — snapshot best-effort
                out["agents"][name] = {"alive": False, "error": str(e)}
        for jname, rec in self.jobs.items():
            out["jobs"][jname] = {
                "workload": rec.workload,
                "gang": rec.gang,
                "members": [{"agent": m.agent, "job": m.job}
                            for m in rec.members],
            }
        return out

    def job_steps(self, name: str) -> dict[str, int]:
        """Per-member retired steps (cluster progress view)."""
        rec = self.jobs[name]
        steps = {}
        for m in rec.members:
            tel = self.agents[m.agent].client.call("telemetry", job=m.job)
            steps[m.job] = sum(c["counters"]["steps_retired"]
                               for c in tel["contexts"])
        return steps

    def close(self) -> None:
        for h in self.agents.values():
            h.client.close()
            h.probe.close()
