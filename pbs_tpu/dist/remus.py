"""Remus over the wire: continuous checkpoint shipping to a peer host.

Reference: Remus (``tools/remus/README:1-4``) keeps a backup host
continuously up to date by repeatedly running the live-migration save
path (``tools/libxc/xc_domain_save.c``) against a *running* domain:
suspend at an epoch boundary, emit the dirty state, resume immediately,
ship the epoch to the backup, and only count it once the backup acks —
the commit handshake that makes failover consistent.

TPU-native re-expression: a job's only state lives at step boundaries
(no mid-step device state), so epoch consistency is free — the session
quiesces the job under the agent's dispatch lock (sleep → record →
wake, microseconds of host work), then ships the save record to the
peer agent *outside* the lock over the ordinary control RPC. The peer
stores the newest acked epoch per job (`push_replica`); the controller's
``recover()`` restores from that replica on host death, so steps,
telemetry counters, and scheduler params survive the failure — the
round-1 gap was exactly that replication never left the local disk.
"""

from __future__ import annotations

import threading
import time
from typing import TYPE_CHECKING

from pbs_tpu.dist.rpc import RpcClient

if TYPE_CHECKING:
    from pbs_tpu.dist.agent import Agent


class RemusSession:
    """One job's replication pump on its source agent.

    Each period: snapshot the job (atomically, under the server dispatch
    lock so no RPC op sees a half-quiesced partition), ship to the peer,
    count the epoch only on ack. Peer loss doesn't kill the session —
    failures are counted and the next tick retries (the RpcClient
    reconnects transparently), matching Remus's behavior when the
    backup link drops: the primary keeps running unprotected.
    """

    def __init__(self, agent: "Agent", job_name: str,
                 peer: tuple[str, int], period_s: float = 0.5,
                 subject: str = "controller",
                 auth_token: str | None = None):
        self.agent = agent
        self.job_name = job_name
        self.peer_addr = (peer[0], int(peer[1]))
        self.period_s = period_s
        self.subject = subject
        # fault_key: logical (source agent + protected job), so each
        # replication channel owns its own deterministic fault stream —
        # a shared default key would interleave consultations across
        # sessions and make seeded chaos traces depend on pump timing.
        # deadline_s: a wedged peer must fail the epoch (failures+=1,
        # next period retries) — not pin the replication thread.
        self.client = RpcClient(self.peer_addr, auth_token=auth_token,
                                fault_key=f"{agent.name}.remus.{job_name}",
                                deadline_s=30.0)
        self.epochs_committed = 0
        self.failures = 0
        self.skipped = 0
        self.last_error: str | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def tick_once(self) -> bool:
        """One epoch: snapshot + ship + ack. Returns True on commit."""
        # Snapshot under the dispatch lock — the same serialization
        # every RPC op gets, so a concurrent `run`/`migrate` op and
        # the quiesce can't interleave (stop-and-copy happens at a
        # quantum boundary because `run` holds the lock mid-round).
        # Time-bounded acquire: a long `run` op (or an op stopping this
        # very session under the lock) must not wedge this thread — a
        # missed epoch just means the previous one stays current.
        if not self.agent.dispatch_lock.acquire(timeout=1.0):
            self.skipped += 1
            return False
        try:
            if self._stop.is_set():
                self.skipped += 1
                return False
            saved = self.agent.snapshot_record(self.job_name)
        except Exception as e:  # noqa: BLE001 — job may be mid-removal
            self.failures += 1
            self.last_error = f"{type(e).__name__}: {e}"
            return False
        finally:
            self.agent.dispatch_lock.release()
        try:
            ack = self.client.call(
                "push_replica", job=self.job_name,
                epoch=self.epochs_committed, saved=saved,
                source=self.agent.name, subject=self.subject,
            )
            if ack.get("stale"):
                # The backup holds a NEWER epoch (ours restarted at 0,
                # or a duplicate was delayed): our push was discarded,
                # so nothing committed — resync past the backup's epoch
                # and let the next tick ship fresh state under it.
                self.epochs_committed = int(ack["epoch"]) + 1
                self.failures += 1
                self.last_error = (
                    f"stale epoch rejected by backup (it holds "
                    f"{ack['epoch']}); resynced")
                return False
            self.epochs_committed += 1  # commit = ack received
            self.last_error = None
            return True
        except Exception as e:  # noqa: BLE001 — protection is best-effort,
            self.failures += 1  # the primary must keep running
            self.last_error = f"{type(e).__name__}: {e}"
            return False

    def start(self) -> "RemusSession":
        def loop() -> None:
            while not self._stop.wait(self.period_s):
                self.tick_once()

        self._thread = threading.Thread(
            target=loop, daemon=True,
            name=f"remus-{self.agent.name}-{self.job_name}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.client.close()

    def status(self) -> dict:
        return {
            "job": self.job_name,
            "peer": list(self.peer_addr),
            "period_s": self.period_s,
            "epochs_committed": self.epochs_committed,
            "failures": self.failures,
            "skipped": self.skipped,
            "last_error": self.last_error,
            "running": self._thread is not None and self._thread.is_alive(),
        }
