"""Weight-only int8 quantization for serving tenants.

No reference analog (pre-LLM artifact); the TPU-native motivation is
the framework's own memory economy: serving tenants are priced by HBM
residency (``runtime/memory.py`` admission accounts, ``sharing.py``
shared weights), and weight-only int8 halves a bf16 tenant's bill
while keeping the KV cache and activations untouched.

Scheme: symmetric per-output-channel scales on every >=2-D weight
(norm vectors stay fp32). A quantized leaf is ``{"q": int8, "s":
fp32}``; the serving forwards dequantize at use via :func:`wload` —
``q.astype(dt) * s`` — which XLA fuses into the consuming matmul's
operand load, so the HBM-resident copy stays int8. Pytree shape is
preserved (stacked layer leaves quantize along the last axis), so
quantized params flow through the same ``lax.scan`` layer stack as
fp params — one forward implementation serves both.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["quantize_weights", "quantized_nbytes", "wload", "embed_rows"]


def _quantize_leaf(w: jax.Array) -> dict:
    """Symmetric per-output-channel int8: scale over the last axis."""
    amax = jnp.max(jnp.abs(w), axis=-2, keepdims=True)  # reduce d_in
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale.astype(jnp.float32)}


def quantize_weights(params: dict) -> dict:
    """Quantize a transformer/MoE param tree (the layout of
    ``models.transformer.init_params`` / ``models.moe``) for the
    *cached/serving* forwards: the embed and head matrices, plus every
    stacked layer matrix (ndim >= 3 — ``(L, d_in, d_out)`` dense,
    ``(L, E, d, f)`` experts). Stacked norm vectors ``(L, d)``, the
    final norm, and the MoE ``router`` pass through — norms because
    per-channel scaling across layers is meaningless, the router
    because routing decisions are disproportionately sensitive to
    weight noise (and it is tiny)."""
    out = dict(params)
    out["embed"] = _quantize_leaf(params["embed"])
    out["head"] = _quantize_leaf(params["head"])
    out["layers"] = {
        k: (_quantize_leaf(v) if v.ndim >= 3 and k != "router" else v)
        for k, v in params["layers"].items()
    }
    return out


def wload(w, dt):
    """Weight access used by the serving forwards: dequantize a
    ``{"q", "s"}`` leaf (int8 stays HBM-resident; the dequant fuses
    into the consumer), or cast a plain array."""
    if isinstance(w, dict):
        return w["q"].astype(dt) * w["s"].astype(dt)
    return w.astype(dt)


def embed_rows(w, tokens, dt):
    """Embedding gather that never dequantizes the whole table:
    gather int8 rows first, then scale by the per-column scales."""
    if isinstance(w, dict):
        return w["q"][tokens].astype(dt) * w["s"][0].astype(dt)
    return w.astype(dt)[tokens]


def quantized_nbytes(params: dict) -> int:
    """Device-resident bytes of a (possibly quantized) param tree —
    what the admission account should charge. Delegates to the same
    accounting the memory manager uses (``runtime.memory.nbytes_of``),
    so the serving bill and the admission bill cannot drift."""
    from pbs_tpu.runtime.memory import nbytes_of

    return nbytes_of(params)
