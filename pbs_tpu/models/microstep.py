"""Gradient-accumulation micro-steps: chunked long steps for latency.

Why this exists (SURVEY.md §7 "hard parts"): a TPU job cannot be
preempted mid-step, so a tenant whose compiled step takes 1 s makes a
100 µs time slice meaningless — the quantum floor is one step. The
reference never has this problem because its hardware preempts by timer
(``xen-4.2.1/xen/common/sched_credit.c:52,1796-1805``: any guest is cut
at the per-domain slice). The TPU answer is *cooperative decomposition*:
split the optimizer step into K compiled micro-batches (each an inner
``lax.scan`` over its own tokens), return to the host between chunks,
and let the executor deschedule at any chunk boundary
(``runtime/executor.py`` micro dispatch + ``Job.micro_per_step``). The
host check between chunks is the "donation/early-exit hook" SURVEY.md
§7 names.

Math contract: K micro-steps over micro-batches b_1..b_K with averaged
accumulated gradients are *exactly* one full-batch step over
concat(b_1..b_K) (equal micro-batch sizes: mean-of-means = global mean,
so averaged grads = full-batch grads; AdamW sees identical inputs).
``tests/test_microstep.py`` asserts parameter-level parity.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from pbs_tpu.models.transformer import (
    TransformerConfig,
    default_optimizer,
    next_token_loss,
)


def make_micro_train_step(
    cfg: TransformerConfig,
    n_micro: int,
    learning_rate: float = 3e-4,
    constrain: Callable = lambda x: x,
    next_batch: Callable[[int], Any] | None = None,
):
    """Returns ``(init_state, micro_step)``.

    - ``init_state(params, next_batch=None) -> state``
    - ``micro_step(state) -> (state, metrics)`` — processes ONE
      micro-batch; every ``n_micro``-th call applies the AdamW update
      and retires the optimizer step.

    ``next_batch(micro_index) -> tokens`` supplies each micro-batch (a
    data-loader hook; tests close over fixed arrays). It lives in the
    *closure*, never in the state pytree: the state carries only arrays
    (params, opt_state, grad accumulator, micro cursor, step) so it
    checkpoints cleanly (np.save leaves); on restore, rebuild
    ``micro_step`` with the same loader and hand it the restored state.

    Pair with ``Job(micro_step_fn=micro_step, micro_per_step=n_micro)``
    so the executor dispatches in chunk units.
    """
    import optax

    if n_micro < 1:
        raise ValueError("n_micro must be >= 1")
    if next_batch is None:
        raise ValueError("next_batch is required (micro-batch supplier)")
    tx = default_optimizer(learning_rate)

    @jax.jit
    def _accum(params, acc, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: next_token_loss(cfg, p, tokens, constrain)
        )(params)
        return loss, jax.tree.map(jnp.add, acc, grads)

    @jax.jit
    def _apply(params, opt_state, acc):
        mean = jax.tree.map(lambda g: g / n_micro, acc)
        updates, opt_state = tx.update(mean, opt_state, params)
        params = optax.apply_updates(params, updates)
        zero = jax.tree.map(jnp.zeros_like, acc)
        return params, opt_state, zero

    def init_state(params):
        return {
            "params": params,
            "opt": tx.init(params),
            "acc": jax.tree.map(jnp.zeros_like, params),
            "micro": 0,
            "step": 0,
        }

    def micro_step(state):
        tokens = next_batch(state["micro"])
        loss, acc = _accum(state["params"], state["acc"], tokens)
        state = dict(state, acc=acc, micro=state["micro"] + 1)
        if state["micro"] >= n_micro:
            params, opt, zero = _apply(state["params"], state["opt"], acc)
            state.update(params=params, opt=opt, acc=zero, micro=0,
                         step=state["step"] + 1)
        ntok = tokens.shape[0] * (tokens.shape[1] - 1)
        return state, {"loss": loss, "tokens": ntok}

    return init_state, micro_step
