"""Continuous batching: slot-based serving engine, TPU-first.

The reference has no serving story at all (SURVEY.md §0); PBS-T's
batch-inference tenant (``make_serve_step``) generates request batches
in lockstep — a late request waits for the whole previous batch. This
module adds the serving engine modern LLM systems use: **continuous
batching** — a fixed pool of decode slots advancing one token per step
for ALL active requests, with new requests admitted into free slots at
step boundaries and finished ones retired immediately.

TPU-first expression of the idea:

- **Static everything**: ``n_slots`` decode lanes, one shared KV slab
  ``(L, n_slots, T, nkv, hd)``, prompts padded to a static bucket.
  Admission/retirement changes DATA (per-slot cursors and masks),
  never shapes — so exactly two XLA programs exist (slot-prefill,
  slot-decode) regardless of traffic.
- **Per-slot cursors**: unlike ``forward_with_cache`` (one scalar
  position for the whole batch), every slot carries its own ``pos``;
  rope tables are gathered per row, cache writes scatter per row, and
  the causal mask compares against each row's own position.
- **Inactive lanes ride along**: an empty slot still computes (masked
  to self-attention on garbage it never emits). Wasted FLOPs on idle
  lanes buy shape stability — the standard TPU trade.
- **Host admission between dispatches**: the engine's ``step()`` is
  a scheduler-quantum-sized unit (one token across slots), so a
  serving Job under the credit scheduler interleaves with training at
  token granularity — the latency story the reference's BOOST class
  exists for.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import OrderedDict, deque
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from pbs_tpu.models.quant import embed_rows, wload
from pbs_tpu.models.generate import _sample
from pbs_tpu.models.transformer import (
    TransformerConfig,
    rms_norm,
    rope_tables,
)


def _rope_rows(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Per-row rope: x (B, S, H, hd); cos/sin (B, S, half)."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    c = cos[:, :, None, :].astype(x.dtype)
    s = sin[:, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


def init_slot_cache(cfg: TransformerConfig, n_slots: int,
                    max_len: int) -> dict:
    shape = (cfg.n_layers, n_slots, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "pos": jnp.zeros((n_slots,), jnp.int32),  # per-slot cursors
    }


def _slot_forward(cfg: TransformerConfig, params: dict, tokens: jax.Array,
                  cache: dict, row_pos: jax.Array,
                  mlp_fn=None) -> tuple[jax.Array, dict]:
    """Forward (B, S) tokens where row b sits at absolute position
    ``row_pos[b]`` (S static; per-row cursors). Writes K/V at
    ``row_pos[b] + s``; row b's query s attends cols <= row_pos[b]+s.
    Returns (logits (B, S, vocab) fp32, updated cache slabs).

    ``mlp_fn(lp, h) -> (y, extra)`` swaps the FFN block — the SAME
    contract as ``generate._forward_with_cache_impl``, so the MoE
    closure serves both paths. ``extra`` is the FFN's auxiliary scalar
    (MoE: drop fraction) SUMMED over layers — callers divide by
    ``cfg.n_layers``, exactly as generate's impl callers do. Caveat the
    MoE caller owns: routing shares expert capacity across every
    co-resident lane of the forward (slots, bucket padding, garbage
    lanes), so engine decode only matches the lockstep path under
    DROPLESS capacity — watch the returned drop telemetry."""
    B, S = tokens.shape
    T = cache["k"].shape[2]
    dt = cfg.dtype
    nh, nkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    group = nh // nkv

    x = embed_rows(params["embed"], tokens, dt)
    cos_full, sin_full = rope_tables(cfg, T)
    # absolute position of every (row, s) element: (B, S)
    abs_pos = row_pos[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    abs_pos = jnp.minimum(abs_pos, T - 1)  # clamp: masked rows only
    cos = cos_full[abs_pos]  # (B, S, half)
    sin = sin_full[abs_pos]

    def body(carry, layer):
        x, extra = carry
        lp, ck, cv = layer  # ck/cv: (B, T, nkv, hd)
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ wload(lp["wq"], dt)).reshape(B, S, nh, hd)
        k = (h @ wload(lp["wk"], dt)).reshape(B, S, nkv, hd)
        v = (h @ wload(lp["wv"], dt)).reshape(B, S, nkv, hd)
        q = _rope_rows(q, cos, sin)
        k = _rope_rows(k, cos, sin)
        # Each row writes S CONTIGUOUS entries at its own cursor: a
        # vmapped dynamic_update_slice, not a scatter — GSPMD
        # partitions DUS on an unsharded axis natively, where the
        # equivalent scatter made tp>2 compiles blow up.
        write = jax.vmap(
            lambda slab, new, p: jax.lax.dynamic_update_slice(
                slab, new, (p, 0, 0)))
        ck = write(ck, k, row_pos)
        cv = write(cv, v, row_pos)
        # attention with per-row causal horizon
        qg = q.reshape(B, S, nkv, group, hd).transpose(0, 2, 3, 1, 4)
        kt = ck.transpose(0, 2, 1, 3)  # (B, nkv, T, hd)
        vt = cv.transpose(0, 2, 1, 3)
        scores = jnp.einsum("bngqh,bnkh->bngqk", qg, kt) / np.sqrt(hd)
        # per-row causal horizon: row b's query s sees cols <= abs_pos
        reach = (jnp.arange(T)[None, None, :]
                 <= abs_pos[:, :, None])  # (B, S, T)
        mask = jnp.broadcast_to(reach[:, None, None, :, :], scores.shape)
        scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(
            scores.astype(jnp.float32), axis=-1).astype(dt)
        attn = jnp.einsum("bngqk,bnkh->bngqh", probs, vt)
        attn = attn.transpose(0, 3, 1, 2, 4).reshape(B, S, nh * hd)
        x = x + attn @ wload(lp["wo"], dt)
        h = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        if mlp_fn is None:
            gate = jax.nn.silu(h @ wload(lp["w1"], dt))
            up = h @ wload(lp["w3"], dt)
            y = (gate * up) @ wload(lp["w2"], dt)
            e = jnp.zeros((), jnp.float32)
        else:
            y, e = mlp_fn(lp, h)
        x = x + y
        return (x, extra + e), (ck, cv)

    zero = jnp.zeros((), jnp.float32)
    (x, extra), (new_k, new_v) = jax.lax.scan(
        body, (x, zero), (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ wload(params["head"], dt)).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v, "pos": cache["pos"]}, extra


def ingest_slot_prompt(cfg: TransformerConfig, params: dict, cache: dict,
                       slot, prompt: jax.Array, plen, mlp_fn=None):
    """The ONE copy of slot-prompt ingestion (trace-safe): gather the
    slot's slabs as a B=1 view, forward the padded prompt from
    position 0, write the slabs back (vmapped-DUS layout — load-bearing
    for tp compiles, see _slot_forward), set the slot cursor. Returns
    ``(last_logits (V,), cache)``; samplers layer on top."""
    sub = {
        "k": jax.lax.dynamic_slice_in_dim(cache["k"], slot, 1, axis=1),
        "v": jax.lax.dynamic_slice_in_dim(cache["v"], slot, 1, axis=1),
        "pos": jnp.zeros((1,), jnp.int32),
    }
    logits, sub, extra = _slot_forward(
        cfg, params, prompt[None, :], sub, jnp.zeros((1,), jnp.int32),
        mlp_fn=mlp_fn)
    cache = dict(cache)
    cache["k"] = jax.lax.dynamic_update_slice_in_dim(
        cache["k"], sub["k"], slot, axis=1)
    cache["v"] = jax.lax.dynamic_update_slice_in_dim(
        cache["v"], sub["v"], slot, axis=1)
    cache["pos"] = cache["pos"].at[slot].set(plen)
    return logits[0, plen - 1], cache, extra


def _shard_serving_params(cfg, params: dict, mesh) -> dict:
    """Place a serving param tree on a tp mesh. One quant-aware
    sharding walk covers all four weight forms (r5 — the former MoE
    and int8 mesh rejections are lifted): dense fp, dense int8, MoE
    fp, MoE int8. MoE trees take the Megatron-attention +
    expert-d_ff serving table; {"q","s"} leaves shard q like the fp
    weight and s with its size-1 reduced axis unsharded."""
    from pbs_tpu.parallel.sharding import (
        param_specs,
        quant_aware_shardings,
    )

    if cfg.n_kv_heads % mesh.shape["tp"]:
        raise ValueError(
            f"n_kv_heads={cfg.n_kv_heads} not divisible by "
            f"tp={mesh.shape['tp']}")
    if isinstance(params.get("layers"), dict) and \
            "router" in params["layers"]:
        from pbs_tpu.parallel.expert import moe_serving_param_specs

        specs = moe_serving_param_specs(cfg)
    else:
        specs = param_specs(cfg)
    return jax.tree.map(
        jax.device_put, params,
        quant_aware_shardings(specs, params, mesh))


def _shard_slot_cache(cache: dict, mesh) -> dict:
    """KV slabs sharded over the kv heads on tp; cursors replicated."""
    import jax.sharding as jsh

    from pbs_tpu.parallel.sharding import slot_cache_kv_sharding

    kv = slot_cache_kv_sharding(mesh)
    rep = jsh.NamedSharding(mesh, jsh.PartitionSpec(None))
    return {
        "k": jax.device_put(cache["k"], kv),
        "v": jax.device_put(cache["v"], kv),
        "pos": jax.device_put(cache["pos"], rep),
    }


@dataclasses.dataclass
class Completion:
    request_id: int
    tokens: list[int]
    prompt_len: int
    steps_waited: int  # engine ticks queued before admission
    ttft_s: float = 0.0  # wall time submit -> first token
    latency_s: float = 0.0  # wall time submit -> completion


class ContinuousBatcher:
    """The slot engine. Host-side control, two compiled programs.

    ``submit`` enqueues; ``step()`` admits into free slots, advances
    one decode token for every active slot, and returns finished
    :class:`Completion`s. All shapes static: ``n_slots`` lanes,
    prompts padded to ``prompt_bucket``, caches sized ``max_len``.
    """

    def __init__(self, cfg: TransformerConfig, params: dict,
                 n_slots: int = 4, prompt_bucket: int = 64,
                 max_len: int | None = None, temperature: float = 0.0,
                 eos_id: int | None = None, seed: int = 0,
                 mesh=None, prefix_cache_size: int = 0,
                 clock=None, mlp_fn=None, submit_hook=None):
        self.cfg = cfg
        # Front-door seam (pbs_tpu.gateway): called as
        # ``submit_hook(rid, prompt_len, max_new)`` on EVERY accepted
        # submit — through the gateway or around it — so a gateway-
        # managed engine can count admission bypasses (the runtime twin
        # of the ``gateway-discipline`` static pass, docs/GATEWAY.md).
        self.submit_hook = submit_hook
        # Latency-stat clock: seconds, monotonic. Injectable so TTFT /
        # completion latencies can be accounted in virtual time —
        # deterministic SLO tests and replayable traces (the xentop
        # analog reads the same stats either way).
        self._now = clock or time.monotonic
        # FFN swap (same seam as generate._forward_with_cache_impl):
        # the MoE family serves through this engine via moe_slot_mlp.
        self.mlp_fn = mlp_fn
        self.n_slots = n_slots
        self.bucket = prompt_bucket
        self.max_len = max_len or cfg.max_seq
        if self.bucket >= self.max_len:
            raise ValueError("prompt_bucket must be < max_len")
        self.temperature = temperature
        self.eos_id = eos_id
        self.mesh = mesh
        cache = init_slot_cache(cfg, n_slots, self.max_len)
        if mesh is not None:
            # Tensor-parallel serving by PLACEMENT (the GSPMD recipe):
            # shard params Megatron-style and the KV slabs over the kv
            # heads; the two jitted programs below are unchanged — XLA
            # propagates the shardings and inserts the collectives.
            if "tp" not in mesh.axis_names:
                raise ValueError(
                    f"serving mesh needs a 'tp' axis; got "
                    f"{mesh.axis_names}")
            params = _shard_serving_params(cfg, params, mesh)
            cache = _shard_slot_cache(cache, mesh)
        self.params = params
        self.cache = cache
        self._key = jax.random.PRNGKey(seed)
        self._ids = itertools.count()
        self.queue: deque = deque()
        # host-side slot table
        self.slot_req: list[int | None] = [None] * n_slots
        self.slot_tokens: list[list[int]] = [[] for _ in range(n_slots)]
        self.slot_remaining = np.zeros(n_slots, np.int32)
        self.slot_prompt_len = np.zeros(n_slots, np.int32)
        self.slot_waited = np.zeros(n_slots, np.int32)
        self.slot_ttft = np.zeros(n_slots, np.float64)
        self.slot_submit_t = np.zeros(n_slots, np.float64)
        self._submitted_step: dict[int, int] = {}
        self._submitted_t: dict[int, float] = {}
        # completed-request latency record (SLO surface): bounded
        self._ttfts: deque = deque(maxlen=1024)
        self._latencies: deque = deque(maxlen=1024)
        self.active = np.zeros(n_slots, bool)
        self.last_tok = np.zeros(n_slots, np.int32)
        self.steps = 0
        self.tokens_emitted = 0
        self.requests_completed = 0
        # This tick's admissions (subclass hook; see _admit).
        self._admitted: list = []
        # FFN auxiliary telemetry (MoE: drop fraction), averaged over
        # forwards — the capacity-starvation signal the lockstep MoE
        # serving path reports, preserved through the engine.
        self._mlp_extra_sum = 0.0
        self._mlp_extra_n = 0
        # Exact-prompt prefix cache (system-prompt reuse): LRU of
        # {prompt bytes -> prompt-window KV + last-position logits}.
        # Entries are DEVICE arrays — storing the lazy slot slice
        # costs bounded HBM instead of a synchronous device-to-host
        # copy on every miss (which would inflate every unique
        # prompt's TTFT). A hit installs the KV into the slot and
        # samples the first token from the cached logits — zero
        # prefill compute. 0 = off.
        # Under a tp serving mesh the cached windows are sliced from
        # the tp-sharded slot cache, so they arrive ALREADY sharded
        # over the kv heads (the sliced dims — layer/slot/seq — are
        # unsharded); _install re-pins the canonical layout with a
        # sharding constraint below, so hits keep the KV on-device and
        # tp-aligned (r5: the former mesh restriction is lifted — tp
        # serving no longer loses the TTFT optimization).
        self.prefix_cache_size = prefix_cache_size
        self._prefix_cache: "OrderedDict[bytes, dict]" = OrderedDict()
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefill_count = 0  # real prefill dispatches (cache misses)

        cfg_ = cfg

        @jax.jit
        def _prefill(params, cache, slot, prompt, plen, key):
            """Write one request's prompt into ``slot`` and sample its
            first token. prompt: (bucket,) padded; plen: real length.
            Also returns the last-position logits (for the prefix
            cache)."""
            last_logits, cache, extra = ingest_slot_prompt(
                cfg_, params, cache, slot, prompt, plen,
                mlp_fn=self.mlp_fn)
            first = _sample(last_logits[None, :], key,
                            self.temperature)[0]
            return first, last_logits, cache, extra

        if mesh is not None:
            from pbs_tpu.parallel.sharding import slot_cache_kv_sharding

            _kv_sharding = slot_cache_kv_sharding(mesh)
        else:
            _kv_sharding = None

        @jax.jit
        def _install(cache, slot, kwin, vwin, plen):
            """Prefix-cache hit: write the cached prompt-window KV
            (L, 1, bucket, nkv, hd) into ``slot``; no forward at all.
            Under a tp mesh the constraint pins the updated slabs back
            to the canonical kv-head sharding (the window arrives
            sharded the same way — the constraint is a no-op reshard
            in the common case, a guard against layout drift always)."""
            cache = dict(cache)
            k = jax.lax.dynamic_update_slice(
                cache["k"], kwin, (0, slot, 0, 0, 0))
            v = jax.lax.dynamic_update_slice(
                cache["v"], vwin, (0, slot, 0, 0, 0))
            if _kv_sharding is not None:
                k = jax.lax.with_sharding_constraint(k, _kv_sharding)
                v = jax.lax.with_sharding_constraint(v, _kv_sharding)
            cache["k"] = k
            cache["v"] = v
            cache["pos"] = cache["pos"].at[slot].set(plen)
            return cache

        @jax.jit
        def _decode(params, cache, last_tok, active, key):
            """One token for every slot; inactive lanes masked."""
            logits, new_cache, extra = _slot_forward(
                cfg_, params, last_tok[:, None], cache, cache["pos"],
                mlp_fn=self.mlp_fn)
            keys = jax.random.split(key, self.n_slots)
            nxt = jax.vmap(
                lambda lg, k: _sample(lg[None, :], k,
                                      self.temperature)[0]
            )(logits[:, 0, :], keys)
            nxt = jnp.where(active, nxt, 0)
            new_cache["pos"] = cache["pos"] + active.astype(jnp.int32)
            return nxt, new_cache, extra

        self._prefill_fn = _prefill
        self._install_fn = _install
        self._decode_fn = _decode
        # Warm both programs NOW: compilation belongs to engine
        # construction, not to the first unlucky request's TTFT — a
        # multi-second jit landing in the SLO percentiles would read
        # as a false violation for the next ~1024 completions.
        wk = jax.random.PRNGKey(0)
        _prefill(self.params, self.cache, 0,
                 jnp.zeros((self.bucket,), jnp.int32), 1, wk)
        if prefix_cache_size:
            _install(self.cache, 0, jnp.zeros(
                (cfg.n_layers, 1, self.bucket, cfg.n_kv_heads,
                 cfg.head_dim), cfg.dtype), jnp.zeros(
                (cfg.n_layers, 1, self.bucket, cfg.n_kv_heads,
                 cfg.head_dim), cfg.dtype), 1)
        _decode(self.params, self.cache,
                jnp.zeros((n_slots,), jnp.int32),
                jnp.zeros((n_slots,), bool), wk)  # results discarded:
        # self.cache is untouched (jit is functional)

    # -- request intake ---------------------------------------------------

    def submit(self, prompt, max_new_tokens: int) -> int:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if not 0 < len(prompt) <= self.bucket:
            raise ValueError(
                f"prompt length {len(prompt)} not in (0, {self.bucket}]")
        if max_new_tokens < 1:
            # prefill always samples one token; a zero-budget request
            # would still emit it and break caller-side accounting
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError("prompt + max_new_tokens exceeds max_len")
        rid = next(self._ids)
        self.queue.append((rid, prompt, int(max_new_tokens)))
        self._submitted_step[rid] = self.steps
        self._submitted_t[rid] = self._now()
        if self.submit_hook is not None:
            self.submit_hook(rid, len(prompt), int(max_new_tokens))
        return rid

    # -- the engine tick --------------------------------------------------

    def _admit(self) -> None:
        # (slot, padded_prompt, plen) of this tick's admissions — the
        # hook subclasses use to mirror work per new tenant (the
        # speculative engine draft-prefills the same prompt).
        # Initialized in __init__ too, so it is safe to read pre-tick.
        self._admitted = []
        for slot in range(self.n_slots):
            if self.active[slot] or not self.queue:
                continue
            rid, prompt, max_new = self.queue.popleft()
            padded = np.zeros(self.bucket, np.int32)
            padded[:len(prompt)] = prompt
            self._admitted.append((slot, padded, len(prompt)))
            self._key, sub = jax.random.split(self._key)
            pkey = prompt.tobytes()
            ent = (self._prefix_cache.get(pkey)
                   if self.prefix_cache_size else None)
            if ent is not None:
                # Hit: install cached KV, sample from cached logits —
                # the prompt forward is skipped entirely.
                self._prefix_cache.move_to_end(pkey)
                self.prefix_hits += 1
                self.cache = self._install_fn(
                    self.cache, slot, ent["k"], ent["v"],
                    int(ent["plen"]))
                first = int(_sample(
                    ent["logits"][None, :], sub, self.temperature)[0])
            else:
                first, last_logits, self.cache, extra = \
                    self._prefill_fn(
                        self.params, self.cache, slot,
                        jnp.asarray(padded), len(prompt), sub)
                first = int(first)
                self._mlp_extra_sum += float(extra) / self.cfg.n_layers
                self._mlp_extra_n += 1
                self.prefill_count += 1
                if self.prefix_cache_size:
                    self.prefix_misses += 1
                    # Device arrays: lazy slices, no host sync here.
                    self._prefix_cache[pkey] = {
                        "k": self.cache["k"][:, slot:slot + 1,
                                             :self.bucket],
                        "v": self.cache["v"][:, slot:slot + 1,
                                             :self.bucket],
                        "logits": last_logits,
                        "plen": len(prompt),
                    }
                    while len(self._prefix_cache) > self.prefix_cache_size:
                        self._prefix_cache.popitem(last=False)
            self.slot_req[slot] = rid
            self.slot_tokens[slot] = [first]
            self.slot_prompt_len[slot] = len(prompt)
            self.slot_remaining[slot] = max_new - 1
            self.slot_waited[slot] = (
                self.steps - self._submitted_step.pop(rid, self.steps))
            now = self._now()
            t_submit = self._submitted_t.pop(rid, now)
            self.slot_submit_t[slot] = t_submit
            self.slot_ttft[slot] = now - t_submit  # first token sampled
            self.active[slot] = True
            self.last_tok[slot] = first
            self.tokens_emitted += 1

    def _retire(self, slot: int) -> Completion:
        lat = self._now() - float(self.slot_submit_t[slot])
        ttft = float(self.slot_ttft[slot])
        comp = Completion(
            request_id=self.slot_req[slot],
            tokens=list(self.slot_tokens[slot]),
            prompt_len=int(self.slot_prompt_len[slot]),
            steps_waited=int(self.slot_waited[slot]),
            ttft_s=ttft,
            latency_s=lat,
        )
        self._ttfts.append(ttft)
        self._latencies.append(lat)
        self.requests_completed += 1
        self.slot_req[slot] = None
        self.slot_tokens[slot] = []
        self.active[slot] = False
        return comp

    def _pre_decode(self) -> tuple[list[Completion], bool]:
        """The tick prologue every engine shares: admit waiting
        requests, retire already-finished slots (prefill-only budgets,
        EOS sampled at admission). Returns (completions, any_active);
        when nothing is active the tick is already accounted."""
        self._admit()
        done: list[Completion] = []
        for slot in range(self.n_slots):
            if self.active[slot] and (
                    self.slot_remaining[slot] <= 0
                    or (self.eos_id is not None
                        and self.last_tok[slot] == self.eos_id)):
                done.append(self._retire(slot))
        if not self.active.any():
            self.steps += 1
            return done, False
        return done, True

    def _emit(self, slot: int, tok: int) -> bool:
        """Book one decoded token into ``slot``; True if the slot just
        finished (budget or EOS) — the ONE copy of the retire
        condition both engines' emit loops use."""
        self.slot_tokens[slot].append(tok)
        self.last_tok[slot] = tok
        self.slot_remaining[slot] -= 1
        self.tokens_emitted += 1
        return bool(
            self.slot_remaining[slot] <= 0
            or (self.eos_id is not None and tok == self.eos_id))

    def step(self) -> list[Completion]:
        """Admit waiting requests, decode one token for every active
        slot, retire finished requests. Returns completions."""
        done, any_active = self._pre_decode()
        if not any_active:
            return done
        self._key, sub = jax.random.split(self._key)
        nxt, self.cache, extra = self._decode_fn(
            self.params, self.cache, jnp.asarray(self.last_tok),
            jnp.asarray(self.active), sub)
        self._mlp_extra_sum += float(extra) / self.cfg.n_layers
        self._mlp_extra_n += 1
        nxt = np.asarray(nxt)
        for slot in range(self.n_slots):
            if not self.active[slot]:
                continue
            if self._emit(slot, int(nxt[slot])):
                done.append(self._retire(slot))
        self.steps += 1
        return done

    def has_work(self) -> bool:
        return bool(self.queue) or bool(self.active.any())

    @staticmethod
    def _pct(values, q: float) -> float:
        # Nearest-rank (utils.stats): the old int(q*n) indexed one rank
        # high — p50 of two samples returned the max, inflating every
        # reported percentile by up to one rank.
        from pbs_tpu.utils.stats import nearest_rank

        return nearest_rank(values, q)

    def stats(self) -> dict:
        """Engine + SLO surface: time-to-first-token and completion
        latency percentiles over the last 1024 completions — the
        numbers a serving tenant's latency SLO is written against
        (and what the feedback policy's BOOST class protects)."""
        return {
            "steps": self.steps,
            "active_slots": int(self.active.sum()),
            "queued": len(self.queue),
            "tokens_emitted": self.tokens_emitted,
            "completed": self.requests_completed,
            "window": len(self._latencies),
            "ttft_p50_s": round(self._pct(self._ttfts, 0.50), 6),
            "ttft_p99_s": round(self._pct(self._ttfts, 0.99), 6),
            "latency_p50_s": round(self._pct(self._latencies, 0.50), 6),
            "latency_p99_s": round(self._pct(self._latencies, 0.99), 6),
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            # FFN auxiliary mean (MoE: drop fraction; 0 for dense) —
            # nonzero under capacity starvation means engine routing
            # has diverged from the dropless/lockstep contract.
            "mlp_extra_mean": round(
                self._mlp_extra_sum / self._mlp_extra_n, 6)
            if self._mlp_extra_n else 0.0,
        }


class SpeculativeBatcher(ContinuousBatcher):
    """Continuous batching WITH speculative decoding: every engine
    tick, a draft model proposes ``k`` tokens per slot and the target
    verifies all ``k+1`` positions in ONE forward; each slot advances
    by its own accepted prefix (the per-row cursors of
    ``speculative.make_per_row_speculative_generate``, which this
    engine shares its slot-cache machinery with).

    Combines the two serving accelerations that matter: continuous
    batching hides admission/retirement latency, speculation
    multiplies decode throughput by the acceptance rate — per
    engine tick a slot emits 1..k+1 tokens instead of exactly 1.
    Greedy-only (``temperature=0``): acceptance is exact token match,
    so outputs are bit-identical to the plain engine's (pinned by
    test). Static shapes throughout: the tick runs a fixed
    (n_slots, k) draft scan + one (n_slots, k+1) verify regardless of
    acceptance; finished/inactive lanes ride along masked.

    Truncation safety: a slot that hits EOS or its token budget
    mid-window retires immediately, so the device cursor (which
    advanced past the truncation) is never decoded from again — the
    next tenant's prefill rewrites it.
    """

    def __init__(self, cfg: TransformerConfig, params: dict,
                 draft_cfg: TransformerConfig, draft_params: dict,
                 k: int = 4, draft_mlp_fn=None, **kw):
        if kw.get("temperature", 0.0) != 0.0:
            raise ValueError(
                "SpeculativeBatcher is greedy-only (temperature=0): "
                "exact-match acceptance is the correctness contract")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if cfg.vocab != draft_cfg.vocab:
            raise ValueError("draft vocab != target vocab")
        super().__init__(cfg, params, **kw)
        self.draft_cfg = draft_cfg
        self.draft_params = draft_params
        self.draft_mlp_fn = draft_mlp_fn
        self.k = k
        self.dcache = init_slot_cache(draft_cfg, self.n_slots,
                                      self.max_len)
        if self.mesh is not None:
            # r5: speculative serving composes with the tp mesh — the
            # parent sharded the target; the draft tree and its slot
            # cache take the same placement. (The prefix cache also
            # composes: a hit installs the TARGET window, and the
            # _admitted hook below draft-prefills hits and misses
            # alike, preserving the pos invariant.)
            self.draft_params = _shard_serving_params(
                draft_cfg, self.draft_params, self.mesh)
            self.dcache = _shard_slot_cache(self.dcache, self.mesh)
        self.spec_proposed = 0
        self.spec_accepted = 0
        # Draft-side FFN telemetry (a starved MoE draft collapses
        # acceptance silently; this is its alarm).
        self._draft_extra_sum = 0.0
        self._draft_extra_n = 0
        dcfg_, cfg_, n_slots = draft_cfg, cfg, self.n_slots

        @jax.jit
        def _draft_prefill(dparams, dcache, slot, prompt, plen):
            """Mirror of the target prefill for the draft cache: the
            shared ingest, logits discarded (the target picks tokens)."""
            _, dcache, extra = ingest_slot_prompt(
                dcfg_, dparams, dcache, slot, prompt, plen,
                mlp_fn=self.draft_mlp_fn)
            return dcache, extra

        kk = self.k

        @jax.jit
        def _spec_decode(params, dparams, tcache, dcache, cur, active):
            """One speculation round across all slots at their own
            cursors. Returns (toks (B, k+1), counts (B,), caches,
            n_proposed, n_accepted)."""
            pos = tcache["pos"]  # (B,), == dcache["pos"] by invariant

            def dstep(c, _):
                tok, dc, dp, de = c
                logits, dc, e = _slot_forward(
                    dcfg_, dparams, tok[:, None], dc, dp,
                    mlp_fn=self.draft_mlp_fn)
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return (nxt, dc, dp + 1, de + e), nxt

            zero_e = jnp.zeros((), jnp.float32)
            (last, dcache, dp, d_extra), props = jax.lax.scan(
                dstep, (cur, dcache, pos, zero_e), None, length=kk)
            t = props.T  # (B, k)
            # Ingest t_k so draft KV reaches pos+k whatever acceptance.
            _, dcache, e2 = _slot_forward(
                dcfg_, dparams, last[:, None], dcache, dp,
                mlp_fn=self.draft_mlp_fn)
            d_extra = d_extra + e2

            x = jnp.concatenate([cur[:, None], t], axis=1)  # (B, k+1)
            logits, tcache, extra = _slot_forward(
                cfg_, params, x, tcache, pos, mlp_fn=self.mlp_fn)
            g = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            from pbs_tpu.models.speculative import greedy_accept_window

            toks, m_row, _bonus = greedy_accept_window(t, g)
            adv = jnp.where(active, m_row + 1, 0)
            tcache = dict(tcache, pos=pos + adv)
            dcache = dict(dcache, pos=pos + adv)
            n_act = jnp.sum(active.astype(jnp.int32))
            return (toks, adv, tcache, dcache, kk * n_act,
                    jnp.sum(jnp.where(active, m_row, 0)), extra, d_extra)

        self._draft_prefill_fn = _draft_prefill
        self._spec_decode_fn = _spec_decode
        # Warm both programs at construction (same SLO reasoning as
        # the parent's warm-up).
        _draft_prefill(self.draft_params, self.dcache, 0,
                       jnp.zeros((self.bucket,), jnp.int32), 1)
        _spec_decode(self.params, self.draft_params, self.cache,
                     self.dcache, jnp.zeros((n_slots,), jnp.int32),
                     jnp.zeros((n_slots,), bool))

    def submit(self, prompt, max_new_tokens: int) -> int:
        # The verify window writes up to k+1 positions past the
        # accepted frontier; reserve that slack in the slab.
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) + max_new_tokens + self.k + 1 > self.max_len:
            raise ValueError(
                "prompt + max_new_tokens + k + 1 exceeds max_len "
                "(speculation needs overshoot room)")
        return super().submit(prompt, max_new_tokens)

    def step(self) -> list[Completion]:
        done, any_active = self._pre_decode()
        for slot, padded, plen in self._admitted:
            self.dcache, d_extra = self._draft_prefill_fn(
                self.draft_params, self.dcache, slot,
                jnp.asarray(padded), plen)
            self._draft_extra_sum += \
                float(d_extra) / self.draft_cfg.n_layers
            self._draft_extra_n += 1
        if not any_active:
            return done
        (toks, counts, self.cache, self.dcache, prop, acc, extra,
         d_extra) = (
            self._spec_decode_fn(
                self.params, self.draft_params, self.cache, self.dcache,
                jnp.asarray(self.last_tok), jnp.asarray(self.active)))
        self._mlp_extra_sum += float(extra) / self.cfg.n_layers
        self._mlp_extra_n += 1
        # kk+1 draft forwards per tick, each a per-layer sum.
        self._draft_extra_sum += (float(d_extra)
                                  / (self.draft_cfg.n_layers
                                     * (self.k + 1)))
        self._draft_extra_n += 1
        toks = np.asarray(toks)
        counts = np.asarray(counts)
        self.spec_proposed += int(prop)
        self.spec_accepted += int(acc)
        for slot in range(self.n_slots):
            if not self.active[slot]:
                continue
            for j in range(int(counts[slot])):
                if self._emit(slot, int(toks[slot, j])):
                    # Truncate mid-window: the device cursor is ahead,
                    # but this slot retires NOW, so it is never decoded
                    # from again.
                    done.append(self._retire(slot))
                    break
        self.steps += 1
        return done

    def stats(self) -> dict:
        st = super().stats()
        st["spec_proposed"] = self.spec_proposed
        st["spec_accepted"] = self.spec_accepted
        st["spec_acceptance"] = round(
            self.spec_accepted / self.spec_proposed, 4) \
            if self.spec_proposed else 0.0
        st["draft_mlp_extra_mean"] = round(
            self._draft_extra_sum / self._draft_extra_n, 6) \
            if self._draft_extra_n else 0.0
        return st


def make_continuous_serve_step(engine: ContinuousBatcher,
                               next_requests=None):
    """Job-shaped wrapper: one engine tick per step (one token across
    slots — a quantum-sized unit, so the credit scheduler interleaves
    serving with training at token granularity). ``next_requests(step)``
    optionally feeds new (prompt, max_new) pairs each tick. The
    ``tokens`` metric is the tick's DELTA of the engine's emitted
    counter, so the TOKENS ledger slot is exact goodput."""

    def serve_step(state):
        step = int(state["step"])
        if next_requests is not None:
            for prompt, max_new in next_requests(step):
                engine.submit(prompt, max_new)
        before = engine.tokens_emitted
        done = engine.step()
        state = {"step": step + 1,
                 "completed": state["completed"] + len(done)}
        return state, {"tokens": engine.tokens_emitted - before}

    return serve_step
